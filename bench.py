"""Headline benchmark — prints ONE JSON line for the driver.

Metric: flagship-transformer training throughput (tokens/s) on the local
accelerator, single chip.

vs_baseline is the GPU-parity ratio from BASELINE.json's north star
("GPU-parity throughput ... with num_gpus=0"): achieved model FLOP/s divided
by an A100's effective training FLOP/s on the same model (312 TFLOP/s bf16
peak × 40% MFU = 125 TFLOP/s — the standard well-tuned-GPU operating
point). vs_baseline >= 1.0 means one TPU chip matches/beats one A100.

Matrix mode (ISSUE 10): ``--sharding dp|fsdp|tp|pp`` benchmarks ONE
parallelism strategy on the same model family through the GSPMD trainer
path (jax_utils.setup_sharded_training / one-jit train step), emitting
the SAME JSON schema with ``detail.sharding`` + ``detail.factorization``
so the driver's comparisons stay schema-stable across modes.

Overlap mode (ISSUE 11): ``--overlap on|off`` runs the paired
gradient-sync microbench on a real 2-worker ring gang — ``off`` is the
monolithic blocking allreduce, ``on`` the bucketed async sync fenced
after backward-sized compute — emitting ``detail.comm_exposed_s`` /
``detail.collective_s`` plus the interleaved-schedule bubble fraction
in the same envelope.
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time


def _emit(tokens_per_s: float, params: int, detail: dict) -> None:
    """Shared JSON emitter — the two modes report identical schemas."""
    achieved_flops = 6.0 * params * tokens_per_s     # fwd+bwd rule of thumb
    a100_effective = 312e12 * 0.40                   # GPU-parity yardstick
    import jax

    device_kind = jax.devices()[0].device_kind
    peaks = {
        "TPU v4": 275e12, "TPU v5 lite": 197e12, "TPU v5e": 197e12,
        "TPU v5p": 459e12, "TPU v6 lite": 918e12,
    }
    peak = next((v for k, v in peaks.items() if device_kind.startswith(k)), None)
    # Matrix mode spans len(jax.devices()) chips; peak scales with them.
    n_dev = detail.get("devices", 1)
    mfu = round(achieved_flops / (peak * n_dev), 4) if peak else None
    print(
        json.dumps(
            {
                "metric": "transformer_train_tokens_per_s_per_chip",
                "value": round(tokens_per_s / n_dev, 1),
                "unit": "tokens/s",
                "vs_baseline": round(achieved_flops / a100_effective / n_dev, 4),
                "detail": {
                    "backend": jax.default_backend(),
                    "device_kind": device_kind,
                    "params": params,
                    "achieved_tflops": round(achieved_flops / 1e12, 2),
                    "mfu": mfu,
                    **detail,
                },
            }
        )
    )


def _phase_breakdown(loss_f, optimizer, params, opt_state, batch,
                     reps: int = 3):
    """Out-of-band fwd/bwd/opt split (ISSUE 20 satellite): times each
    sub-phase with its own jit AFTER the headline window closes, so the
    measured metric is untouched. Mirrors the trainer's vjp-through-jit
    split (train/jax_utils.py). Returns per-step ``{"fwd_s", "bwd_s",
    "opt_s"}`` or None when the split path fails."""
    import jax
    import optax

    try:
        fwd_fn = jax.jit(lambda p, b: jax.vjp(loss_f, p, b))
        bwd_fn = jax.jit(lambda vjp_fn, ct: vjp_fn(ct)[0])

        def _opt(p, o, g):
            updates, new_o = optimizer.update(g, o, p)
            return optax.apply_updates(p, updates), new_o

        opt_fn = jax.jit(_opt)
        loss, vjp_fn = fwd_fn(params, batch)
        grads = bwd_fn(vjp_fn, jax.numpy.ones_like(loss))
        jax.block_until_ready(opt_fn(params, opt_state, grads))
        fwd = bwd = opt = 0.0
        for _ in range(reps):
            t0 = time.perf_counter()
            loss, vjp_fn = fwd_fn(params, batch)
            jax.block_until_ready(loss)
            t1 = time.perf_counter()
            grads = bwd_fn(vjp_fn, jax.numpy.ones_like(loss))
            jax.block_until_ready(grads)
            t2 = time.perf_counter()
            jax.block_until_ready(opt_fn(params, opt_state, grads))
            t3 = time.perf_counter()
            fwd += t1 - t0
            bwd += t2 - t1
            opt += t3 - t2
        return {
            "fwd_s": round(fwd / reps, 6),
            "bwd_s": round(bwd / reps, 6),
            "opt_s": round(opt / reps, 6),
        }
    except Exception:  # rtlint: disable=swallowed-exception - phase split is best-effort garnish; the headline MFU numbers stand without it
        return None


def sharded_main(mode: str) -> None:
    """--sharding matrix entry: train the bench transformer through the
    GSPMD path under ONE strategy and report the same schema."""
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.transformer import (
        TransformerConfig, init_params, loss_fn, num_params,
        param_logical_dims, partition_stages, stage_forward, logits_loss,
    )
    from ray_tpu.parallel.mesh import MeshSpec
    from ray_tpu.train import jax_utils

    backend = jax.default_backend()
    on_accel = backend in ("tpu", "gpu")
    n_dev = len(jax.devices())
    if on_accel:
        config = TransformerConfig(
            vocab_size=8192, dim=4096, n_layers=4, n_heads=32, n_kv_heads=32,
            hidden_dim=16384, max_seq=1024, dtype=jnp.bfloat16,
        )
        batch, steps = 4 * n_dev if mode in ("dp", "fsdp") else 16, 10
    else:  # CPU matrix smoke: dims divisible by every axis size we use
        config = TransformerConfig(
            vocab_size=512, dim=128, n_layers=4, n_heads=8, n_kv_heads=8,
            hidden_dim=256, max_seq=128, dtype=jnp.float32,
        )
        batch, steps = n_dev, 2

    optimizer = optax.adamw(3e-4)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, config.max_seq + 1), 0,
        config.vocab_size,
    )

    def batch_loss(params, tok):
        return loss_fn(params, tok[:, :-1], tok[:, 1:], config)

    if mode == "pp":
        tokens_per_s, p, extra = _bench_pp(
            config, optimizer, tokens, steps,
            init_params, partition_stages, stage_forward, logits_loss,
        )
    else:
        axes = {mode: n_dev}
        mesh = MeshSpec(axes).build(jax.devices())
        setup = jax_utils.setup_sharded_training(
            lambda: init_params(config, jax.random.PRNGKey(0)),
            optimizer,
            mesh=mesh,
            logical_dims=param_logical_dims(config),
        )
        step_fn = jax_utils.build_sharded_train_step(
            batch_loss, optimizer, setup
        )
        tokens_sh = setup.shard_batch(tokens)
        params, opt_state = setup.params, setup.opt_state
        params, opt_state, loss = step_fn(params, opt_state, tokens_sh)
        first_loss = float(loss)
        start = time.perf_counter()
        for _ in range(steps):
            params, opt_state, loss = step_fn(params, opt_state, tokens_sh)
        loss_value = float(loss)
        elapsed = time.perf_counter() - start
        if not (loss_value < first_loss):
            print(
                f"BENCH SANITY FAILED: loss did not decrease "
                f"({first_loss} -> {loss_value})",
                file=sys.stderr,
            )
            raise SystemExit(1)
        tokens_per_s = batch * config.max_seq * steps / elapsed
        p = num_params(params)
        extra = {
            "loss": loss_value,
            "factorization": setup.factorization,
        }
        phases = _phase_breakdown(
            batch_loss, optimizer, params, opt_state, tokens_sh
        )
        if phases:
            extra["phases"] = phases
    _emit(
        tokens_per_s, p,
        {"sharding": mode, "devices": n_dev, **extra},
    )


def _bench_pp(config, optimizer, tokens, steps, init_params,
              partition_stages, stage_forward, logits_loss):
    """Single-process INTERLEAVED pipeline (S=2 ranks x v=2 chunks, M=8
    microbatches): same per-chunk math the MPMD stage runner executes,
    here in topological order (no wire), so the matrix row measures the
    staged computation's throughput. The interleaved schedules the MPMD
    runner would follow are validated inline; the bubble fraction the
    row reports is the interleaved (S−1)/(v·M+S−1)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.parallel.pipeline import (
        bubble_fraction, schedule_interleaved_1f1b, validate_schedule,
    )

    num_stages, microbatches, virtual = 2, 8, 2
    num_chunks = num_stages * virtual
    # The op streams the two MPMD stage ranks would run for this shape —
    # deadlock/coverage-check them before spending compute on the row.
    validate_schedule(
        [
            schedule_interleaved_1f1b(num_stages, microbatches, r, virtual)
            for r in range(num_stages)
        ],
        num_virtual=virtual,
    )
    params = init_params(config, jax.random.PRNGKey(0))
    chunks = partition_stages(params, config, num_chunks)
    opt_states = [optimizer.init(c) for c in chunks]

    def _mid_fwd(i):
        def f(p, x):
            return stage_forward(p, x, config, first=(i == 0), last=False)
        return f

    def _mid_bwd(i):
        fwd = _mid_fwd(i)

        def b(p, x, ct):
            _, vjp_fn = jax.vjp(fwd, p, x)
            gp, gx = vjp_fn(ct)
            # chunk 0 eats int tokens: no usable input cotangent.
            return gp if i == 0 else (gp, gx)
        return b

    fwds = [jax.jit(_mid_fwd(i)) for i in range(num_chunks - 1)]
    bwds = [jax.jit(_mid_bwd(i)) for i in range(num_chunks - 1)]

    def last_loss(p, a, targets):
        return logits_loss(
            stage_forward(p, a, config, first=False, last=True), targets
        )

    grad_last = jax.jit(jax.value_and_grad(last_loss, argnums=(0, 1)))

    def apply(p, o, g):
        updates, new_o = optimizer.update(g, o, p)
        return jax.tree.map(
            lambda w, u: w + u.astype(w.dtype), p, updates
        ), new_o

    apply = jax.jit(apply)
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    mb = inputs.shape[0] // microbatches

    # Per-phase accumulator (ISSUE 20 satellite): the staged loop already
    # runs fwd/bwd/opt as separate jits, so attribution is direct timing
    # around serial sections — no extra syncs beyond the data
    # dependencies the schedule enforces anyway.
    phase_acc = {"fwd": 0.0, "bwd": 0.0, "opt": 0.0}

    def one_step():
        g_acc = [None] * num_chunks
        losses = []

        def acc(i, g):
            g_acc[i] = g if g_acc[i] is None else jax.tree.map(
                jnp.add, g_acc[i], g
            )

        for m in range(microbatches):
            x = inputs[m * mb:(m + 1) * mb]
            y = targets[m * mb:(m + 1) * mb]
            acts, a = [], x
            t0 = time.perf_counter()
            for i in range(num_chunks - 1):
                acts.append(a)
                a = fwds[i](chunks[i], a)
            jax.block_until_ready(a)
            t1 = time.perf_counter()
            loss, (g_last, da) = grad_last(chunks[-1], a, y)
            acc(num_chunks - 1, g_last)
            for i in reversed(range(1, num_chunks - 1)):
                gp, da = bwds[i](chunks[i], acts[i], da)
                acc(i, gp)
            acc(0, bwds[0](chunks[0], acts[0], da))
            jax.block_until_ready(g_acc[0])
            t2 = time.perf_counter()
            phase_acc["fwd"] += t1 - t0
            phase_acc["bwd"] += t2 - t1
            losses.append(loss)
        t3 = time.perf_counter()
        for i in range(num_chunks):
            g = jax.tree.map(lambda v: v / microbatches, g_acc[i])
            chunks[i], opt_states[i] = apply(chunks[i], opt_states[i], g)
        jax.block_until_ready(chunks)
        phase_acc["opt"] += time.perf_counter() - t3
        return float(jnp.mean(jnp.stack(losses)))

    first_loss = one_step()  # warmup/compile
    phase_acc.update(fwd=0.0, bwd=0.0, opt=0.0)  # drop the compile step
    start = time.perf_counter()
    for _ in range(steps):
        loss_value = one_step()
    elapsed = time.perf_counter() - start
    if not (loss_value < first_loss):
        print(
            f"BENCH SANITY FAILED: loss did not decrease "
            f"({first_loss} -> {loss_value})",
            file=sys.stderr,
        )
        raise SystemExit(1)
    p = sum(
        int(jnp.size(l)) for s in chunks for l in jax.tree.leaves(s)
    )
    tokens_per_s = inputs.shape[0] * inputs.shape[1] * steps / elapsed
    bubble = bubble_fraction(num_stages, microbatches, virtual)
    return tokens_per_s, p, {
        "loss": loss_value,
        "factorization": {"dp": 1, "fsdp": 1, "tp": 1, "pp": num_stages},
        "microbatches": microbatches,
        "virtual_stages": virtual,
        "schedule_bubble_fraction": round(bubble, 4),
        "phases": {
            "fwd_s": round(phase_acc["fwd"] / steps, 6),
            "bwd_s": round(phase_acc["bwd"] / steps, 6),
            "opt_s": round(phase_acc["opt"] / steps, 6),
            "pp_bubble_frac": round(bubble, 4),
        },
    }


def _overlap_worker(ctx, steps: int, overlap: bool, bucket_bytes: int):
    """Gang-member body for --overlap: paired gradient-sync microbench
    plus a short deterministic SGD run whose loss trajectory must be
    IDENTICAL across modes (2-rank ring sums are two-operand adds, so
    bucketed and monolithic reductions are bitwise equal)."""
    import time

    import jax
    import numpy as np

    from ray_tpu.train import jax_utils
    from ray_tpu.util.collective import bucketing

    coll = ctx.collective()
    group_name = ctx.group_name

    # Synthetic grad pytree: mixed shapes (matrix/vector/scalar leaves)
    # so bucket boundaries never align with leaf boundaries. ~14MB.
    rng = np.random.default_rng(100 + ctx.rank)
    grads = {
        "emb": rng.standard_normal((1024, 512)).astype(np.float32),
        "layers": [
            {
                "w": rng.standard_normal((512, 512)).astype(np.float32),
                "b": rng.standard_normal(512).astype(np.float32),
            }
            for _ in range(10)
        ],
        "head": rng.standard_normal((512, 1024)).astype(np.float32),
        "scale": np.float32(0.5),
    }
    leaves = [np.asarray(l) for l in jax.tree.leaves(grads)]
    nbytes = sum(4 * bucketing.leaf_size(l) for l in leaves)
    n_buckets = len(bucketing.partition_buckets(leaves, bucket_bytes))

    # Warm (jit traces, mailboxes), then calibrate: one blocking sync
    # measures the comm time a backward pass would have to hide.
    jax_utils.sync_gradients_sharded([grads], group_name, overlap=False)
    coll.barrier()
    t0 = time.perf_counter()
    jax_utils.sync_gradients_sharded([grads], group_name, overlap=False)
    comm_ref = time.perf_counter() - t0
    coll.barrier()

    spin = rng.standard_normal((384, 384)).astype(np.float32)
    wall = exposed = collective = float("inf")
    for _ in range(steps):
        t0 = time.perf_counter()
        if overlap:
            handle = jax_utils.begin_gradient_sync(
                [grads], group_name, bucket_bytes=bucket_bytes
            )
            # Stand-in for the rest of backward: BLAS matmuls release
            # the GIL (like real device compute), sized to the
            # calibrated comm time so a working overlap fully hides it.
            acc = spin
            while time.perf_counter() - t0 < 1.5 * comm_ref:
                acc = (acc @ spin) / 384.0  # rescale: keep finite
            handle.result()
            step_exposed = handle.stats["comm_exposed_s"]
            step_collective = handle.stats["collective_s"]
        else:
            jax_utils.sync_gradients_sharded(
                [grads], group_name, overlap=False
            )
            # Blocking path: every comm second is exposed to the step.
            step_exposed = step_collective = time.perf_counter() - t0
        wall = min(wall, time.perf_counter() - t0)
        exposed = min(exposed, step_exposed)
        collective = min(collective, step_collective)
        coll.barrier()

    # Parity run: 2-rank data-parallel SGD on a linear model whose
    # params span two leaves; tiny bucket_bytes forces multi-bucket
    # syncs on the overlap path.
    prng = np.random.default_rng(7)
    true_w = prng.standard_normal(24).astype(np.float32)
    x = prng.standard_normal((96, 24)).astype(np.float32)
    y = x @ true_w
    xs = x[ctx.rank::ctx.world_size]
    ys = y[ctx.rank::ctx.world_size]
    w = {"a": np.zeros(16, np.float32), "b": np.zeros(8, np.float32)}
    traj = []
    for _ in range(12):
        w_full = np.concatenate([w["a"], w["b"]])
        err = xs @ w_full - ys
        g_full = ((2.0 / len(xs)) * (xs.T @ err)).astype(np.float32)
        g = {"a": g_full[:16], "b": g_full[16:]}
        if overlap:
            g = jax_utils.begin_gradient_sync(
                [g], group_name, bucket_bytes=48
            ).result()
        else:
            g = jax_utils.sync_gradients_sharded(
                [g], group_name, overlap=False
            )
        w = {k: w[k] - 0.2 * np.asarray(g[k]) for k in w}
        traj.append(
            float(
                np.mean((x @ np.concatenate([w["a"], w["b"]]) - y) ** 2)
            )
        )
    return {
        "wall_s": wall,
        "comm_exposed_s": exposed,
        "collective_s": collective,
        "comm_ref_s": comm_ref,
        "grad_bytes": int(nbytes),
        "buckets": n_buckets,
        "loss_trajectory": traj,
    }


def overlap_main(mode: str) -> None:
    """--overlap on|off: the paired half of the BENCH_r06 comparison.

    Forms a REAL 2-worker ring gang (the DCN-tier CPU twin) and times
    one gradient sync per step: ``off`` is the monolithic blocking
    allreduce (all comm exposed); ``on`` launches the bucketed async
    sync and fences after backward-sized compute, so ``comm_exposed_s``
    is only the fence-blocked tail. Emits the shared JSON envelope;
    ``vs_baseline`` is the fraction of collective time HIDDEN from the
    step (0 for the blocking path, →1 when overlap works)."""
    import ray_tpu
    from ray_tpu.parallel.pipeline import (
        bubble_fraction, schedule_interleaved_1f1b, validate_schedule,
    )
    from ray_tpu.util.collective.bucketing import DEFAULT_BUCKET_BYTES
    from ray_tpu.util.gang import WorkerGang

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    overlap = mode == "on"
    bucket_bytes = 2 << 20  # ~7 buckets over the ~14MB synthetic tree
    # The interleaved schedules this PR ships ride the same release
    # gate: deadlock/coverage-validate the acceptance grid inline.
    for s in (2, 4):
        for m in (4, 8):
            for v in (1, 2):
                validate_schedule(
                    [
                        schedule_interleaved_1f1b(s, m, r, v)
                        for r in range(s)
                    ],
                    num_virtual=v,
                )
    ray_tpu.init(num_cpus=8)
    try:
        gang = WorkerGang(2, backend="ring")
        try:
            per_rank = gang.run(
                _overlap_worker, timeout=600,
                steps=5, overlap=overlap, bucket_bytes=bucket_bytes,
            )
        finally:
            gang.shutdown()
    finally:
        ray_tpu.shutdown()

    # The sync is collective: the step waits on the slowest rank.
    slow = max(per_rank, key=lambda r: r["comm_exposed_s"])
    exposed, coll_s = slow["comm_exposed_s"], slow["collective_s"]
    hidden = max(0.0, 1.0 - exposed / coll_s) if coll_s > 0 else 0.0
    print(
        json.dumps(
            {
                "metric": "gradient_sync_effective_bytes_per_s",
                "value": round(slow["grad_bytes"] / slow["wall_s"], 1),
                "unit": "bytes/s",
                "vs_baseline": round(hidden, 4),
                "detail": {
                    "overlap": mode,
                    "world_size": 2,
                    "grad_bytes": slow["grad_bytes"],
                    "bucket_bytes": bucket_bytes,
                    "default_bucket_bytes": DEFAULT_BUCKET_BYTES,
                    "buckets": slow["buckets"],
                    "wall_s": round(slow["wall_s"], 6),
                    "comm_exposed_s": round(exposed, 6),
                    "collective_s": round(coll_s, 6),
                    "comm_ref_s": round(slow["comm_ref_s"], 6),
                    "loss_trajectory": per_rank[0]["loss_trajectory"],
                    "interleaved_valid": 1,
                    "schedule_bubble_fraction": round(
                        bubble_fraction(2, 8, 2), 4
                    ),
                    "phases": {
                        "comm_exposed_s": round(exposed, 6),
                        "collective_s": round(coll_s, 6),
                    },
                },
            }
        )
    )


def main() -> None:
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.transformer import (
        TransformerConfig, init_params, loss_fn, num_params,
    )

    backend = jax.default_backend()
    on_accel = backend in ("tpu", "gpu")
    if on_accel:
        # Shape chosen by an on-chip sweep (round 3): wide MXU-saturating
        # matmuls (dim 4096, hidden 16384 — both multiples of the 128-lane
        # MXU tile), batch 12 x seq 1024 tokens/step (the largest batch
        # that stays HBM-resident — 13/14 regress ~7%, 16 OOMs), bf16
        # weights, NO remat (f32 elementwise intermediates are
        # micro-checkpointed in models/transformer.py). Measured
        # 142 TFLOP/s on v5e (72% MFU).
        config = TransformerConfig(
            vocab_size=8192, dim=4096, n_layers=3, n_heads=32, n_kv_heads=32,
            hidden_dim=16384, max_seq=1024, dtype=jnp.bfloat16,
        )
        batch, steps = 12, 10
    else:  # CPU smoke fallback so the bench never crashes the driver
        config = TransformerConfig.tiny()
        batch, steps = 2, 2

    params = init_params(config, jax.random.PRNGKey(0))
    optimizer = optax.adamw(3e-4)
    opt_state = jax.jit(optimizer.init)(params)
    # seq+1 tokens so the shifted inputs keep a block-aligned length.
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, config.max_seq + 1), 0, config.vocab_size
    )

    # donate params+opt_state: in-place updates halve optimizer-state HBM
    # traffic and free the memory for activations (VERDICT r2 ask 1a).
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, tokens):
        # Next-token LM objective (shifted targets).
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        loss, grads = jax.value_and_grad(loss_fn)(params, inputs, targets, config)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    # warmup/compile. float() forces a device->host read — on remote-attached
    # chips block_until_ready alone does not guarantee execution finished.
    params, opt_state, loss = train_step(params, opt_state, tokens)
    first_loss = float(loss)
    start = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = train_step(params, opt_state, tokens)
    loss_value = float(loss)  # chained params => all steps must complete
    elapsed = time.perf_counter() - start
    # Loss sanity: repeated steps on a fixed batch must strictly improve —
    # the throughput number provably comes from real, chained optimizer
    # steps (a broken/no-op step would leave the loss flat).
    if not (loss_value < first_loss):
        print(
            f"BENCH SANITY FAILED: loss did not decrease "
            f"({first_loss} -> {loss_value})",
            file=sys.stderr,
        )
        raise SystemExit(1)

    tokens_per_step = batch * config.max_seq
    tokens_per_s = tokens_per_step * steps / elapsed
    p = num_params(params)
    achieved_flops = 6.0 * p * tokens_per_s          # fwd+bwd rule of thumb
    a100_effective = 312e12 * 0.40                   # GPU-parity yardstick
    vs_baseline = achieved_flops / a100_effective

    # Peak bf16 FLOP/s per chip kind, for MFU attribution in the detail.
    device_kind = jax.devices()[0].device_kind
    peaks = {
        "TPU v4": 275e12, "TPU v5 lite": 197e12, "TPU v5e": 197e12,
        "TPU v5p": 459e12, "TPU v6 lite": 918e12,
    }
    peak = next((v for k, v in peaks.items() if device_kind.startswith(k)), None)
    mfu = round(achieved_flops / peak, 4) if peak else None

    # fwd/bwd/opt split measured AFTER the headline window (own jits),
    # so the tokens/s number above is exactly what it always was.
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    phases = _phase_breakdown(
        lambda prm, b: loss_fn(prm, b[0], b[1], config),
        optimizer, params, opt_state, (inputs, targets),
    )

    print(
        json.dumps(
            {
                "metric": "transformer_train_tokens_per_s_per_chip",
                "value": round(tokens_per_s, 1),
                "unit": "tokens/s",
                "vs_baseline": round(vs_baseline, 4),
                "detail": {
                    "backend": backend,
                    "device_kind": device_kind,
                    "params": p,
                    "achieved_tflops": round(achieved_flops / 1e12, 2),
                    "mfu": mfu,
                    "loss": loss_value,
                    **({"phases": phases} if phases else {}),
                },
            }
        )
    )


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--sharding", choices=("dp", "fsdp", "tp", "pp"), default=None,
        help="matrix mode: bench ONE parallelism strategy via the GSPMD "
        "trainer path instead of the single-chip headline",
    )
    parser.add_argument(
        "--overlap", choices=("on", "off"), default=None,
        help="paired gradient-sync microbench on a real 2-worker ring "
        "gang: off = monolithic blocking sync, on = bucketed async sync "
        "overlapped with backward-sized compute",
    )
    cli = parser.parse_args()
    if cli.sharding and "xla_force_host_platform_device_count" not in (
        os.environ.get("XLA_FLAGS", "")
    ) and os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # CPU twin: the matrix needs >1 device to shard over.
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
    try:
        if cli.overlap:
            overlap_main(cli.overlap)
        elif cli.sharding:
            sharded_main(cli.sharding)
        else:
            main()
    except Exception as exc:  # never crash the driver: report the failure
        print(
            json.dumps(
                {
                    "metric": "transformer_train_tokens_per_s_per_chip",
                    "value": 0.0,
                    "unit": "tokens/s",
                    "vs_baseline": 0.0,
                    "detail": {"error": f"{type(exc).__name__}: {exc}"[:500]},
                }
            )
        )
        sys.exit(0)

"""Headline benchmark — prints ONE JSON line for the driver.

Metric: flagship-transformer training throughput (tokens/s) on the local
accelerator, single chip.

vs_baseline is the GPU-parity ratio from BASELINE.json's north star
("GPU-parity throughput ... with num_gpus=0"): achieved model FLOP/s divided
by an A100's effective training FLOP/s on the same model (312 TFLOP/s bf16
peak × 40% MFU = 125 TFLOP/s — the standard well-tuned-GPU operating
point). vs_baseline >= 1.0 means one TPU chip matches/beats one A100.

Matrix mode (ISSUE 10): ``--sharding dp|fsdp|tp|pp`` benchmarks ONE
parallelism strategy on the same model family through the GSPMD trainer
path (jax_utils.setup_sharded_training / one-jit train step), emitting
the SAME JSON schema with ``detail.sharding`` + ``detail.factorization``
so the driver's comparisons stay schema-stable across modes.
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time


def _emit(tokens_per_s: float, params: int, detail: dict) -> None:
    """Shared JSON emitter — the two modes report identical schemas."""
    achieved_flops = 6.0 * params * tokens_per_s     # fwd+bwd rule of thumb
    a100_effective = 312e12 * 0.40                   # GPU-parity yardstick
    import jax

    device_kind = jax.devices()[0].device_kind
    peaks = {
        "TPU v4": 275e12, "TPU v5 lite": 197e12, "TPU v5e": 197e12,
        "TPU v5p": 459e12, "TPU v6 lite": 918e12,
    }
    peak = next((v for k, v in peaks.items() if device_kind.startswith(k)), None)
    # Matrix mode spans len(jax.devices()) chips; peak scales with them.
    n_dev = detail.get("devices", 1)
    mfu = round(achieved_flops / (peak * n_dev), 4) if peak else None
    print(
        json.dumps(
            {
                "metric": "transformer_train_tokens_per_s_per_chip",
                "value": round(tokens_per_s / n_dev, 1),
                "unit": "tokens/s",
                "vs_baseline": round(achieved_flops / a100_effective / n_dev, 4),
                "detail": {
                    "backend": jax.default_backend(),
                    "device_kind": device_kind,
                    "params": params,
                    "achieved_tflops": round(achieved_flops / 1e12, 2),
                    "mfu": mfu,
                    **detail,
                },
            }
        )
    )


def sharded_main(mode: str) -> None:
    """--sharding matrix entry: train the bench transformer through the
    GSPMD path under ONE strategy and report the same schema."""
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.transformer import (
        TransformerConfig, init_params, loss_fn, num_params,
        param_logical_dims, partition_stages, stage_forward, logits_loss,
    )
    from ray_tpu.parallel.mesh import MeshSpec
    from ray_tpu.train import jax_utils

    backend = jax.default_backend()
    on_accel = backend in ("tpu", "gpu")
    n_dev = len(jax.devices())
    if on_accel:
        config = TransformerConfig(
            vocab_size=8192, dim=4096, n_layers=4, n_heads=32, n_kv_heads=32,
            hidden_dim=16384, max_seq=1024, dtype=jnp.bfloat16,
        )
        batch, steps = 4 * n_dev if mode in ("dp", "fsdp") else 16, 10
    else:  # CPU matrix smoke: dims divisible by every axis size we use
        config = TransformerConfig(
            vocab_size=512, dim=128, n_layers=4, n_heads=8, n_kv_heads=8,
            hidden_dim=256, max_seq=128, dtype=jnp.float32,
        )
        batch, steps = n_dev, 2

    optimizer = optax.adamw(3e-4)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, config.max_seq + 1), 0,
        config.vocab_size,
    )

    def batch_loss(params, tok):
        return loss_fn(params, tok[:, :-1], tok[:, 1:], config)

    if mode == "pp":
        tokens_per_s, p, extra = _bench_pp(
            config, optimizer, tokens, steps,
            init_params, partition_stages, stage_forward, logits_loss,
        )
    else:
        axes = {mode: n_dev}
        mesh = MeshSpec(axes).build(jax.devices())
        setup = jax_utils.setup_sharded_training(
            lambda: init_params(config, jax.random.PRNGKey(0)),
            optimizer,
            mesh=mesh,
            logical_dims=param_logical_dims(config),
        )
        step_fn = jax_utils.build_sharded_train_step(
            batch_loss, optimizer, setup
        )
        tokens_sh = setup.shard_batch(tokens)
        params, opt_state = setup.params, setup.opt_state
        params, opt_state, loss = step_fn(params, opt_state, tokens_sh)
        first_loss = float(loss)
        start = time.perf_counter()
        for _ in range(steps):
            params, opt_state, loss = step_fn(params, opt_state, tokens_sh)
        loss_value = float(loss)
        elapsed = time.perf_counter() - start
        if not (loss_value < first_loss):
            print(
                f"BENCH SANITY FAILED: loss did not decrease "
                f"({first_loss} -> {loss_value})",
                file=sys.stderr,
            )
            raise SystemExit(1)
        tokens_per_s = batch * config.max_seq * steps / elapsed
        p = num_params(params)
        extra = {
            "loss": loss_value,
            "factorization": setup.factorization,
        }
    _emit(
        tokens_per_s, p,
        {"sharding": mode, "devices": n_dev, **extra},
    )


def _bench_pp(config, optimizer, tokens, steps, init_params,
              partition_stages, stage_forward, logits_loss):
    """Single-process 2-stage microbatched pipeline: same math the MPMD
    stage runner executes, here in topological order (no wire), so the
    matrix row measures the staged computation's throughput."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.parallel.pipeline import bubble_fraction

    num_stages, microbatches = 2, 4
    params = init_params(config, jax.random.PRNGKey(0))
    stages = partition_stages(params, config, num_stages)
    opt_states = [optimizer.init(s) for s in stages]

    def s0_fwd(p, x):
        return stage_forward(p, x, config, first=True, last=False)

    def s1_loss(p, a, targets):
        return logits_loss(
            stage_forward(p, a, config, first=False, last=True), targets
        )

    fwd0 = jax.jit(s0_fwd)
    grad1 = jax.jit(jax.value_and_grad(s1_loss, argnums=(0, 1)))

    def bwd0(p, x, ct):
        _, vjp_fn = jax.vjp(s0_fwd, p, x)
        return vjp_fn(ct)[0]

    bwd0 = jax.jit(bwd0)

    def apply(p, o, g):
        updates, new_o = optimizer.update(g, o, p)
        return jax.tree.map(
            lambda w, u: w + u.astype(w.dtype), p, updates
        ), new_o

    apply = jax.jit(apply)
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    mb = inputs.shape[0] // microbatches

    def one_step():
        g_acc = [None, None]
        losses = []
        for m in range(microbatches):
            x = inputs[m * mb:(m + 1) * mb]
            y = targets[m * mb:(m + 1) * mb]
            a = fwd0(stages[0], x)
            loss, (g1, da) = grad1(stages[1], a, y)
            g0 = bwd0(stages[0], x, da)
            losses.append(loss)
            for i, g in ((0, g0), (1, g1)):
                g_acc[i] = g if g_acc[i] is None else jax.tree.map(
                    jnp.add, g_acc[i], g
                )
        for i in range(num_stages):
            g = jax.tree.map(lambda v: v / microbatches, g_acc[i])
            stages[i], opt_states[i] = apply(stages[i], opt_states[i], g)
        return float(jnp.mean(jnp.stack(losses)))

    first_loss = one_step()  # warmup/compile
    start = time.perf_counter()
    for _ in range(steps):
        loss_value = one_step()
    elapsed = time.perf_counter() - start
    if not (loss_value < first_loss):
        print(
            f"BENCH SANITY FAILED: loss did not decrease "
            f"({first_loss} -> {loss_value})",
            file=sys.stderr,
        )
        raise SystemExit(1)
    p = sum(
        int(jnp.size(l)) for s in stages for l in jax.tree.leaves(s)
    )
    tokens_per_s = inputs.shape[0] * inputs.shape[1] * steps / elapsed
    return tokens_per_s, p, {
        "loss": loss_value,
        "factorization": {"dp": 1, "fsdp": 1, "tp": 1, "pp": num_stages},
        "microbatches": microbatches,
        "schedule_bubble_fraction": round(
            bubble_fraction(num_stages, microbatches), 4
        ),
    }


def main() -> None:
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.transformer import (
        TransformerConfig, init_params, loss_fn, num_params,
    )

    backend = jax.default_backend()
    on_accel = backend in ("tpu", "gpu")
    if on_accel:
        # Shape chosen by an on-chip sweep (round 3): wide MXU-saturating
        # matmuls (dim 4096, hidden 16384 — both multiples of the 128-lane
        # MXU tile), batch 12 x seq 1024 tokens/step (the largest batch
        # that stays HBM-resident — 13/14 regress ~7%, 16 OOMs), bf16
        # weights, NO remat (f32 elementwise intermediates are
        # micro-checkpointed in models/transformer.py). Measured
        # 142 TFLOP/s on v5e (72% MFU).
        config = TransformerConfig(
            vocab_size=8192, dim=4096, n_layers=3, n_heads=32, n_kv_heads=32,
            hidden_dim=16384, max_seq=1024, dtype=jnp.bfloat16,
        )
        batch, steps = 12, 10
    else:  # CPU smoke fallback so the bench never crashes the driver
        config = TransformerConfig.tiny()
        batch, steps = 2, 2

    params = init_params(config, jax.random.PRNGKey(0))
    optimizer = optax.adamw(3e-4)
    opt_state = jax.jit(optimizer.init)(params)
    # seq+1 tokens so the shifted inputs keep a block-aligned length.
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, config.max_seq + 1), 0, config.vocab_size
    )

    # donate params+opt_state: in-place updates halve optimizer-state HBM
    # traffic and free the memory for activations (VERDICT r2 ask 1a).
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, tokens):
        # Next-token LM objective (shifted targets).
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        loss, grads = jax.value_and_grad(loss_fn)(params, inputs, targets, config)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    # warmup/compile. float() forces a device->host read — on remote-attached
    # chips block_until_ready alone does not guarantee execution finished.
    params, opt_state, loss = train_step(params, opt_state, tokens)
    first_loss = float(loss)
    start = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = train_step(params, opt_state, tokens)
    loss_value = float(loss)  # chained params => all steps must complete
    elapsed = time.perf_counter() - start
    # Loss sanity: repeated steps on a fixed batch must strictly improve —
    # the throughput number provably comes from real, chained optimizer
    # steps (a broken/no-op step would leave the loss flat).
    if not (loss_value < first_loss):
        print(
            f"BENCH SANITY FAILED: loss did not decrease "
            f"({first_loss} -> {loss_value})",
            file=sys.stderr,
        )
        raise SystemExit(1)

    tokens_per_step = batch * config.max_seq
    tokens_per_s = tokens_per_step * steps / elapsed
    p = num_params(params)
    achieved_flops = 6.0 * p * tokens_per_s          # fwd+bwd rule of thumb
    a100_effective = 312e12 * 0.40                   # GPU-parity yardstick
    vs_baseline = achieved_flops / a100_effective

    # Peak bf16 FLOP/s per chip kind, for MFU attribution in the detail.
    device_kind = jax.devices()[0].device_kind
    peaks = {
        "TPU v4": 275e12, "TPU v5 lite": 197e12, "TPU v5e": 197e12,
        "TPU v5p": 459e12, "TPU v6 lite": 918e12,
    }
    peak = next((v for k, v in peaks.items() if device_kind.startswith(k)), None)
    mfu = round(achieved_flops / peak, 4) if peak else None

    print(
        json.dumps(
            {
                "metric": "transformer_train_tokens_per_s_per_chip",
                "value": round(tokens_per_s, 1),
                "unit": "tokens/s",
                "vs_baseline": round(vs_baseline, 4),
                "detail": {
                    "backend": backend,
                    "device_kind": device_kind,
                    "params": p,
                    "achieved_tflops": round(achieved_flops / 1e12, 2),
                    "mfu": mfu,
                    "loss": loss_value,
                },
            }
        )
    )


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--sharding", choices=("dp", "fsdp", "tp", "pp"), default=None,
        help="matrix mode: bench ONE parallelism strategy via the GSPMD "
        "trainer path instead of the single-chip headline",
    )
    cli = parser.parse_args()
    if cli.sharding and "xla_force_host_platform_device_count" not in (
        os.environ.get("XLA_FLAGS", "")
    ) and os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # CPU twin: the matrix needs >1 device to shard over.
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
    try:
        if cli.sharding:
            sharded_main(cli.sharding)
        else:
            main()
    except Exception as exc:  # never crash the driver: report the failure
        print(
            json.dumps(
                {
                    "metric": "transformer_train_tokens_per_s_per_chip",
                    "value": 0.0,
                    "unit": "tokens/s",
                    "vs_baseline": 0.0,
                    "detail": {"error": f"{type(exc).__name__}: {exc}"[:500]},
                }
            )
        )
        sys.exit(0)

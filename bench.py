"""Headline benchmark — prints ONE JSON line for the driver.

Metric: flagship-transformer training throughput (tokens/s) on the local
accelerator, single chip.

vs_baseline is the GPU-parity ratio from BASELINE.json's north star
("GPU-parity throughput ... with num_gpus=0"): achieved model FLOP/s divided
by an A100's effective training FLOP/s on the same model (312 TFLOP/s bf16
peak × 40% MFU = 125 TFLOP/s — the standard well-tuned-GPU operating
point). vs_baseline >= 1.0 means one TPU chip matches/beats one A100.
"""

from __future__ import annotations

import functools
import json
import sys
import time


def main() -> None:
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.transformer import (
        TransformerConfig, init_params, loss_fn, num_params,
    )

    backend = jax.default_backend()
    on_accel = backend in ("tpu", "gpu")
    if on_accel:
        # Shape chosen by an on-chip sweep (round 3): wide MXU-saturating
        # matmuls (dim 4096, hidden 16384 — both multiples of the 128-lane
        # MXU tile), batch 12 x seq 1024 tokens/step (the largest batch
        # that stays HBM-resident — 13/14 regress ~7%, 16 OOMs), bf16
        # weights, NO remat (f32 elementwise intermediates are
        # micro-checkpointed in models/transformer.py). Measured
        # 142 TFLOP/s on v5e (72% MFU).
        config = TransformerConfig(
            vocab_size=8192, dim=4096, n_layers=3, n_heads=32, n_kv_heads=32,
            hidden_dim=16384, max_seq=1024, dtype=jnp.bfloat16,
        )
        batch, steps = 12, 10
    else:  # CPU smoke fallback so the bench never crashes the driver
        config = TransformerConfig.tiny()
        batch, steps = 2, 2

    params = init_params(config, jax.random.PRNGKey(0))
    optimizer = optax.adamw(3e-4)
    opt_state = jax.jit(optimizer.init)(params)
    # seq+1 tokens so the shifted inputs keep a block-aligned length.
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, config.max_seq + 1), 0, config.vocab_size
    )

    # donate params+opt_state: in-place updates halve optimizer-state HBM
    # traffic and free the memory for activations (VERDICT r2 ask 1a).
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, tokens):
        # Next-token LM objective (shifted targets).
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        loss, grads = jax.value_and_grad(loss_fn)(params, inputs, targets, config)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    # warmup/compile. float() forces a device->host read — on remote-attached
    # chips block_until_ready alone does not guarantee execution finished.
    params, opt_state, loss = train_step(params, opt_state, tokens)
    first_loss = float(loss)
    start = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = train_step(params, opt_state, tokens)
    loss_value = float(loss)  # chained params => all steps must complete
    elapsed = time.perf_counter() - start
    # Loss sanity: repeated steps on a fixed batch must strictly improve —
    # the throughput number provably comes from real, chained optimizer
    # steps (a broken/no-op step would leave the loss flat).
    if not (loss_value < first_loss):
        print(
            f"BENCH SANITY FAILED: loss did not decrease "
            f"({first_loss} -> {loss_value})",
            file=sys.stderr,
        )
        raise SystemExit(1)

    tokens_per_step = batch * config.max_seq
    tokens_per_s = tokens_per_step * steps / elapsed
    p = num_params(params)
    achieved_flops = 6.0 * p * tokens_per_s          # fwd+bwd rule of thumb
    a100_effective = 312e12 * 0.40                   # GPU-parity yardstick
    vs_baseline = achieved_flops / a100_effective

    # Peak bf16 FLOP/s per chip kind, for MFU attribution in the detail.
    device_kind = jax.devices()[0].device_kind
    peaks = {
        "TPU v4": 275e12, "TPU v5 lite": 197e12, "TPU v5e": 197e12,
        "TPU v5p": 459e12, "TPU v6 lite": 918e12,
    }
    peak = next((v for k, v in peaks.items() if device_kind.startswith(k)), None)
    mfu = round(achieved_flops / peak, 4) if peak else None

    print(
        json.dumps(
            {
                "metric": "transformer_train_tokens_per_s_per_chip",
                "value": round(tokens_per_s, 1),
                "unit": "tokens/s",
                "vs_baseline": round(vs_baseline, 4),
                "detail": {
                    "backend": backend,
                    "device_kind": device_kind,
                    "params": p,
                    "achieved_tflops": round(achieved_flops / 1e12, 2),
                    "mfu": mfu,
                    "loss": loss_value,
                },
            }
        )
    )


if __name__ == "__main__":
    try:
        main()
    except Exception as exc:  # never crash the driver: report the failure
        print(
            json.dumps(
                {
                    "metric": "transformer_train_tokens_per_s_per_chip",
                    "value": 0.0,
                    "unit": "tokens/s",
                    "vs_baseline": 0.0,
                    "detail": {"error": f"{type(exc).__name__}: {exc}"[:500]},
                }
            )
        )
        sys.exit(0)

// raytpu C++ client API — the C++ worker/driver surface (reference N32
// role: cpp/ :: ray::Task(...).Remote(), re-scoped for the ray_tpu wire).
//
// Speaks wire format v1 (versioned envelope + msgpack payloads, see
// ray_tpu/_private/rpc.py) over blocking TCP. Capabilities:
//   * control-plane RPCs: KV put/get, cluster state queries
//   * cross-language tasks: submit a module-qualified Python function
//     ("pkg.module:attr") with plain msgpack args; the worker replies
//     with msgpack values — no Python pickle anywhere on the path.
//
// Cross-language calling matches the reference's Java→Python convention
// (function named by qualified name, simple-type args).

#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <string>
#include <vector>

namespace raytpu {

// Minimal msgpack value model — exactly what the wire payloads need.
struct Value {
  enum class Type { Nil, Bool, Int, Double, Str, Bin, Array, Map };
  Type type = Type::Nil;
  bool b = false;
  int64_t i = 0;
  double d = 0.0;
  std::string s;                 // Str and Bin share storage
  std::vector<Value> array;
  std::map<std::string, Value> map;  // string-keyed maps only

  static Value nil();
  static Value boolean(bool v);
  static Value integer(int64_t v);
  static Value number(double v);
  static Value str(std::string v);
  static Value bin(std::string v);
  static Value arr(std::vector<Value> v);
  static Value obj(std::map<std::string, Value> v);

  bool is_nil() const { return type == Type::Nil; }
  int64_t as_int(int64_t fallback = 0) const;
  std::string as_str(const std::string &fallback = "") const;
  const Value *get(const std::string &key) const;  // map lookup or nullptr
};

std::string msgpack_encode(const Value &value);
// Throws std::runtime_error on malformed input.
Value msgpack_decode(const std::string &raw);

// One blocking connection speaking the framed RPC protocol.
class Connection {
 public:
  Connection() = default;
  ~Connection();
  Connection(const Connection &) = delete;
  Connection &operator=(const Connection &) = delete;

  // Throws std::runtime_error on failure.
  void Connect(const std::string &host, int port);
  void Close();
  bool connected() const { return fd_ >= 0; }

  // Synchronous request/reply. Throws on transport error or ERR reply.
  Value Call(const std::string &method, const Value &payload);
  // Same, with a pre-encoded payload (typed wire_gen.h messages).
  Value CallRaw(const std::string &method, const std::string &payload);

 private:
  int fd_ = -1;
  uint32_t next_msgid_ = 1;
};

// High-level client: controller + on-demand agent/worker connections.
class Client {
 public:
  // Controller address, e.g. ("127.0.0.1", 6380).
  void Connect(const std::string &host, int port);

  // Internal KV (GCS KV role).
  void KvPut(const std::string &ns, const std::string &key,
             const std::string &value);
  // Returns false if the key is absent.
  bool KvGet(const std::string &ns, const std::string &key,
             std::string *value_out);

  // {resource: total} for the cluster.
  std::map<std::string, double> ClusterResources();

  // Submit fn_ref ("pkg.module:attr") with msgpack args to a leased
  // worker; blocks for the result. Throws std::runtime_error with the
  // remote traceback on task failure.
  Value SubmitTask(const std::string &fn_ref, const std::vector<Value> &args,
                   double num_cpus = 1.0);

 private:
  Connection controller_;
  std::string job_id_ = "job-cpp-client";
  int task_counter_ = 0;
};

}  // namespace raytpu

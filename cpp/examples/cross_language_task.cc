// Example: drive a ray_tpu cluster from C++ (reference N32 role).
//
//   cross_language_task <controller_host> <controller_port>
//
// Exercises KV put/get, cluster state, and a cross-language task calling
// a Python function by module-qualified name with msgpack args. Prints
// one result line per capability; exits nonzero on any failure.

#include <cstdio>
#include <string>

#include "raytpu/client.h"

int main(int argc, char **argv) {
  if (argc != 3) {
    std::fprintf(stderr, "usage: %s <controller_host> <controller_port>\n",
                 argv[0]);
    return 2;
  }
  try {
    raytpu::Client client;
    client.Connect(argv[1], std::atoi(argv[2]));

    client.KvPut("cpp-test", "greeting", "hello from c++");
    std::string stored;
    if (!client.KvGet("cpp-test", "greeting", &stored) ||
        stored != "hello from c++") {
      std::fprintf(stderr, "kv round-trip mismatch\n");
      return 1;
    }
    std::printf("kv: %s\n", stored.c_str());

    auto resources = client.ClusterResources();
    std::printf("cluster CPU: %.1f\n", resources["CPU"]);

    // math:hypot — any importable module-qualified function works.
    raytpu::Value result = client.SubmitTask(
        "math:hypot",
        {raytpu::Value::number(3.0), raytpu::Value::number(4.0)});
    std::printf("task math:hypot(3,4) = %.1f\n", result.d);
    if (result.d != 5.0) {
      std::fprintf(stderr, "unexpected task result\n");
      return 1;
    }

    // Error propagation: a missing attribute must raise with a traceback.
    try {
      client.SubmitTask("math:not_a_function", {});
      std::fprintf(stderr, "expected failure did not raise\n");
      return 1;
    } catch (const std::exception &err) {
      std::printf("error propagation: ok\n");
    }
    std::printf("CPP CLIENT: ALL OK\n");
    return 0;
  } catch (const std::exception &err) {
    std::fprintf(stderr, "FAILED: %s\n", err.what());
    return 1;
  }
}

// raytpu C++ client implementation — see include/raytpu/client.h.

#include "raytpu/client.h"

#include "raytpu/wire_gen.h"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>
#include <stdexcept>

namespace raytpu {

// ---------------------------------------------------------------------------
// Value helpers
// ---------------------------------------------------------------------------
Value Value::nil() { return Value{}; }
Value Value::boolean(bool v) {
  Value out; out.type = Type::Bool; out.b = v; return out;
}
Value Value::integer(int64_t v) {
  Value out; out.type = Type::Int; out.i = v; return out;
}
Value Value::number(double v) {
  Value out; out.type = Type::Double; out.d = v; return out;
}
Value Value::str(std::string v) {
  Value out; out.type = Type::Str; out.s = std::move(v); return out;
}
Value Value::bin(std::string v) {
  Value out; out.type = Type::Bin; out.s = std::move(v); return out;
}
Value Value::arr(std::vector<Value> v) {
  Value out; out.type = Type::Array; out.array = std::move(v); return out;
}
Value Value::obj(std::map<std::string, Value> v) {
  Value out; out.type = Type::Map; out.map = std::move(v); return out;
}

int64_t Value::as_int(int64_t fallback) const {
  if (type == Type::Int) return i;
  if (type == Type::Double) return int64_t(d);
  return fallback;
}

std::string Value::as_str(const std::string &fallback) const {
  if (type == Type::Str || type == Type::Bin) return s;
  return fallback;
}

const Value *Value::get(const std::string &key) const {
  if (type != Type::Map) return nullptr;
  auto it = map.find(key);
  return it == map.end() ? nullptr : &it->second;
}

// ---------------------------------------------------------------------------
// msgpack encode (subset: the types our payloads use)
// ---------------------------------------------------------------------------
namespace {

void put_u16(std::string &out, uint16_t v) {
  out.push_back(char(v >> 8)); out.push_back(char(v));
}
void put_u32(std::string &out, uint32_t v) {
  out.push_back(char(v >> 24)); out.push_back(char(v >> 16));
  out.push_back(char(v >> 8)); out.push_back(char(v));
}
void put_u64(std::string &out, uint64_t v) {
  for (int shift = 56; shift >= 0; shift -= 8) out.push_back(char(v >> shift));
}

void encode_into(const Value &value, std::string &out) {
  switch (value.type) {
    case Value::Type::Nil:
      out.push_back(char(0xc0));
      break;
    case Value::Type::Bool:
      out.push_back(char(value.b ? 0xc3 : 0xc2));
      break;
    case Value::Type::Int: {
      int64_t v = value.i;
      if (v >= 0 && v < 128) {
        out.push_back(char(v));
      } else if (v < 0 && v >= -32) {
        out.push_back(char(0xe0 | (v + 32)));
      } else {
        out.push_back(char(0xd3));  // int64
        put_u64(out, uint64_t(v));
      }
      break;
    }
    case Value::Type::Double: {
      out.push_back(char(0xcb));
      uint64_t bits;
      std::memcpy(&bits, &value.d, 8);
      put_u64(out, bits);
      break;
    }
    case Value::Type::Str: {
      size_t n = value.s.size();
      if (n < 32) {
        out.push_back(char(0xa0 | n));
      } else if (n < 256) {
        out.push_back(char(0xd9)); out.push_back(char(n));
      } else if (n < 65536) {
        out.push_back(char(0xda)); put_u16(out, uint16_t(n));
      } else {
        out.push_back(char(0xdb)); put_u32(out, uint32_t(n));
      }
      out += value.s;
      break;
    }
    case Value::Type::Bin: {
      size_t n = value.s.size();
      if (n < 256) {
        out.push_back(char(0xc4)); out.push_back(char(n));
      } else if (n < 65536) {
        out.push_back(char(0xc5)); put_u16(out, uint16_t(n));
      } else {
        out.push_back(char(0xc6)); put_u32(out, uint32_t(n));
      }
      out += value.s;
      break;
    }
    case Value::Type::Array: {
      size_t n = value.array.size();
      if (n < 16) {
        out.push_back(char(0x90 | n));
      } else if (n < 65536) {
        out.push_back(char(0xdc)); put_u16(out, uint16_t(n));
      } else {
        out.push_back(char(0xdd)); put_u32(out, uint32_t(n));
      }
      for (const auto &item : value.array) encode_into(item, out);
      break;
    }
    case Value::Type::Map: {
      size_t n = value.map.size();
      if (n < 16) {
        out.push_back(char(0x80 | n));
      } else if (n < 65536) {
        out.push_back(char(0xde)); put_u16(out, uint16_t(n));
      } else {
        out.push_back(char(0xdf)); put_u32(out, uint32_t(n));
      }
      for (const auto &kv : value.map) {
        encode_into(Value::str(kv.first), out);
        encode_into(kv.second, out);
      }
      break;
    }
  }
}

// ---------------------------------------------------------------------------
// msgpack decode
// ---------------------------------------------------------------------------
struct Reader {
  const uint8_t *data;
  size_t size;
  size_t pos = 0;

  uint8_t u8() {
    require(1);
    return data[pos++];
  }
  uint16_t u16() { require(2); uint16_t v = (uint16_t(data[pos]) << 8) | data[pos + 1]; pos += 2; return v; }
  uint32_t u32() {
    require(4);
    uint32_t v = 0;
    for (int k = 0; k < 4; ++k) v = (v << 8) | data[pos + k];
    pos += 4;
    return v;
  }
  uint64_t u64() {
    require(8);
    uint64_t v = 0;
    for (int k = 0; k < 8; ++k) v = (v << 8) | data[pos + k];
    pos += 8;
    return v;
  }
  std::string bytes(size_t n) {
    require(n);
    std::string out(reinterpret_cast<const char *>(data + pos), n);
    pos += n;
    return out;
  }
  void require(size_t n) {
    if (pos + n > size) throw std::runtime_error("msgpack: truncated");
  }
};

Value decode_value(Reader &r) {
  uint8_t tag = r.u8();
  if (tag < 0x80) return Value::integer(tag);             // positive fixint
  if (tag >= 0xe0) return Value::integer(int8_t(tag));    // negative fixint
  if ((tag & 0xf0) == 0x90) {                             // fixarray
    std::vector<Value> items(tag & 0x0f);
    for (auto &item : items) item = decode_value(r);
    return Value::arr(std::move(items));
  }
  if ((tag & 0xf0) == 0x80) {                             // fixmap
    std::map<std::string, Value> out;
    for (int k = 0; k < (tag & 0x0f); ++k) {
      Value key = decode_value(r);
      out[key.as_str()] = decode_value(r);
    }
    return Value::obj(std::move(out));
  }
  if ((tag & 0xe0) == 0xa0) return Value::str(r.bytes(tag & 0x1f));  // fixstr
  switch (tag) {
    case 0xc0: return Value::nil();
    case 0xc2: return Value::boolean(false);
    case 0xc3: return Value::boolean(true);
    case 0xc4: return Value::bin(r.bytes(r.u8()));
    case 0xc5: return Value::bin(r.bytes(r.u16()));
    case 0xc6: return Value::bin(r.bytes(r.u32()));
    case 0xca: {  // float32
      uint32_t bits = r.u32();
      float f;
      std::memcpy(&f, &bits, 4);
      return Value::number(double(f));
    }
    case 0xcb: {  // float64
      uint64_t bits = r.u64();
      double d;
      std::memcpy(&d, &bits, 8);
      return Value::number(d);
    }
    case 0xcc: return Value::integer(r.u8());
    case 0xcd: return Value::integer(r.u16());
    case 0xce: return Value::integer(r.u32());
    case 0xcf: return Value::integer(int64_t(r.u64()));
    case 0xd0: return Value::integer(int8_t(r.u8()));
    case 0xd1: return Value::integer(int16_t(r.u16()));
    case 0xd2: return Value::integer(int32_t(r.u32()));
    case 0xd3: return Value::integer(int64_t(r.u64()));
    case 0xd9: return Value::str(r.bytes(r.u8()));
    case 0xda: return Value::str(r.bytes(r.u16()));
    case 0xdb: return Value::str(r.bytes(r.u32()));
    case 0xdc: {
      size_t n = r.u16();
      std::vector<Value> items(n);
      for (auto &item : items) item = decode_value(r);
      return Value::arr(std::move(items));
    }
    case 0xdd: {
      size_t n = r.u32();
      std::vector<Value> items(n);
      for (auto &item : items) item = decode_value(r);
      return Value::arr(std::move(items));
    }
    case 0xde: {
      size_t n = r.u16();
      std::map<std::string, Value> out;
      for (size_t k = 0; k < n; ++k) {
        Value key = decode_value(r);
        out[key.as_str()] = decode_value(r);
      }
      return Value::obj(std::move(out));
    }
    case 0xdf: {
      size_t n = r.u32();
      std::map<std::string, Value> out;
      for (size_t k = 0; k < n; ++k) {
        Value key = decode_value(r);
        out[key.as_str()] = decode_value(r);
      }
      return Value::obj(std::move(out));
    }
    default:
      throw std::runtime_error("msgpack: unsupported tag");
  }
}

void write_all(int fd, const char *data, size_t n) {
  while (n > 0) {
    ssize_t written = ::send(fd, data, n, MSG_NOSIGNAL);
    if (written <= 0) throw std::runtime_error("raytpu: send failed");
    data += written;
    n -= size_t(written);
  }
}

void read_all(int fd, char *data, size_t n) {
  while (n > 0) {
    ssize_t got = ::read(fd, data, n);
    if (got <= 0) throw std::runtime_error("raytpu: connection closed");
    data += got;
    n -= size_t(got);
  }
}

}  // namespace

std::string msgpack_encode(const Value &value) {
  std::string out;
  encode_into(value, out);
  return out;
}

Value msgpack_decode(const std::string &raw) {
  Reader r{reinterpret_cast<const uint8_t *>(raw.data()), raw.size()};
  return decode_value(r);
}

// ---------------------------------------------------------------------------
// Connection — wire format v1 framing
// ---------------------------------------------------------------------------
Connection::~Connection() { Close(); }

void Connection::Connect(const std::string &host, int port) {
  Close();
  fd_ = socket(AF_INET, SOCK_STREAM, 0);
  if (fd_ < 0) throw std::runtime_error("raytpu: socket() failed");
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(uint16_t(port));
  if (inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    Close();
    throw std::runtime_error("raytpu: bad host " + host);
  }
  if (connect(fd_, reinterpret_cast<sockaddr *>(&addr), sizeof(addr)) != 0) {
    Close();
    throw std::runtime_error("raytpu: connect failed to " + host + ":" +
                             std::to_string(port));
  }
  int one = 1;
  setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

void Connection::Close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

Value Connection::Call(const std::string &method, const Value &payload) {
  return CallRaw(method, msgpack_encode(payload));
}

Value Connection::CallRaw(const std::string &method,
                          const std::string &payload) {
  if (fd_ < 0) throw std::runtime_error("raytpu: not connected");
  constexpr uint8_t kVersion = 1, kReq = 0, kRep = 1, kErr = 2, kPush = 3;
  std::string body;
  uint32_t msgid = next_msgid_++;
  body.push_back(char(kVersion));
  body.push_back(char(kReq));
  // msgid + method_len are little-endian on this wire (struct '<I','<H').
  for (int shift = 0; shift < 32; shift += 8) body.push_back(char(msgid >> shift));
  uint16_t mlen = uint16_t(method.size());
  body.push_back(char(mlen & 0xff));
  body.push_back(char(mlen >> 8));
  body += method;
  body += payload;
  std::string frame;
  uint32_t len = uint32_t(body.size());
  for (int shift = 0; shift < 32; shift += 8) frame.push_back(char(len >> shift));
  frame += body;
  write_all(fd_, frame.data(), frame.size());

  while (true) {
    char head[4];
    read_all(fd_, head, 4);
    uint32_t rlen = 0;
    for (int k = 3; k >= 0; --k) rlen = (rlen << 8) | uint8_t(head[k]);
    std::string rbody(rlen, '\0');
    read_all(fd_, rbody.data(), rlen);
    if (rlen < 8) throw std::runtime_error("raytpu: short frame");
    uint8_t kind = uint8_t(rbody[1]);
    uint32_t rid = 0;
    for (int k = 5; k >= 2; --k) rid = (rid << 8) | uint8_t(rbody[k]);
    uint16_t rmlen = uint16_t(uint8_t(rbody[6])) |
                     (uint16_t(uint8_t(rbody[7])) << 8);
    std::string rpayload = rbody.substr(8 + rmlen);
    if (kind == kPush) continue;  // unsolicited pubsub — ignore
    if (rid != msgid) continue;   // stale reply (shouldn't happen: sync use)
    Value decoded = rpayload.empty() ? Value::nil() : msgpack_decode(rpayload);
    if (kind == kErr) {
      throw std::runtime_error("raytpu remote error in " + method + ":\n" +
                               decoded.as_str("<no traceback>"));
    }
    if (kind != kRep) throw std::runtime_error("raytpu: unexpected kind");
    return decoded;
  }
}

// ---------------------------------------------------------------------------
// Client
// ---------------------------------------------------------------------------
void Client::Connect(const std::string &host, int port) {
  controller_.Connect(host, port);
}

void Client::KvPut(const std::string &ns, const std::string &key,
                   const std::string &value) {
  controller_.Call("kv_put", Value::obj({
      {"namespace", Value::str(ns)},
      {"key", Value::str(key)},
      {"value", Value::bin(value)},
      {"overwrite", Value::boolean(true)},
  }));
}

bool Client::KvGet(const std::string &ns, const std::string &key,
                   std::string *value_out) {
  Value reply = controller_.Call("kv_get", Value::obj({
      {"namespace", Value::str(ns)},
      {"key", Value::str(key)},
  }));
  const Value *status = reply.get("status");
  if (status == nullptr || status->as_str() != "ok") return false;
  const Value *value = reply.get("value");
  if (value_out != nullptr && value != nullptr) *value_out = value->as_str();
  return true;
}

std::map<std::string, double> Client::ClusterResources() {
  Value reply = controller_.Call("cluster_resources", Value::obj({}));
  std::map<std::string, double> out;
  if (reply.type == Value::Type::Map) {
    for (const auto &kv : reply.map) {
      out[kv.first] = kv.second.type == Value::Type::Double
                          ? kv.second.d
                          : double(kv.second.as_int());
    }
  }
  return out;
}

Value Client::SubmitTask(const std::string &fn_ref,
                         const std::vector<Value> &args, double num_cpus) {
  // Typed wire messages (generated from src/schema/wire_schema.py — the
  // reference's protobuf TaskSpec role, SURVEY N14) replace hand-built
  // payload maps on the whole lease→push→reply path.
  wire::LeaseRequest lease_req;
  lease_req.resources["CPU"] = num_cpus;
  lease_req.job_id = job_id_;
  wire::LeaseGrant grant = wire::LeaseGrant::FromValue(
      controller_.CallRaw("request_lease", lease_req.Encode()));
  if (grant.status != "ok") {
    throw std::runtime_error("raytpu: lease request failed: " +
                             (grant.status.empty() ? "<no status>"
                                                   : grant.status));
  }
  if (grant.agent_addr.type != Value::Type::Array ||
      grant.agent_addr.array.size() != 2) {
    throw std::runtime_error("raytpu: malformed agent_addr");
  }
  Connection agent;
  agent.Connect(grant.agent_addr.array[0].as_str(),
                int(grant.agent_addr.array[1].as_int()));
  wire::WorkerLeaseRequest worker_req;
  worker_req.resources["CPU"] = num_cpus;
  worker_req.runtime_env = Value::obj({});
  worker_req.job_id = job_id_;
  wire::WorkerLeaseReply lease = wire::WorkerLeaseReply::FromValue(
      agent.CallRaw("lease_worker", worker_req.Encode()));
  if (lease.status != "ok") {
    throw std::runtime_error("raytpu: worker lease failed");
  }
  if (lease.worker_addr.type != Value::Type::Array ||
      lease.worker_addr.array.size() != 2) {
    throw std::runtime_error("raytpu: malformed worker_addr");
  }
  if (lease.lease_id.empty()) {
    throw std::runtime_error("raytpu: lease reply missing lease_id");
  }
  Connection worker;
  worker.Connect(lease.worker_addr.array[0].as_str(),
                 int(lease.worker_addr.array[1].as_int()));

  wire::TaskSpec spec;
  spec.task_id = "tsk-cpp-" + std::to_string(++task_counter_);
  spec.job_id = job_id_;
  spec.cross_language = true;
  spec.function_ref = fn_ref;
  spec.name = fn_ref;
  spec.args = msgpack_encode(Value::arr(std::vector<Value>(args)));
  spec.num_returns = 1;
  spec.resources["CPU"] = num_cpus;
  spec.owner.worker_id = "cpp-client";
  spec.owner.address = Value::arr({Value::str(""), Value::integer(0)});
  spec.runtime_env = Value::obj({});
  wire::TaskReply reply = wire::TaskReply::FromValue(
      worker.CallRaw("push_task", spec.Encode()));
  // Hand the lease back so the worker returns to the agent's idle pool.
  try {
    wire::ReturnWorkerRequest ret;
    ret.lease_id = lease.lease_id;
    agent.CallRaw("return_worker", ret.Encode());
  } catch (const std::exception &) {
  }
  if (reply.status != "ok") {
    throw std::runtime_error(
        "raytpu task failed: " +
        (reply.error_text.empty() ? std::string("<no detail>")
                                  : reply.error_text));
  }
  if (reply.returns.empty() || reply.returns[0].data.empty()) {
    return Value::nil();
  }
  return msgpack_decode(reply.returns[0].data);
}

}  // namespace raytpu

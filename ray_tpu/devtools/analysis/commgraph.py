"""Static communication graph: every send/recv/collective/overlap-launch
site in the package, with its tag expression, group, and rank-guard
context.

This is the front-end ROADMAP item 2's compiled dataflow graphs will
invoke at graph-declaration time: before a gang pre-opens on-device p2p
channels, the channel graph here proves the protocol is well-formed —
every send has a skeleton-compatible recv, no two sites can emit the
same tag on one group, and rank-guarded endpoints complement instead of
coincide.

Tag expressions are normalized to *skeletons*: literal fragments are
kept verbatim and dynamic fragments (f-string holes, ``.format`` /
``%`` placeholders, arbitrary expressions) become wildcards. Two
skeletons *unify* when some concrete string matches both — e.g. the
stage-runner's forward-activation send ``f"{step_tag}f{m}v{vs + 1}"``
and its recv ``f"{step_tag}f{m}v{vs}"`` both normalize to
``{}f{}v{}`` and unify, while ``{}f{}v{}`` vs ``{}b{}v{}`` do not
(see :func:`skeletons_unify` for the exact semantics). Matching errs
generous, so "unmatched" findings are high-confidence: no assignment
of dynamic fragments could ever have produced a partner.

Extraction is scoped by path (``util/collective/``, ``train/``,
``parallel/``) plus a group-ish receiver heuristic elsewhere, so socket
``.send()`` / RPC ``.recv()`` plumbing in ``_private/`` never enters
the graph.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from ray_tpu.devtools.lint.core import call_name

# Wildcard marker inside a skeleton (rendered "{}" for humans/JSON).
WILD = "\x00"

_P2P_SEND = {"send", "send_async", "push"}
_P2P_RECV = {"recv", "pop"}
# Channel-object verbs (rtdag DeviceChannel). ``push``/``pop`` are far
# too common as plain container methods to admit on receiver shape
# alone, so a site only counts when it passes an explicit ``tag=``
# keyword — the certified-tag idiom.
_TAG_KW_ONLY = {"push", "pop"}
_COLLECTIVES = {
    "allreduce", "allreduce_sharded", "allgather", "reducescatter",
    "broadcast", "barrier",
}
_LAUNCHES = {"launch_bucketed_allreduce", "begin_gradient_sync"}
_METHODS = _P2P_SEND | _P2P_RECV | _COLLECTIVES | _LAUNCHES

# Signature-derived defaults when no ``tag=`` is passed at the site.
_DEFAULT_TAG = {
    "allreduce": "__ar",
    "allreduce_sharded": "__hier",
}

# Positional index of the tag argument, per method.
_TAG_POS = {
    "send": 2, "send_async": 2, "recv": 1,
    "allreduce": 2, "allreduce_sharded": 2,
}

# Receivers that look like a collective group handle. Matches the tail
# component: ``self.group``, ``group``, ``coll``, ``collective``,
# ``self._ring``, ``gang.comm`` — not ``conn`` / ``engine`` /
# ``self._sock``.
_GROUPISH = re.compile(r"(^|\.)_?(group|coll\w*|comm\w*|ring|gang)\d*$")

# Paths where bare/self receivers also count (the backends themselves).
_COMM_PATHS = ("util/collective/",)
# Paths scanned for group-ish sites at all. serve/_private is included
# (ISSUE 13): the serve control plane hosts no collectives today, so the
# scan doubles as a tripwire against one sneaking onto the request path.
_SCAN_PATHS = ("util/collective/", "train/", "parallel/", "release/",
               "bench", "serve/_private/", "serve/llm/", "dag/")

_RANKISH = re.compile(r"rank|stage|process_index")


@dataclass
class CommSite:
    path: str
    line: int
    col: int
    func: str               # enclosing function qual ('' at module level)
    kind: str               # send | recv | collective | launch
    method: str             # the call tail, e.g. send_async
    group: str              # receiver text ('' for bare helper calls)
    tag: str                # skeleton (WILD marks dynamic fragments)
    tag_src: str            # original tag expression source
    peer: str               # dst/src expression source ('' when unknown)
    guards: list = field(default_factory=list)  # [[var, op, value], ...]
    act_wire: bool = False  # payload is the __act self-describing tuple
    thunk: bool = False     # inside a lambda/partial handed elsewhere

    def to_dict(self) -> dict:
        d = self.__dict__.copy()
        d["tag"] = render_skeleton(self.tag)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "CommSite":
        d = dict(d)
        d["tag"] = parse_skeleton(d["tag"])
        return cls(**d)


def render_skeleton(skel: str) -> str:
    return skel.replace(WILD, "{}")


def parse_skeleton(text: str) -> str:
    return text.replace("{}", WILD)


def _collapse(parts: list[str]) -> str:
    """Join fragments, merging consecutive wildcards into one."""
    out: list[str] = []
    for p in parts:
        if p == WILD and out and out[-1].endswith(WILD):
            continue
        out.append(p)
    return "".join(out)


_FORMAT_HOLE = re.compile(r"\{[^{}]*\}")
_PERCENT_HOLE = re.compile(r"%[sdrfxi]")


def tag_skeleton(node: ast.AST | None, default: str = "") -> str:
    """Normalize a tag expression AST to a skeleton string."""
    if node is None:
        return default
    if isinstance(node, ast.Constant):
        return node.value if isinstance(node.value, str) else WILD
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append(WILD)
        return _collapse(parts)
    if isinstance(node, ast.Call):
        fn = node.func
        if (isinstance(fn, ast.Attribute) and fn.attr == "format"
                and isinstance(fn.value, ast.Constant)
                and isinstance(fn.value.value, str)):
            fmt = fn.value.value.replace("{{", "\x01").replace("}}", "\x02")
            skel = _FORMAT_HOLE.sub(WILD, fmt)
            return _collapse(
                [skel.replace("\x01", "{").replace("\x02", "}")]
            )
    if isinstance(node, ast.BinOp):
        if isinstance(node.op, ast.Add):
            return _collapse(
                [tag_skeleton(node.left, WILD),
                 tag_skeleton(node.right, WILD)]
            )
        if isinstance(node.op, ast.Mod) and \
                isinstance(node.left, ast.Constant) and \
                isinstance(node.left.value, str):
            return _collapse([_PERCENT_HOLE.sub(WILD, node.left.value)])
    return WILD


def _tokens(skel: str) -> list[str]:
    """Alternating literal/wildcard token sequence of a skeleton."""
    out: list[str] = []
    for i, part in enumerate(skel.split(WILD)):
        if i:
            out.append(WILD)
        if part:
            out.append(part)
    return out


def _pattern_matches(pattern: str, literal: str) -> bool:
    rx = ".*".join(re.escape(p) for p in pattern.split(WILD))
    return re.fullmatch(rx, literal, re.S) is not None


def skeletons_unify(a: str, b: str) -> bool:
    """True when the two skeletons denote the same channel family.

    Literal vs literal is string equality; pattern vs literal is real
    wildcard matching (a hole absorbs any substring). Pattern vs
    pattern requires the *same literal structure* — naive two-sided
    wildcard absorption would call ``{}f{}v{}`` and ``{}b{}v{}``
    compatible (the string ``"fbv"`` matches both) and erase exactly
    the forward/backward distinction the stage-runner tags encode.
    """
    if fully_literal(a):
        return a == b if fully_literal(b) else _pattern_matches(b, a)
    if fully_literal(b):
        return _pattern_matches(a, b)
    return _tokens(a) == _tokens(b)


def fully_literal(skel: str) -> bool:
    return WILD not in skel


# ---------------------------------------------------------------------------
# Extraction
# ---------------------------------------------------------------------------

def _receiver(call: ast.Call) -> str | None:
    """Dotted receiver text of a method call; None for bare calls."""
    if isinstance(call.func, ast.Attribute):
        try:
            return ast.unparse(call.func.value)
        except (ValueError, RecursionError):
            return None
    return None


def _receiver_ok(recv_txt: str | None, relpath: str) -> bool:
    if recv_txt is None:
        return False
    if _GROUPISH.search(recv_txt):
        return True
    in_backend = any(p in relpath for p in _COMM_PATHS)
    return in_backend and (recv_txt == "self"
                          or recv_txt.startswith("self."))


def _arg(call: ast.Call, pos: int | None, *kws: str) -> ast.AST | None:
    for kw in call.keywords:
        if kw.arg in kws:
            return kw.value
    if pos is not None and pos < len(call.args):
        return call.args[pos]
    return None


def _safe_unparse(node: ast.AST | None) -> str:
    if node is None:
        return ""
    try:
        return ast.unparse(node)
    except (ValueError, RecursionError):
        return "<expr>"


def _guard_atoms(test: ast.AST, negated: bool) -> list[list[str]]:
    comps = [test]
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And) \
            and not negated:
        comps = list(test.values)
    atoms: list[list[str]] = []
    for c in comps:
        if not (isinstance(c, ast.Compare) and len(c.ops) == 1
                and isinstance(c.ops[0], (ast.Eq, ast.NotEq))):
            continue
        var = _safe_unparse(c.left)
        val = _safe_unparse(c.comparators[0])
        if not (_RANKISH.search(var) or _RANKISH.search(val)):
            continue
        positive = isinstance(c.ops[0], ast.Eq) != negated
        atoms.append([var, "==" if positive else "!=", val])
    return atoms


def _site_context(call: ast.Call, parents: dict,
                  func_of: dict) -> tuple[str, list, bool]:
    """(enclosing function qual, guard atoms, in-thunk) for a call."""
    guards: list = []
    thunk = False
    prev: ast.AST = call
    cur = parents.get(call)
    while cur is not None:
        if isinstance(cur, ast.Lambda):
            thunk = True
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return func_of.get(cur, cur.name), guards, thunk
        if isinstance(cur, ast.If) and prev is not cur.test:
            negated = any(prev is s for s in cur.orelse)
            guards.extend(_guard_atoms(cur.test, negated))
        prev, cur = cur, parents.get(cur)
    return "", guards, thunk


def _payload_is_act_wire(node: ast.AST | None) -> bool:
    if node is None:
        return False
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id == "_ACT_WIRE":
            return True
        if isinstance(sub, ast.Constant) and sub.value == "__act":
            return True
    return False


def _classify(method: str) -> str:
    if method in _P2P_SEND:
        return "send"
    if method in _P2P_RECV:
        return "recv"
    if method in _LAUNCHES:
        return "launch"
    return "collective"


def _make_site(relpath: str, call: ast.Call, method: str, group: str,
               args_call: ast.Call, shift: int, parents: dict,
               func_of: dict, thunk_forced: bool) -> CommSite:
    """Build a site record. ``args_call`` carries the argument list
    (differs from ``call`` for ``functools.partial(group.send, ...)``
    thunks, where positions shift by one)."""
    kind = _classify(method)
    pos = _TAG_POS.get(method)
    tag_node = _arg(args_call,
                    pos + shift if pos is not None else None, "tag")
    skel = tag_skeleton(tag_node, default=_DEFAULT_TAG.get(method, ""))
    if method in _TAG_KW_ONLY:
        # Channel verbs: the peer is baked into the channel object at
        # compile time, not visible at the call site.
        peer = None
        payload = _arg(args_call, 0 + shift, "value") \
            if kind == "send" else None
    elif kind == "send":
        peer = _arg(args_call, 1 + shift, "dst_rank", "dst")
        payload = _arg(args_call, 0 + shift, "array", "payload")
    elif kind == "recv":
        peer = _arg(args_call, 0 + shift, "src_rank", "src")
        payload = None
    else:
        peer, payload = None, None
    func, guards, thunk = _site_context(call, parents, func_of)
    return CommSite(
        path=relpath, line=call.lineno, col=call.col_offset + 1,
        func=func, kind=kind, method=method, group=group,
        tag=skel, tag_src=_safe_unparse(tag_node),
        peer=_safe_unparse(peer), guards=guards,
        act_wire=_payload_is_act_wire(payload),
        thunk=thunk or thunk_forced,
    )


def extract_sites(tree: ast.Module, relpath: str) -> list[dict]:
    """All communication sites in a parsed file, as JSON-serializable
    dicts (the ``comm`` section of the cached per-file summary)."""
    if not any(p in relpath for p in _SCAN_PATHS):
        return []
    parents: dict = {}
    func_of: dict = {}

    def index(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            parents[child] = node
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func_of[child] = f"{prefix}{child.name}"
                index(child, f"{prefix}{child.name}.")
            elif isinstance(child, ast.ClassDef):
                index(child, f"{prefix}{child.name}.")
            else:
                index(child, prefix)

    index(tree, "")

    sites: list[CommSite] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        tail = name.rsplit(".", 1)[-1] if name else ""
        if tail in _METHODS and isinstance(node.func, ast.Attribute):
            if tail in _TAG_KW_ONLY and not any(
                kw.arg == "tag" for kw in node.keywords
            ):
                continue  # container .push()/.pop(), not a channel verb
            recv_txt = _receiver(node)
            if _receiver_ok(recv_txt, relpath):
                sites.append(_make_site(
                    relpath, node, tail, recv_txt or "", node, 0,
                    parents, func_of, thunk_forced=False,
                ))
            continue
        # functools.partial(group.send, arr, dst, tag=...) — the send
        # is referenced, not called; positional args shift by one.
        if tail == "partial" and node.args and \
                isinstance(node.args[0], ast.Attribute):
            target = node.args[0]
            if target.attr in _METHODS and not (
                target.attr in _TAG_KW_ONLY
                and not any(kw.arg == "tag" for kw in node.keywords)
            ):
                recv_txt = _safe_unparse(target.value)
                if _receiver_ok(recv_txt, relpath):
                    sites.append(_make_site(
                        relpath, node, target.attr, recv_txt,
                        node, 1, parents, func_of, thunk_forced=True,
                    ))
    sites += _wrapper_sites(tree, relpath, sites, parents, func_of)
    return [s.to_dict() for s in sites]


def _wrapper_sites(tree: ast.Module, relpath: str, direct: list[CommSite],
                   parents: dict, func_of: dict) -> list[CommSite]:
    """One level of wrapper-forwarded tag propagation.

    The stage-runner idiom routes every activation wire through thin
    helpers — ``self._send(arr, dst, f"{step_tag}f{m}v{vs + 1}")`` calls
    a ``_send(self, array, dst, tag, ...)`` that does
    ``group.send(..., tag=tag)``. The direct site only sees the opaque
    ``{}`` skeleton; the structured tag lives at the *wrapper call
    site*. When a direct site's tag expression is exactly a parameter
    of its enclosing function, each same-class (or module-local) call
    to that function with an explicit tag argument yields a derived
    site carrying the caller's tag skeleton and guard context.
    """
    node_of = {qual: fn for fn, qual in func_of.items()}
    wrappers: dict[str, list[tuple[CommSite, str]]] = {}
    for site in direct:
        fn = node_of.get(site.func)
        if fn is None or not site.tag_src.isidentifier():
            continue
        params = [a.arg for a in fn.args.args + fn.args.kwonlyargs]
        if site.tag_src in params:
            wrappers.setdefault(site.func, []).append(
                (site, site.tag_src)
            )
    if not wrappers:
        return []

    derived: list[CommSite] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if not name:
            continue
        head, _, tail = name.partition(".")
        caller, guards, thunk = _site_context(node, parents, func_of)
        owner = caller.rpartition(".")[0]
        if head in ("self", "cls") and tail and owner:
            qual = f"{owner}.{tail}"
        elif "." not in name:
            qual = name
        else:
            continue
        for inner, tag_param in wrappers.get(qual, ()):
            fn = node_of[qual]
            params = [a.arg for a in fn.args.args]
            offset = 1 if params and params[0] in ("self", "cls") else 0
            try:
                pos = params.index(tag_param) - offset
            except ValueError:
                pos = None
            tag_node = _arg(node, pos, tag_param)
            if tag_node is None:
                continue  # the direct site already covers the default
            derived.append(CommSite(
                path=relpath, line=node.lineno, col=node.col_offset + 1,
                func=caller, kind=inner.kind, method=inner.method,
                group=inner.group, tag=tag_skeleton(tag_node, WILD),
                tag_src=_safe_unparse(tag_node), peer="",
                guards=guards, act_wire=inner.act_wire, thunk=thunk,
            ))
    return derived


# ---------------------------------------------------------------------------
# Channel graph
# ---------------------------------------------------------------------------

@dataclass
class Channel:
    send: CommSite
    recvs: list[CommSite] = field(default_factory=list)


class CommGraph:
    """Per-group channel view over a flat site list."""

    def __init__(self, sites: list[CommSite]):
        self.sites = sites
        self.sends = [s for s in sites if s.kind == "send"]
        self.recvs = [s for s in sites if s.kind == "recv"]

    @classmethod
    def from_summaries(cls, site_dicts: list[dict]) -> "CommGraph":
        return cls([CommSite.from_dict(d) for d in site_dicts])

    def channels(self) -> list[Channel]:
        """Each send paired with every skeleton-compatible recv.

        Matching is generous across group keys: receiver *text* differs
        legitimately between endpoints (``self.group`` on the sender,
        ``coll`` on the receiver can be the same runtime group), so
        only the tag skeleton gates the pairing — which keeps the
        unmatched findings high-confidence.
        """
        out = []
        for s in self.sends:
            out.append(Channel(
                send=s,
                recvs=[r for r in self.recvs
                       if skeletons_unify(s.tag, r.tag)],
            ))
        return out

    def unmatched_recvs(self) -> list[CommSite]:
        return [r for r in self.recvs
                if not any(skeletons_unify(s.tag, r.tag)
                           for s in self.sends)]

    # -- export ---------------------------------------------------------

    def to_json(self) -> dict:
        return {
            "sites": [s.to_dict() for s in self.sites],
            "channels": [
                {
                    "send": f"{c.send.path}:{c.send.line}",
                    "tag": render_skeleton(c.send.tag),
                    "recvs": [f"{r.path}:{r.line}" for r in c.recvs],
                }
                for c in self.channels()
            ],
        }

    def to_dot(self) -> str:
        """Graphviz digraph: send sites -> tag-family nodes -> recv
        sites, one subgraph cluster per file."""
        def nid(s: CommSite) -> str:
            return f"s{abs(hash((s.path, s.line, s.col))) % 10**10}"

        lines = [
            "digraph commgraph {",
            "  rankdir=LR;",
            '  node [fontname="monospace" fontsize=10];',
        ]
        by_path: dict[str, list[CommSite]] = {}
        for s in self.sites:
            by_path.setdefault(s.path, []).append(s)
        for i, (path, sites) in enumerate(sorted(by_path.items())):
            lines.append(f"  subgraph cluster_{i} {{")
            lines.append(f'    label="{path}";')
            for s in sites:
                shape = {"send": "box", "recv": "ellipse",
                         "launch": "hexagon"}.get(s.kind, "diamond")
                label = (f"{s.method} L{s.line}\\n"
                         f"tag={render_skeleton(s.tag)}")
                lines.append(
                    f'    {nid(s)} [shape={shape} label="{label}"];'
                )
            lines.append("  }")
        tags: dict[str, str] = {}
        for c in self.channels():
            key = render_skeleton(c.send.tag)
            if key not in tags:
                tags[key] = f"t{len(tags)}"
                lines.append(
                    f'  {tags[key]} [shape=plaintext label="[{key}]"];'
                )
            lines.append(f"  {nid(c.send)} -> {tags[key]};")
            for r in c.recvs:
                lines.append(f"  {tags[key]} -> {nid(r)};")
        lines.append("}")
        return "\n".join(lines) + "\n"


def graph_from_project(project) -> CommGraph:
    """Build the channel graph from a ProjectGraph carrying per-file
    ``comm_sites`` summaries (attached by the lint runner)."""
    sites = getattr(project, "comm_sites", None) or []
    return CommGraph.from_summaries(sites)

"""Whole-program static analyses layered above the rtlint callgraph.

``commgraph`` extracts every communication site in the package and
builds the per-group channel graph that the protocol-verification
rules (unmatched-p2p, tag-collision, rank-asymmetric-channel,
schedule-deadlock) and the future compiled-dataflow-graph layer
(ROADMAP item 2) consume.
"""

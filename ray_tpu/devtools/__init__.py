"""Developer tooling that ships with the framework (``ray_tpu lint``)."""

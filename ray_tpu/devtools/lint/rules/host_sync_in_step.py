"""host-sync-in-step: device→host synchronization inside the step hot loop.

``block_until_ready()``, ``np.asarray(device_array)``, ``.item()``,
``float(loss)`` and ``jax.device_get`` all stall the host until the
device queue drains. Inside the per-step training loop that turns the
async dispatch pipeline into lock-step execution — the flight recorder
(PR 8) shows it as compute-bound when it is actually host-bound.

Scope: the training/model/parallel layers. Fires inside functions whose
name marks them as the per-step body (``*step*``) and inside ``for``/
``while`` loops of the driving loops (``fit``/``*loop*``/``*epoch*``).
End-of-run barriers (timing, final metrics) live outside the loop and
do not fire.
"""

from __future__ import annotations

import ast
import re

from ray_tpu.devtools.lint.core import (
    FileContext,
    Rule,
    Severity,
    call_name,
    register_rule,
)

_SCOPE = ("train/", "models/", "parallel/", "ops/")

_STEP_FN_RE = re.compile(r"(^|_)step($|_)|^step")
# `schedule` covers the MPMD stage runner (ISSUE 10): a function driving
# the per-microbatch 1F1B op stream is as hot as the step body itself.
_LOOP_FN_RE = re.compile(r"(^|_)(fit|loop|epoch|schedule)s?($|_)")

_SYNC_TAILS = {
    "block_until_ready": "forces a device sync",
    "item": "device->host copy + sync",
    "device_get": "device->host copy + sync",
}
_SYNC_FULL = {
    "np.asarray": "materializes the device array on host",
    "numpy.asarray": "materializes the device array on host",
    "jax.device_get": "device->host copy + sync",
    "float": "scalar device->host sync",
    "int": "scalar device->host sync",
}


def _in_loop(ctx: FileContext, node: ast.AST, fn: ast.AST) -> bool:
    parents = ctx.parent_map()
    cur = parents.get(node)
    while cur is not None and cur is not fn:
        if isinstance(cur, (ast.For, ast.While, ast.AsyncFor)):
            return True
        cur = parents.get(cur)
    return False


@register_rule
class HostSyncInStep(Rule):
    name = "host-sync-in-step"
    severity = Severity.WARNING
    description = (
        "block_until_ready()/.item()/float()/np.asarray on device values "
        "inside the training-step hot loop — stalls dispatch pipelining"
    )

    def check(self, ctx: FileContext):
        if not ctx.in_path(*_SCOPE):
            return
        for qual, fn in ctx.functions().items():
            leaf = qual.rsplit(".", 1)[-1]
            is_step = bool(_STEP_FN_RE.search(leaf))
            is_loop = bool(_LOOP_FN_RE.search(leaf))
            if not (is_step or is_loop):
                continue
            from ray_tpu.devtools.lint.callgraph import _own_statements

            for node in _own_statements(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                tail = name.rsplit(".", 1)[-1]
                why = _SYNC_FULL.get(name) or _SYNC_TAILS.get(tail)
                if why is None:
                    continue
                # float()/int() only matter on non-literal args.
                if name in ("float", "int") and (
                    not node.args
                    or isinstance(node.args[0], ast.Constant)
                ):
                    continue
                # Inside a loop-driver function, only the loop body is
                # hot; inside a *step* function everything is.
                if is_loop and not is_step and \
                        not _in_loop(ctx, node, fn):
                    continue
                yield self.finding(
                    ctx, node,
                    f"`{name}` in `{qual}` {why} inside the step hot "
                    f"loop — move it outside the loop or onto the "
                    f"metrics/report path",
                )

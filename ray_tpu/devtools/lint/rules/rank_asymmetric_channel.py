"""rank-asymmetric-channel: a matched send/recv pair whose rank guards
coincide instead of complementing.

A p2p wire needs the *sender* guard and the *receiver* guard to select
different ranks — ``if rank == src: send(...) else: recv(...)`` is the
correct broadcast shape (the else negates the guard, so the endpoints
complement). When BOTH endpoints of one tag family sit under the SAME
positive equality guard, the selected rank sends to itself and every
other rank runs neither side: the send buffers forever and the
intended receivers block on nothing. The same analysis flags the
self-send directly when the destination expression equals the guarded
rank value.

Guards are extracted syntactically (``rank == <expr>`` comparisons on
rank-ish names, with else-branch negation) — no value analysis — so
the rule only fires when both sides carry an *identical* positive
atom, keeping it high-precision.
"""

from __future__ import annotations

from ray_tpu.devtools.lint.core import (
    FileContext,
    Finding,
    Rule,
    Severity,
    register_rule,
)


def _positive_atoms(site) -> set[tuple[str, str]]:
    return {(var, val) for var, op, val in site.guards if op == "=="}


@register_rule
class RankAsymmetricChannel(Rule):
    name = "rank-asymmetric-channel"
    severity = Severity.ERROR
    description = ("send and recv of one tag family guarded onto the "
                   "SAME rank — the wire has no second endpoint")

    def check_project(self, ctxs: list[FileContext]):
        project = ctxs[0].project if ctxs else None
        if project is None:
            return
        from ray_tpu.devtools.analysis.commgraph import (
            graph_from_project,
            render_skeleton,
        )

        graph = graph_from_project(project)
        seen: set[tuple] = set()
        for channel in graph.channels():
            s = channel.send
            s_atoms = _positive_atoms(s)
            if not s_atoms:
                continue
            # Self-send: destination expression equals the value the
            # guard just pinned this rank to.
            for var, val in s_atoms:
                if s.peer and s.peer == val and \
                        (s.path, s.line, "self") not in seen:
                    seen.add((s.path, s.line, "self"))
                    yield Finding(
                        rule=self.name, path=s.path, line=s.line,
                        col=s.col, severity=self.severity,
                        message=(
                            f"send to {s.peer!r} under guard "
                            f"'{var} == {val}' targets the sending "
                            f"rank itself"
                        ),
                    )
            for r in channel.recvs:
                if (s.path, s.line, r.path, r.line) in seen:
                    continue
                common = s_atoms & _positive_atoms(r)
                if not common:
                    continue
                seen.add((s.path, s.line, r.path, r.line))
                var, val = sorted(common)[0]
                yield Finding(
                    rule=self.name, path=s.path, line=s.line,
                    col=s.col, severity=self.severity,
                    message=(
                        f"send (tag '{render_skeleton(s.tag)}') and "
                        f"its recv at {r.path}:{r.line} are both "
                        f"guarded by '{var} == {val}' — only that "
                        f"rank runs either side, so the channel has "
                        f"no second endpoint"
                    ),
                )

"""swallowed-exception: broad except that silently discards the error.

``except Exception: pass`` in a dashboard handler hides the stack trace
that would have explained the next incident; in a reconnect path it
hides the *reason* a node never came back. A broad handler must do at
least one of: re-raise, log, record to a span, or be explicitly
suppressed with a reason (best-effort cleanup like ``sock.close()`` is
legitimate — say so at the site).
"""

from __future__ import annotations

import ast

from ray_tpu.devtools.lint.core import (
    FileContext,
    Rule,
    Severity,
    call_name,
    register_rule,
)

# A call whose target ends with one of these counts as "handled".
_HANDLER_TAILS = {
    "debug", "info", "warning", "warn", "error", "exception", "critical",
    "log", "print", "print_exc", "format_exc", "record_exception",
    "set_status", "record_error", "fail",
}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in ("Exception", "BaseException")
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name)
                   and e.id in ("Exception", "BaseException")
                   for e in t.elts)
    return False


def _handles(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = call_name(node)
            tail = name.rsplit(".", 1)[-1]
            if tail in _HANDLER_TAILS or tail.startswith("_log") or \
                    tail.endswith("_debug") or tail.endswith("_log"):
                return True
    return False


def _does_anything(handler: ast.ExceptHandler) -> bool:
    """False when the body is pure pass/continue/`...` — the fully
    silent swallow this rule targets. Handlers that compute a fallback
    value are a different (lesser) smell and stay out of scope.
    """
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Expr) and \
                isinstance(stmt.value, ast.Constant):
            continue  # docstring / `...`
        if isinstance(stmt, ast.Return) and (
            stmt.value is None or isinstance(stmt.value, ast.Constant)
        ):
            continue  # `return None` / `return ""` — still silent
        return True
    return False


@register_rule
class SwallowedException(Rule):
    name = "swallowed-exception"
    severity = Severity.WARNING
    description = (
        "bare/broad except whose body neither re-raises, logs, nor "
        "records to a span — failures vanish without a trace"
    )

    def check(self, ctx: FileContext):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node):
                continue
            if _does_anything(node) or _handles(node):
                continue
            kind = "bare except" if node.type is None else \
                f"except {ast.unparse(node.type)}"
            yield self.finding(
                ctx, node,
                f"`{kind}` silently swallows the error: log it, narrow "
                f"the type, re-raise — or suppress here with the reason "
                f"this is safe to ignore",
            )

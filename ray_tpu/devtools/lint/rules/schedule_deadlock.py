"""schedule-deadlock: run the pipeline schedule validator at lint time
over every (S, M, v) grid the repo declares, so a bad schedule config
fails ``ray_tpu lint`` instead of hanging a gang at 3am.

Grid sources:

* literal call sites of ``schedule_1f1b`` / ``schedule_interleaved_1f1b``
  in scanned Python (``bench.py``, ``release/*.py``, tests) — argument
  names resolve through same-function literal assignments
  (``num_stages, microbatches, virtual = 2, 8, 2``) and literal
  ``for s in (2, 4):`` loop iterables, cartesian-product style;
* structured ``schedule_grids:`` declarations on entries in
  ``release/release_tests.yaml`` — either ``{stages, microbatches,
  virtual}`` shapes or explicit per-rank ``ops`` streams for
  simulation fixtures.

Each unique grid is expanded with the REAL schedule generator and
tick-simulated by the REAL ``validate_schedule`` (no reimplementation
to drift); a raise becomes a finding at the declaring site. Certified
grids are recorded on the ProjectGraph for ``ray_tpu lint
--comm-graph`` to report.
"""

from __future__ import annotations

import ast
import os

from ray_tpu.devtools.lint.callgraph import _own_statements
from ray_tpu.devtools.lint.core import (
    FileContext,
    Finding,
    Rule,
    Severity,
    register_rule,
)

_SCHEDULE_FNS = {"schedule_1f1b", "schedule_interleaved_1f1b"}
# Simulation cost ceiling: S ranks x M*v ops each; grids above this are
# configs no release entry ships and not worth lint wall time.
_MAX_OPS = 4096
_MAX_COMBOS = 64


def validate_grid(stages: int, microbatches: int,
                  virtual: int) -> str | None:
    """Expand + simulate one grid with the real validator; returns the
    error text, or None when the grid is deadlock-free."""
    from ray_tpu.parallel.pipeline import (
        schedule_interleaved_1f1b,
        validate_schedule,
    )

    try:
        schedules = [
            schedule_interleaved_1f1b(stages, microbatches, r, virtual)
            for r in range(stages)
        ]
        validate_schedule(schedules, num_virtual=virtual)
    except ValueError as exc:
        return str(exc)
    return None


def _literal_ints(node: ast.AST) -> list[int] | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return [node.value]
    return None


def _scope_env(scope: ast.AST) -> dict[str, list[int]]:
    """name -> possible literal int values, from assignments and
    literal-iterable for loops in one function (or module) scope."""
    env: dict[str, list[int]] = {}

    def bind(name: str, values: list[int]) -> None:
        env.setdefault(name, [])
        for v in values:
            if v not in env[name]:
                env[name].append(v)

    for node in _own_statements(scope):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt, val = node.targets[0], node.value
            if isinstance(tgt, ast.Name):
                ints = _literal_ints(val)
                if ints:
                    bind(tgt.id, ints)
            elif isinstance(tgt, ast.Tuple) and \
                    isinstance(val, ast.Tuple) and \
                    len(tgt.elts) == len(val.elts):
                for t, v in zip(tgt.elts, val.elts):
                    ints = _literal_ints(v)
                    if isinstance(t, ast.Name) and ints:
                        bind(t.id, ints)
        elif isinstance(node, ast.For) and \
                isinstance(node.target, ast.Name) and \
                isinstance(node.iter, (ast.Tuple, ast.List)):
            values: list[int] = []
            for elt in node.iter.elts:
                ints = _literal_ints(elt)
                if not ints:
                    values = []
                    break
                values += ints
            if values:
                bind(node.target.id, values)
    return env


def _resolve(node: ast.AST | None, env: dict[str, list[int]],
             default: list[int] | None = None) -> list[int] | None:
    if node is None:
        return default
    ints = _literal_ints(node)
    if ints:
        return ints
    if isinstance(node, ast.Name):
        return env.get(node.id)
    return None


def _grids_from_ctx(ctx: FileContext):
    """(stages, microbatches, virtual, line) combos declared by literal
    schedule calls in one file."""
    env_cache: dict[int, dict] = {}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        tail = fn.attr if isinstance(fn, ast.Attribute) else \
            fn.id if isinstance(fn, ast.Name) else ""
        if tail not in _SCHEDULE_FNS:
            continue
        scope = ctx.enclosing_function(node) or ctx.tree
        env = env_cache.get(id(scope))
        if env is None:
            env = env_cache[id(scope)] = _scope_env(scope)
        args = node.args
        kw = {k.arg: k.value for k in node.keywords}
        s_vals = _resolve(args[0] if args else kw.get("num_stages"), env)
        m_vals = _resolve(
            args[1] if len(args) > 1 else kw.get("num_microbatches"),
            env)
        if tail == "schedule_1f1b":
            v_vals = [1]
        else:
            v_vals = _resolve(
                args[3] if len(args) > 3 else kw.get("num_virtual"),
                env, default=[1])
        if not (s_vals and m_vals and v_vals):
            continue
        combos = [
            (s, m, v)
            for s in s_vals for m in m_vals for v in v_vals
            if 0 < s and 0 < m and 0 < v and s * m * v <= _MAX_OPS
        ]
        for combo in combos[:_MAX_COMBOS]:
            yield (*combo, node.lineno)


def _entry_line(lines: list[str], name: str) -> int:
    for i, text in enumerate(lines, start=1):
        if f"name: {name}" in text:
            return i
    return 1


def _grids_from_yaml(root: str):
    """Structured grid declarations from release_tests.yaml:
    (kind, payload, yaml_relpath, line, entry_name)."""
    relpath = "release/release_tests.yaml"
    path = os.path.join(root, relpath)
    if not os.path.exists(path):
        return
    try:
        import yaml
    except ImportError:
        return
    try:
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
        entries = yaml.safe_load(text)
    except (OSError, ValueError, yaml.YAMLError):
        return  # run_all.py owns yaml schema errors; not a lint concern
    if not isinstance(entries, list):
        return
    lines = text.splitlines()
    for entry in entries:
        if not isinstance(entry, dict):
            continue
        name = str(entry.get("name", "?"))
        line = _entry_line(lines, name)
        for grid in entry.get("schedule_grids") or ():
            if not isinstance(grid, dict):
                continue
            if "ops" in grid:
                yield ("ops", grid, relpath, line, name)
            elif {"stages", "microbatches"} <= set(grid):
                yield ("shape", grid, relpath, line, name)


@register_rule
class ScheduleDeadlock(Rule):
    name = "schedule-deadlock"
    severity = Severity.ERROR
    description = ("a declared (S, M, v) pipeline grid fails the "
                   "schedule simulator — would deadlock at run time")

    def check_project(self, ctxs: list[FileContext]):
        project = ctxs[0].project if ctxs else None
        certified: list[dict] = []
        verdicts: dict[tuple, str | None] = {}

        def check(s: int, m: int, v: int) -> str | None:
            key = (s, m, v)
            if key not in verdicts:
                verdicts[key] = validate_grid(s, m, v)
            return verdicts[key]

        for ctx in ctxs:
            for s, m, v, line in _grids_from_ctx(ctx):
                error = check(s, m, v)
                certified.append({
                    "stages": s, "microbatches": m, "virtual": v,
                    "ok": error is None,
                    "source": f"{ctx.path}:{line}",
                })
                if error is not None:
                    yield Finding(
                        rule=self.name, path=ctx.path, line=line,
                        col=1, severity=self.severity,
                        message=(
                            f"schedule grid S={s} M={m} v={v} fails "
                            f"validation: {error}"
                        ),
                    )

        root = project.root if project is not None else ""
        if root:
            from ray_tpu.parallel.pipeline import validate_schedule

            for kind, grid, relpath, line, name in _grids_from_yaml(
                    root):
                if kind == "shape":
                    s = int(grid["stages"])
                    m = int(grid["microbatches"])
                    v = int(grid.get("virtual", 1))
                    if s * m * v > _MAX_OPS:
                        continue
                    error = check(s, m, v)
                    certified.append({
                        "stages": s, "microbatches": m, "virtual": v,
                        "ok": error is None,
                        "source": f"{relpath} ({name})",
                    })
                else:
                    ops = [
                        [tuple(op) for op in rank_ops]
                        for rank_ops in grid["ops"]
                    ]
                    v = int(grid.get("virtual", 1))
                    try:
                        validate_schedule(ops, num_virtual=v)
                        error = None
                    except ValueError as exc:
                        error = str(exc)
                    certified.append({
                        "stages": len(ops), "microbatches": "ops",
                        "virtual": v, "ok": error is None,
                        "source": f"{relpath} ({name})",
                    })
                if error is not None:
                    yield Finding(
                        rule=self.name, path=relpath, line=line,
                        col=1, severity=self.severity,
                        message=(
                            f"schedule_grids entry of '{name}' fails "
                            f"validation: {error}"
                        ),
                    )

        if project is not None:
            # Deduplicated record for `ray_tpu lint --comm-graph`.
            seen: set[tuple] = set()
            project.certified_grids = [
                g for g in certified
                if (key := (g["stages"], g["microbatches"],
                            g["virtual"])) not in seen
                and not seen.add(key)
            ]

"""Built-in rtlint rules. Importing this package registers them all."""

from ray_tpu.devtools.lint.rules import (  # noqa: F401
    blocking_in_async,
    comm_recorder_bypass,
    host_sync_in_step,
    lockset_order,
    non_atomic_write,
    rank_asymmetric_channel,
    rank_divergent_collective,
    schedule_deadlock,
    swallowed_exception,
    sync_inside_overlap_window,
    tag_collision,
    unmatched_p2p,
)

"""Built-in rtlint rules. Importing this package registers them all."""

from ray_tpu.devtools.lint.rules import (  # noqa: F401
    blocking_in_async,
    host_sync_in_step,
    lockset_order,
    non_atomic_write,
    rank_divergent_collective,
    swallowed_exception,
    sync_inside_overlap_window,
)

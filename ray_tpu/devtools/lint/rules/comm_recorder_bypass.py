"""comm-recorder-bypass: comm traffic invisible to the flight recorder.

ISSUE 14 made every collective and ring p2p op append a record to the
per-process flight ring (``ray_tpu.util.collective.flight``) — that is
what lets the hang doctor name the rank missing from a wedged
``(group, tag, seq)``. The recording happens in exactly one place:
``util/collective/collective.py``, where the group methods are wrapped
by ``_traced_method`` and the ring wire helpers record each mailbox
send/recv. Code that tunnels *around* that layer produces comm traffic
the watchdog can never see, so a hang there is silent again.

Two bypass shapes are flagged outside the collective module itself:

* a raw transport RPC whose method string starts with ``coll_send/``
  (the ring wire protocol) — hand-rolled sends skip the wire record;
* a subclass of the group family (``BaseGroup`` / ``RingGroup`` /
  ``XlaGroup`` / ``HierarchicalGroup``) overriding ``send`` / ``recv``
  / ``send_async`` — the ``_traced_method`` registration loop only
  wraps classes defined in ``collective.py``, so such an override
  silently sheds both the span and the flight record.

Plain ``group.send(...)`` / ``group.recv(...)`` call sites are the
blessed idiom (they ARE recorded) and are never flagged.
"""

from __future__ import annotations

import ast

from ray_tpu.devtools.lint.core import (
    FileContext,
    Rule,
    Severity,
    register_rule,
)

_WIRE_PREFIX = "coll_send"
_GROUP_BASES = {"BaseGroup", "RingGroup", "XlaGroup", "HierarchicalGroup"}
_WRAPPED_METHODS = {"send", "recv", "send_async"}
_EXEMPT_SUFFIX = "util/collective/collective.py"


def _string_head(node: ast.AST | None) -> str | None:
    """The leading literal text of a str constant or f-string, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr) and node.values:
        first = node.values[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            return first.value
    return None


def _base_name(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


@register_rule
class CommRecorderBypass(Rule):
    name = "comm-recorder-bypass"
    severity = Severity.WARNING
    description = (
        "comm traffic routed around the flight recorder (raw coll_send/ "
        "RPC or a group-family send/recv override outside "
        "collective.py) — the hang doctor cannot attribute stalls it "
        "never records"
    )

    def check(self, ctx: FileContext):
        if ctx.path.replace("\\", "/").endswith(_EXEMPT_SUFFIX):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                for arg in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    head = _string_head(arg)
                    if head is not None and head.startswith(_WIRE_PREFIX):
                        yield self.finding(
                            ctx, node,
                            f"raw `{head}…` transport RPC bypasses the "
                            "comm flight recorder — go through the "
                            "group's send/send_async so the hang doctor "
                            "can see this wire",
                        )
                        break
            elif isinstance(node, ast.ClassDef):
                bases = {_base_name(b) for b in node.bases}
                if not bases & _GROUP_BASES:
                    continue
                for item in node.body:
                    if (
                        isinstance(
                            item,
                            (ast.FunctionDef, ast.AsyncFunctionDef),
                        )
                        and item.name in _WRAPPED_METHODS
                    ):
                        yield self.finding(
                            ctx, item,
                            f"`{node.name}.{item.name}` overrides a "
                            "group wire method outside collective.py: "
                            "the _traced_method wrap (span + flight "
                            "record) only covers classes defined there, "
                            "so this override's traffic is invisible to "
                            "the hang doctor",
                        )

"""sync-inside-overlap-window: blocking the host while buckets fly.

``begin_gradient_sync`` opens an OVERLAP WINDOW: the bucketed gradient
allreduce is in flight on background threads and the host thread is
supposed to keep feeding the device (later microbatches, the next
chunk's backward). A host synchronization inside that window —
``block_until_ready()``, ``.item()``, ``float(loss)``,
``np.asarray(device_array)``, ``jax.device_get`` — or a second
BLOCKING collective (``sync_gradients*``, ``.allreduce(...)``,
``.barrier()``) stalls exactly the compute the overlap exists to hide,
silently turning the async path back into the monolithic one. The
flight recorder then shows ``comm_exposed_s`` creeping back toward
``collective_s`` with no code diff to blame.

The window closes at the fence: ``handle.result()`` / ``.fence()``.
Detection is lexical per function (source order), which matches how
the window is actually used — launch, compute, fence, step.

Scope: the training/model/parallel layers (same as host-sync-in-step).
"""

from __future__ import annotations

import ast

from ray_tpu.devtools.lint.core import (
    FileContext,
    Rule,
    Severity,
    call_name,
    register_rule,
)

_SCOPE = ("train/", "models/", "parallel/", "ops/")

_OPEN_TAILS = {"begin_gradient_sync"}
_CLOSE_TAILS = {"result", "fence", "finish_gradient_sync"}

_SYNC_TAILS = {
    "block_until_ready": "forces a device sync",
    "item": "device->host copy + sync",
    "device_get": "device->host copy + sync",
    "barrier": "blocks the host on every rank",
    "allreduce": "a second blocking collective serializes the window",
    "allreduce_sharded": "a second blocking collective serializes the window",
    "sync_gradients": "the monolithic blocking sync defeats the overlap",
    "sync_gradients_sharded": "the monolithic blocking sync defeats the overlap",
}
_SYNC_FULL = {
    "np.asarray": "materializes the device array on host",
    "numpy.asarray": "materializes the device array on host",
    "jax.device_get": "device->host copy + sync",
    "float": "scalar device->host sync",
    "int": "scalar device->host sync",
}


@register_rule
class SyncInsideOverlapWindow(Rule):
    name = "sync-inside-overlap-window"
    severity = Severity.WARNING
    description = (
        "host sync or blocking collective between begin_gradient_sync() "
        "and the fence — stalls the compute the overlap should hide"
    )

    def check(self, ctx: FileContext):
        if not ctx.in_path(*_SCOPE):
            return
        for qual, fn in ctx.functions().items():
            from ray_tpu.devtools.lint.callgraph import _own_statements

            calls = [
                n for n in _own_statements(fn) if isinstance(n, ast.Call)
            ]
            calls.sort(
                key=lambda n: (n.lineno, n.col_offset)
            )
            open_at: ast.Call | None = None
            for node in calls:
                name = call_name(node)
                tail = name.rsplit(".", 1)[-1]
                if tail in _OPEN_TAILS:
                    open_at = node
                    continue
                if tail in _CLOSE_TAILS:
                    open_at = None
                    continue
                if open_at is None:
                    continue
                why = _SYNC_FULL.get(name) or _SYNC_TAILS.get(tail)
                if why is None:
                    continue
                # float()/int() only matter on non-literal args.
                if name in ("float", "int") and (
                    not node.args
                    or isinstance(node.args[0], ast.Constant)
                ):
                    continue
                yield self.finding(
                    ctx, node,
                    f"`{name}` in `{qual}` {why} while the bucketed "
                    f"gradient sync launched on line {open_at.lineno} is "
                    f"still in flight — move it past the "
                    f"`handle.result()` fence (or fence first)",
                )

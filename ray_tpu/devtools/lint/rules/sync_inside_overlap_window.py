"""sync-inside-overlap-window: blocking the host while buckets fly.

``begin_gradient_sync`` opens an OVERLAP WINDOW: the bucketed gradient
allreduce is in flight on background threads and the host thread is
supposed to keep feeding the device (later microbatches, the next
chunk's backward). A host synchronization inside that window —
``block_until_ready()``, ``.item()``, ``float(loss)``,
``np.asarray(device_array)``, ``jax.device_get`` — or a second
BLOCKING collective (``sync_gradients*``, ``.allreduce(...)``,
``.barrier()``) stalls exactly the compute the overlap exists to hide,
silently turning the async path back into the monolithic one. The
flight recorder then shows ``comm_exposed_s`` creeping back toward
``collective_s`` with no code diff to blame.

The window closes at the fence of the HANDLE — and the handle is
tracked through aliases: ``h = begin_gradient_sync(...); g = h;
g.result()`` closes the window, while ``other_future.result()`` does
NOT (the ISSUE-12 fix: previously any ``.result()`` text closed it).
Helpers that *return* the handle (found via the whole-program
``returning_closure``) open a window at their call sites too; a helper
that returns the handle to its own caller hands off the window with
it. An alias that escapes (passed to another call) drops out of
tracking, falling back to the permissive any-fence-closes behavior.

Scope: the training/model/parallel layers (same as host-sync-in-step).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from ray_tpu.devtools.lint.callgraph import (
    _own_statements,
    owner_class_of,
)
from ray_tpu.devtools.lint.core import (
    FileContext,
    Rule,
    Severity,
    call_name,
    register_rule,
)

_SCOPE = ("train/", "models/", "parallel/", "ops/")

_OPEN_TAILS = {"begin_gradient_sync"}
_CLOSE_TAILS = {"result", "fence"}
_CLOSE_BARE = {"finish_gradient_sync"}

_SYNC_TAILS = {
    "block_until_ready": "forces a device sync",
    "item": "device->host copy + sync",
    "device_get": "device->host copy + sync",
    "barrier": "blocks the host on every rank",
    "allreduce": "a second blocking collective serializes the window",
    "allreduce_sharded": "a second blocking collective serializes the window",
    "sync_gradients": "the monolithic blocking sync defeats the overlap",
    "sync_gradients_sharded": "the monolithic blocking sync defeats the overlap",
}
_SYNC_FULL = {
    "np.asarray": "materializes the device array on host",
    "numpy.asarray": "materializes the device array on host",
    "jax.device_get": "device->host copy + sync",
    "float": "scalar device->host sync",
    "int": "scalar device->host sync",
}


@dataclass
class _Event:
    line: int
    col: int
    kind: str           # open | copy | close | escape | ret | sync
    node: ast.AST
    obj: str = ""       # alias text the event concerns
    dst: str = ""       # copy target
    why: str = ""       # sync explanation
    name: str = ""      # call name for the message

    def key(self):
        return (self.line, self.col)


def _safe_unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except (ValueError, RecursionError):
        return "<expr>"


@register_rule
class SyncInsideOverlapWindow(Rule):
    name = "sync-inside-overlap-window"
    severity = Severity.WARNING
    description = (
        "host sync or blocking collective between begin_gradient_sync() "
        "and the handle's fence — stalls the compute the overlap "
        "should hide"
    )

    def _openers(self, ctx: FileContext):
        """Helper fids that transitively return the sync handle."""
        project = ctx.project
        if project is None:
            return None, frozenset()
        helpers = getattr(project, "_handle_helpers", None)
        if helpers is None:
            helpers = project.returning_closure(_OPEN_TAILS)
            project._handle_helpers = helpers
        return project, helpers

    def _is_opener(self, name: str, ctx, project, helpers,
                   owner: str | None) -> bool:
        if name.rsplit(".", 1)[-1] in _OPEN_TAILS:
            return True
        if project is None:
            return False
        return project.resolve_call(ctx.module, owner, name) in helpers

    def check(self, ctx: FileContext):
        if not ctx.in_path(*_SCOPE):
            return
        project, helpers = self._openers(ctx)
        parents = ctx.parent_map()
        for qual, fn in ctx.functions().items():
            owner = owner_class_of(qual)
            events: list[_Event] = []
            for node in _own_statements(fn):
                if isinstance(node, ast.Assign) and \
                        len(node.targets) == 1 and \
                        isinstance(node.value, (ast.Name, ast.Attribute)):
                    events.append(_Event(
                        node.lineno, node.col_offset, "copy", node,
                        obj=_safe_unparse(node.value),
                        dst=_safe_unparse(node.targets[0]),
                    ))
                    continue
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if not name:
                    continue
                tail = name.rsplit(".", 1)[-1]
                if self._is_opener(name, ctx, project, helpers, owner):
                    # `return begin_...(...)` forwards the handle —
                    # the window belongs to the caller.
                    parent = parents.get(node)
                    if isinstance(parent, ast.Return):
                        continue
                    target = ""
                    if isinstance(parent, ast.Assign) and \
                            len(parent.targets) == 1:
                        target = _safe_unparse(parent.targets[0])
                    events.append(_Event(
                        node.lineno, node.col_offset, "open", node,
                        dst=target,
                    ))
                    continue
                if tail in _CLOSE_BARE:
                    events.append(_Event(
                        node.lineno, node.col_offset, "close", node,
                    ))
                    continue
                if tail in _CLOSE_TAILS and "." in name:
                    events.append(_Event(
                        node.lineno, node.col_offset, "close", node,
                        obj=name.rsplit(".", 1)[0],
                    ))
                    continue
                why = _SYNC_FULL.get(name) or _SYNC_TAILS.get(tail)
                if why is not None:
                    if name in ("float", "int") and (
                        not node.args
                        or isinstance(node.args[0], ast.Constant)
                    ):
                        continue
                    events.append(_Event(
                        node.lineno, node.col_offset, "sync", node,
                        why=why, name=name,
                    ))
                # Aliases handed to arbitrary calls escape tracking.
                for arg in list(node.args) + \
                        [k.value for k in node.keywords]:
                    if isinstance(arg, (ast.Name, ast.Attribute)):
                        events.append(_Event(
                            node.lineno, node.col_offset, "escape",
                            node, obj=_safe_unparse(arg),
                        ))

            window_open = False
            open_line = 0
            aliases: set[str] = set()
            loose = False   # open, but no trackable alias
            for ev in sorted(events, key=_Event.key):
                if ev.kind == "open":
                    window_open, open_line = True, ev.line
                    aliases = {ev.dst} if ev.dst else set()
                    loose = not aliases
                elif not window_open:
                    continue
                elif ev.kind == "copy":
                    if ev.obj in aliases:
                        aliases.add(ev.dst)
                    else:
                        aliases.discard(ev.dst)
                elif ev.kind == "close":
                    if not ev.obj or ev.obj in aliases or loose:
                        window_open = False
                elif ev.kind == "escape":
                    if ev.obj in aliases:
                        aliases.discard(ev.obj)
                        if not aliases:
                            loose = True
                elif ev.kind == "sync":
                    yield self.finding(
                        ctx, ev.node,
                        f"`{ev.name}` in `{qual}` {ev.why} while the "
                        f"bucketed gradient sync launched on line "
                        f"{open_line} is still in flight — move it "
                        f"past the handle's `result()`/`fence()` (or "
                        f"fence first)",
                    )

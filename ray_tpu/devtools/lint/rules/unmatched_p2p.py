"""unmatched-p2p: a p2p send whose tag skeleton no recv can match, or
a recv no send can produce — per direction x tag family.

A tag with no partner is a guaranteed hang on the host-memory backends
(``recv`` blocks until its timeout, ``send_async`` buffers forever) and
a protocol error the compiled-graph channel pre-open would reject. The
match deliberately errs generous (see ``skeletons_unify``): recvs are
searched across every group key because receiver *text* differs
legitimately between endpoints of the same runtime group — so anything
still unmatched is high-confidence dead wire.
"""

from __future__ import annotations

from ray_tpu.devtools.lint.core import (
    FileContext,
    Finding,
    Rule,
    Severity,
    register_rule,
)


@register_rule
class UnmatchedP2p(Rule):
    name = "unmatched-p2p"
    severity = Severity.ERROR
    description = ("p2p send with no skeleton-compatible recv (or "
                   "vice versa) — a guaranteed hang or dead wire")

    def check_project(self, ctxs: list[FileContext]):
        project = ctxs[0].project if ctxs else None
        if project is None:
            return
        from ray_tpu.devtools.analysis.commgraph import (
            graph_from_project,
            render_skeleton,
        )

        graph = graph_from_project(project)
        if not graph.sends and not graph.recvs:
            return
        for channel in graph.channels():
            if channel.recvs:
                continue
            s = channel.send
            yield Finding(
                rule=self.name, path=s.path, line=s.line, col=s.col,
                severity=self.severity,
                message=(
                    f"{s.method} with tag "
                    f"'{render_skeleton(s.tag)}' has no matching recv "
                    f"anywhere in the scanned program — the payload is "
                    f"never consumed (in {s.func or '<module>'})"
                ),
            )
        for r in graph.unmatched_recvs():
            yield Finding(
                rule=self.name, path=r.path, line=r.line, col=r.col,
                severity=self.severity,
                message=(
                    f"recv with tag '{render_skeleton(r.tag)}' has no "
                    f"send that could produce it — blocks until "
                    f"timeout (in {r.func or '<module>'})"
                ),
            )

"""rank-divergent-collective: a collective op under a rank-dependent branch.

Collectives are rendezvous points: *every* rank of the gang must reach
the same collective in the same order, or the gang deadlocks — or
worse, with the PR-7 quantized wire path, ranks pair mismatched
messages and training silently desyncs. A branch conditioned on
``rank`` (which differs per process) guarding a ``psum``/``allreduce``
is the canonical way to write that bug. Branching on ``world_size`` is
fine — it is uniform across the gang.

The point-to-point ops (``send``/``recv``/``p2p``) are intentionally
excluded: rank-conditional send/recv is how p2p is *supposed* to look.
"""

from __future__ import annotations

import ast
import re

from ray_tpu.devtools.lint.core import (
    FileContext,
    Rule,
    Severity,
    call_name,
    iter_calls,
    register_rule,
)

# Group-wide ops: every rank must call them.
_COLLECTIVE_TAILS = {
    "psum", "pmean", "pmax", "pmin", "allreduce", "all_reduce",
    "allgather", "all_gather", "reduce_scatter", "barrier", "broadcast",
    "allreduce_sharded", "sync_gradients", "sync_gradients_sharded",
    "hierarchical_psum", "hierarchical_pmean",
}

# Names that vary per process. `world_size`/`num_workers` are uniform
# and deliberately absent.
_RANK_NAME_RE = re.compile(
    r"(^|[._])(rank|local_rank|world_rank|node_rank|process_index|"
    r"host_id|is_coordinator|is_main|is_leader)($|[._(])"
)


def _test_is_rank_dependent(test: ast.AST) -> str | None:
    """Return the offending sub-expression text, or None if uniform."""
    for node in ast.walk(test):
        if isinstance(node, (ast.Name, ast.Attribute)):
            try:
                txt = ast.unparse(node)
            except (ValueError, RecursionError):
                continue
            if _RANK_NAME_RE.search(txt):
                return txt
        elif isinstance(node, ast.Call):
            name = call_name(node)
            if _RANK_NAME_RE.search(name):
                return name
    return None


@register_rule
class RankDivergentCollective(Rule):
    name = "rank-divergent-collective"
    severity = Severity.ERROR
    description = (
        "collective op (psum/allreduce/barrier/...) guarded by a branch "
        "conditioned on rank-derived values — gangs deadlock or silently "
        "desync when ranks disagree on collective call order"
    )

    def check(self, ctx: FileContext):
        parents = ctx.parent_map()
        for call in iter_calls(ctx.tree):
            name = call_name(call)
            tail = name.rsplit(".", 1)[-1]
            if tail not in _COLLECTIVE_TAILS:
                continue
            # Walk outward; stop at the function boundary (a whole
            # function only entered on one rank is a call-site decision
            # we cannot see locally).
            cur = parents.get(call)
            child = call
            while cur is not None and not isinstance(
                    cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if isinstance(cur, (ast.If, ast.While)):
                    # Only flag when the call lives in the *body/orelse*,
                    # not in the test expression itself.
                    if child is not cur.test:
                        offender = _test_is_rank_dependent(cur.test)
                        if offender:
                            yield self.finding(
                                ctx, call,
                                f"collective `{name}` under a branch on "
                                f"`{offender}` (line {cur.lineno}): ranks "
                                f"that skip it desync the gang — hoist "
                                f"the collective out of the branch or "
                                f"make the condition rank-uniform",
                            )
                            break
                child = cur
                cur = parents.get(cur)

"""non-atomic-write: state files written without tmp-then-rename.

A crash (or chaos SIGKILL) between ``open(path, "w")`` and the final
``write`` leaves a *torn* file at the real name — the PR-6 checkpoint
work made every manifest/marker write go tmp + ``os.replace`` so
readers see old-or-new, never garbage. This rule keeps it that way:
any write-mode ``open`` in framework code must either target a temp
that is later ``os.replace``d inside the same function, or go through
``ray_tpu._private.atomic_io``.

Streaming writers (multi-GB record files, log appends) cannot be
small-file atomic — suppress with a reason at those sites.
"""

from __future__ import annotations

import ast

from ray_tpu.devtools.lint.core import (
    FileContext,
    Rule,
    Severity,
    call_name,
    register_rule,
)

_WRITE_MODES = {"w", "wb", "wt", "w+", "wb+", "x", "xb"}


def _open_write_target(call: ast.Call) -> ast.AST | None:
    """The path expression of a write-mode builtin open(), else None."""
    if call_name(call) != "open" or not call.args:
        return None
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str) \
            and mode.value in _WRITE_MODES:
        return call.args[0]
    return None


@register_rule
class NonAtomicWrite(Rule):
    name = "non-atomic-write"
    severity = Severity.WARNING
    description = (
        "open(path, 'w') state write without the tmp-then-os.replace "
        "idiom — use ray_tpu._private.atomic_io so crashes never leave "
        "torn files"
    )

    def check(self, ctx: FileContext):
        parents = ctx.parent_map()

        # Pass 1: per enclosing function, the unparsed first args of
        # every os.replace() call.
        replaced: dict[ast.AST | None, set[str]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and \
                    call_name(node) in ("os.replace", "os.rename") \
                    and node.args:
                fn = ctx.enclosing_function(node)
                try:
                    src = ast.unparse(node.args[0])
                except (ValueError, RecursionError):
                    continue
                replaced.setdefault(fn, set()).add(src)

        # Pass 2: every write-mode open must have its path os.replace'd
        # within the same function.
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            target = _open_write_target(node)
            if target is None:
                continue
            fn = ctx.enclosing_function(node)
            try:
                target_src = ast.unparse(target)
            except (ValueError, RecursionError):
                continue
            safe = replaced.get(fn, set()) | replaced.get(None, set())
            if target_src in safe:
                continue
            # A variable holding the temp name may be replaced under a
            # different spelling; treat `X` as safe when any replace
            # source *contains* X's name (e.g. `tmp` vs `tmp`).
            if isinstance(target, ast.Name) and any(
                    target.id == s or target.id in s for s in safe):
                continue
            yield self.finding(
                ctx, node,
                f"`open({target_src}, 'w')` without a matching "
                f"`os.replace` in the same function: a crash mid-write "
                f"leaves a torn file — use atomic_io.atomic_write_* "
                f"(tmp + rename), or suppress with a reason if this is "
                f"a streaming/scratch write",
            )

"""tag-collision: two distinct sites that can emit the SAME tag on one
group — the failure mode the blake2s bucket signatures in
``bucketing.py`` exist to prevent (a colliding tag lets one in-flight
transfer consume another's payload: wrong bytes, right shape, silent).

Two tiers, both strict so the rule stays high-precision:

* cross-function: two send/launch sites whose tags are FULLY LITERAL
  and identical, on the same group key. Dynamic skeletons that merely
  *could* coincide (two ``{}/ag`` sites fed by different ``tag``
  parameters) are excluded — the exact and quantized ring paths share
  those skeletons legitimately because they are mutually exclusive.
* same-function: two distinct sites whose tag *source text* is
  identical (the holes are the same expressions, so whenever both
  sites execute, the emitted strings coincide) — the copy-paste case.

Collectives are exempt: sequential reuse of the default ``__ar`` tag
across call sites is the normal idiom; only concurrent p2p wires and
overlap launches need unique tags.
"""

from __future__ import annotations

from ray_tpu.devtools.lint.core import (
    FileContext,
    Finding,
    Rule,
    Severity,
    register_rule,
)


@register_rule
class TagCollision(Rule):
    name = "tag-collision"
    severity = Severity.ERROR
    description = ("two sites can emit the same tag on one group — "
                   "one transfer can consume another's payload")

    def check_project(self, ctxs: list[FileContext]):
        project = ctxs[0].project if ctxs else None
        if project is None:
            return
        from ray_tpu.devtools.analysis.commgraph import (
            fully_literal,
            graph_from_project,
            render_skeleton,
        )

        graph = graph_from_project(project)
        sites = [s for s in graph.sites if s.kind in ("send", "launch")]
        # Wrapper-derived sites share (path, line) with siblings from
        # the same call (exact + act-wire inner branches): one site per
        # location.
        uniq: dict[tuple, object] = {}
        for s in sites:
            uniq.setdefault((s.path, s.line, s.col, s.tag), s)
        sites = list(uniq.values())

        by_literal: dict[tuple, list] = {}
        by_src: dict[tuple, list] = {}
        for s in sites:
            if fully_literal(s.tag):
                by_literal.setdefault((s.group, s.tag), []).append(s)
            elif s.tag_src and s.func and \
                    not s.tag_src.isidentifier():
                # A bare-identifier tag (forwarded parameter) appears
                # legitimately at several sites of one helper — e.g.
                # the exact and act-wire branches of the stage
                # runner's _send. Only structured expressions
                # (f-strings, concatenations) join this tier.
                by_src.setdefault(
                    (s.path, s.func, s.group, s.tag_src), []
                ).append(s)

        for (group, tag), group_sites in sorted(by_literal.items()):
            if len(group_sites) < 2:
                continue
            group_sites.sort(key=lambda s: (s.path, s.line))
            first = group_sites[0]
            for dup in group_sites[1:]:
                yield Finding(
                    rule=self.name, path=dup.path, line=dup.line,
                    col=dup.col, severity=self.severity,
                    message=(
                        f"tag '{tag}' on group '{group or 'default'}' "
                        f"is also emitted at {first.path}:{first.line} "
                        f"— concurrent transfers would collide"
                    ),
                )
        for (path, func, _group, src), group_sites in sorted(
                by_src.items()):
            spots = sorted({(s.line, s.col) for s in group_sites})
            if len(spots) < 2:
                continue
            first_line = spots[0][0]
            for line, col in spots[1:]:
                s = next(x for x in group_sites
                         if (x.line, x.col) == (line, col))
                yield Finding(
                    rule=self.name, path=path, line=line, col=col,
                    severity=self.severity,
                    message=(
                        f"tag expression {src!r} "
                        f"('{render_skeleton(s.tag)}') duplicated at "
                        f"{path}:{first_line} in {func} — both sites "
                        f"emit identical tags when they execute"
                    ),
                )

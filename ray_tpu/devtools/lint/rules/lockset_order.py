"""lockset-order: inconsistent lock-acquisition orderings (deadlock risk).

Classic two-pass lockset analysis: pass 1 (callgraph.analyze_locks)
records every ordered pair "lock B acquired while lock A held" — via
lexical ``with`` nesting *and* one level of same-class calls made under
a lock. Pass 2 (here) flags cycles in that order graph: if one code
path takes A→B and another B→A, two threads can each hold one and wait
forever on the other.

ISSUE 12 made pass 2 whole-program: every call made while a lock is
held (``ModuleLocks.calls_under_lock``) resolves through the
ProjectGraph, so ``gang.py`` holding its registry lock while calling
into ``collective.py`` — which takes the group-table lock — produces a
cross-module edge, and the AB/BA diff runs over one global graph with
module-namespaced lock ids. Propagation stays one call level deep
(same trade-off as the class-local pass); cross-process "locks" are
leases/tokens with their own runtime protocols, still out of scope.
"""

from __future__ import annotations

from ray_tpu.devtools.lint import callgraph
from ray_tpu.devtools.lint.core import (
    FileContext,
    Finding,
    Rule,
    Severity,
    register_rule,
)


@register_rule
class LocksetOrder(Rule):
    name = "lockset-order"
    severity = Severity.ERROR
    description = (
        "two code paths acquire the same pair of locks in opposite "
        "orders — a textbook AB/BA deadlock"
    )

    def check_project(self, ctxs: list[FileContext]):
        project = ctxs[0].project if ctxs else None
        analyses: dict[str, tuple[FileContext, callgraph.ModuleLocks]] = {}
        for ctx in ctxs:
            res = callgraph.analyze_locks(ctx.tree, ctx.path)
            if res.locks:
                analyses[ctx.path] = (ctx, res)

        def ns(path: str, lock: str) -> str:
            return f"{path}:{lock}"

        by_pair: dict[tuple[str, str], callgraph.LockOrderEdge] = {}
        for path, (_ctx, res) in analyses.items():
            for e in res.edges:
                key = (ns(path, e.first), ns(path, e.second))
                by_pair.setdefault(key, e)

        if project is not None:
            mod_of: dict[str, tuple] = {
                ctx.module: (path, res)
                for path, (ctx, res) in analyses.items()
                if ctx.module
            }
            for path, (ctx, res) in analyses.items():
                if not ctx.module:
                    continue
                for cul in res.calls_under_lock:
                    owner = callgraph.owner_class_of(cul.qual)
                    fid = project.resolve_call(
                        ctx.module, owner, cul.callee)
                    if fid is None or fid[0] == ctx.module:
                        continue  # local pairs handled by pass 1
                    target = mod_of.get(fid[0])
                    if target is None:
                        continue
                    tpath, tres = target
                    for site in tres.acquired.get(fid[1], ()):
                        a = ns(path, cul.lock)
                        b = ns(tpath, site.lock)
                        if a == b:
                            continue
                        by_pair.setdefault((a, b), callgraph.LockOrderEdge(
                            a, b, path, cul.line,
                            via=(f"{cul.qual}: holds {cul.lock}, calls "
                                 f"{project.render(fid)} which takes "
                                 f"{site.lock}"),
                        ))

        reported: set[frozenset] = set()
        for (a, b), edge in sorted(by_pair.items()):
            rev = by_pair.get((b, a))
            if rev is None:
                continue
            pair = frozenset((a, b))
            if pair in reported:
                continue
            reported.add(pair)
            yield Finding(
                rule=self.name,
                path=edge.path,
                line=edge.line,
                col=1,
                severity=self.severity,
                message=(
                    f"inconsistent lock order: `{a}` -> `{b}` here "
                    f"({edge.via}) but `{b}` -> `{a}` at "
                    f"{rev.path}:{rev.line} ({rev.via}) — pick one "
                    f"global order or merge the critical sections"
                ),
            )

    # Back-compat for direct per-file use (no runner): same analysis,
    # one file.
    def check(self, ctx: FileContext):
        yield from self.check_project([ctx])

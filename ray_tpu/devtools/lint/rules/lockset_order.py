"""lockset-order: inconsistent lock-acquisition orderings (deadlock risk).

Classic two-pass lockset analysis: pass 1 (callgraph.analyze_locks)
records every ordered pair "lock B acquired while lock A held" — via
lexical ``with`` nesting *and* one level of same-class calls made under
a lock. Pass 2 (here) flags cycles in that order graph: if one code
path takes A→B and another B→A, two threads can each hold one and wait
forever on the other.

Module-local on purpose: ray_tpu keeps each subsystem's locks in one
module, and cross-process "locks" are leases/tokens with their own
protocols (checked at runtime by the chaos suite, not here).
"""

from __future__ import annotations

from ray_tpu.devtools.lint import callgraph
from ray_tpu.devtools.lint.core import (
    FileContext,
    Finding,
    Rule,
    Severity,
    register_rule,
)


@register_rule
class LocksetOrder(Rule):
    name = "lockset-order"
    severity = Severity.ERROR
    description = (
        "two code paths acquire the same pair of locks in opposite "
        "orders — a textbook AB/BA deadlock"
    )

    def check(self, ctx: FileContext):
        result = callgraph.analyze_locks(ctx.tree, ctx.path)
        if not result.edges:
            return
        # first-seen edge per ordered pair (for the report site).
        by_pair: dict[tuple[str, str], callgraph.LockOrderEdge] = {}
        for e in result.edges:
            by_pair.setdefault((e.first, e.second), e)
        reported: set[frozenset] = set()
        for (a, b), edge in sorted(by_pair.items()):
            rev = by_pair.get((b, a))
            if rev is None:
                continue
            pair = frozenset((a, b))
            if pair in reported:
                continue
            reported.add(pair)
            yield Finding(
                rule=self.name,
                path=ctx.path,
                line=edge.line,
                col=1,
                severity=self.severity,
                message=(
                    f"inconsistent lock order: `{a}` -> `{b}` here "
                    f"({edge.via}) but `{b}` -> `{a}` at line "
                    f"{rev.line} ({rev.via}) — pick one global order "
                    f"or merge the critical sections"
                ),
            )

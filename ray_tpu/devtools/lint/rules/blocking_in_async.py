"""blocking-in-async: blocking work reachable from the async RPC lane.

One ``time.sleep`` in a coroutine stalls *every* connection multiplexed
on that event loop — heartbeats miss, leases expire, and the failure
detector declares healthy nodes dead. The same goes for synchronous
subprocess spawns and unbounded file reads inside async handlers.

Scope: code reachable from an ``async def`` whose file lives in the
framework async lane (``_private/``, ``serve/_private/``,
``dashboard/``, ``data/_internal/``). Reachability rides the
whole-program callgraph, so a sync helper in ``util/`` called from a
dashboard coroutine is flagged at the helper's site. ``open()`` rides
the same transitive graph as the hard-blocking primitives (the ISSUE-9
lexical-only gap): a function reference handed to
``asyncio.to_thread(...)`` is an argument, not a call edge, so the
blessed thread-offload idiom stays silent.
"""

from __future__ import annotations

import ast

from ray_tpu.devtools.lint import callgraph
from ray_tpu.devtools.lint.core import (
    FileContext,
    Finding,
    Rule,
    Severity,
    call_name,
    register_rule,
)

_BLOCKING = {
    "time.sleep": "blocks the event loop; use `await asyncio.sleep(...)`",
    "subprocess.run": "blocks the loop; use `asyncio.create_subprocess_exec`",
    "subprocess.call": "blocks the loop; use `asyncio.create_subprocess_exec`",
    "subprocess.check_call":
        "blocks the loop; use `asyncio.create_subprocess_exec`",
    "subprocess.check_output":
        "blocks the loop; use `asyncio.create_subprocess_exec`",
    "socket.create_connection":
        "blocking dial on the loop; use `asyncio.open_connection`",
    "urllib.request.urlopen":
        "blocking HTTP on the loop; move to a thread or aiohttp",
    "requests.get": "blocking HTTP on the loop; move to a thread or aiohttp",
    "requests.post": "blocking HTTP on the loop; move to a thread or aiohttp",
    "requests.request":
        "blocking HTTP on the loop; move to a thread or aiohttp",
    "open": "sync file I/O on the event loop; use `asyncio.to_thread(...)`",
}

_SCOPE = ("_private/", "dashboard/", "data/_internal/")


@register_rule
class BlockingInAsync(Rule):
    name = "blocking-in-async"
    severity = Severity.ERROR
    description = (
        "time.sleep / sync subprocess / blocking I/O reachable from an "
        "async def in framework rpc/controller/agent/serve/dashboard code"
    )

    def check_project(self, ctxs: list[FileContext]):
        project = ctxs[0].project if ctxs else None
        if project is None:
            for ctx in ctxs:
                yield from self.check(ctx)
            return
        reach = project.async_reachable()
        for fid, info in project.functions():
            root = fid if info["async"] else reach.get(fid)
            if root is None:
                continue
            if not any(s in project.path(root) for s in _SCOPE):
                continue
            for name, line, col in info["calls"]:
                hint = _BLOCKING.get(name)
                if hint is None:
                    continue
                where = (
                    f"`async def {fid[1]}`" if root == fid
                    else (f"`{fid[1]}`, reachable from `async def "
                          f"{project.render(root)}`")
                )
                yield Finding(
                    rule=self.name, path=project.path(fid),
                    line=line, col=col + 1,
                    severity=self.severity,
                    message=f"`{name}` inside {where}: {hint}",
                )

    # Module-local fallback for contexts parsed without a runner.
    def check(self, ctx: FileContext):
        if not ctx.in_path(*_SCOPE):
            return
        functions = ctx.functions()
        reach = callgraph.async_reachable(functions)
        for qual, fn in functions.items():
            root = reach.get(qual)
            direct_async = isinstance(fn, ast.AsyncFunctionDef)
            if root is None and not direct_async:
                continue
            for node in callgraph._own_statements(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                hint = _BLOCKING.get(name)
                if hint is None:
                    continue
                where = (
                    f"`async def {qual}`" if direct_async
                    else f"`{qual}`, reachable from `async def {root}`"
                )
                yield self.finding(
                    ctx, node,
                    f"`{name}` inside {where}: {hint}",
                )

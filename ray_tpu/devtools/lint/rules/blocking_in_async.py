"""blocking-in-async: blocking work reachable from the async RPC lane.

One ``time.sleep`` in a coroutine stalls *every* connection multiplexed
on that event loop — heartbeats miss, leases expire, and the failure
detector declares healthy nodes dead. The same goes for synchronous
subprocess spawns and unbounded file reads inside async handlers.

Scope: framework async code (``_private/``, ``serve/_private/``,
``dashboard/``, ``data/_internal/``). Hard-blocking primitives
(``time.sleep``, ``subprocess.*``, blocking socket dials, ``requests``)
are flagged even when reached *transitively* through module-local sync
helpers; plain ``open()`` is only flagged lexically inside an
``async def`` (helpers that touch files have legitimate sync callers).
"""

from __future__ import annotations

import ast

from ray_tpu.devtools.lint import callgraph
from ray_tpu.devtools.lint.core import (
    FileContext,
    Rule,
    Severity,
    call_name,
    iter_calls,
    register_rule,
)

_BLOCKING = {
    "time.sleep": "blocks the event loop; use `await asyncio.sleep(...)`",
    "subprocess.run": "blocks the loop; use `asyncio.create_subprocess_exec`",
    "subprocess.call": "blocks the loop; use `asyncio.create_subprocess_exec`",
    "subprocess.check_call":
        "blocks the loop; use `asyncio.create_subprocess_exec`",
    "subprocess.check_output":
        "blocks the loop; use `asyncio.create_subprocess_exec`",
    "socket.create_connection":
        "blocking dial on the loop; use `asyncio.open_connection`",
    "urllib.request.urlopen":
        "blocking HTTP on the loop; move to a thread or aiohttp",
    "requests.get": "blocking HTTP on the loop; move to a thread or aiohttp",
    "requests.post": "blocking HTTP on the loop; move to a thread or aiohttp",
    "requests.request":
        "blocking HTTP on the loop; move to a thread or aiohttp",
}

# Only flagged lexically inside `async def` (not via the call graph).
_LEXICAL_ONLY = {
    "open": "sync file I/O on the event loop; use `asyncio.to_thread(...)`",
}

_SCOPE = ("_private/", "dashboard/", "data/_internal/")


@register_rule
class BlockingInAsync(Rule):
    name = "blocking-in-async"
    severity = Severity.ERROR
    description = (
        "time.sleep / sync subprocess / blocking I/O reachable from an "
        "async def in framework rpc/controller/agent/serve/dashboard code"
    )

    def check(self, ctx: FileContext):
        if not ctx.in_path(*_SCOPE):
            return
        functions = ctx.functions()
        reach = callgraph.async_reachable(functions)
        for qual, fn in functions.items():
            root = reach.get(qual)
            direct_async = isinstance(fn, ast.AsyncFunctionDef)
            if root is None and not direct_async:
                continue
            for node in callgraph._own_statements(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                hint = _BLOCKING.get(name)
                if hint is None and direct_async:
                    hint = _LEXICAL_ONLY.get(name)
                if hint is None:
                    continue
                where = (
                    f"`async def {qual}`" if direct_async
                    else f"`{qual}`, reachable from `async def {root}`"
                )
                yield self.finding(
                    ctx, node,
                    f"`{name}` inside {where}: {hint}",
                )

"""rtlint runner: file discovery, rule execution, baseline diff, CLI.

Programmatic entry point is :func:`run_paths`; the CLI (`ray_tpu lint`)
is :func:`main`, wired from ``ray_tpu/scripts.py``.

Exit codes: 0 clean (modulo baseline), 1 new findings or stale baseline
entries, 2 usage/internal error. A rule that *crashes* on a file is
itself reported as a finding (`rtlint-crash`) rather than taking the
whole run down — an analyzer that dies on weird-but-valid code is a
false-negative storm, which the `lint_clean` release entry gates.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from dataclasses import dataclass, field

from ray_tpu.devtools.lint.baseline import DEFAULT_BASELINE, Baseline
from ray_tpu.devtools.lint.cache import (
    DEFAULT_CACHE,
    SummaryCache,
    fingerprint_source,
)
from ray_tpu.devtools.lint.core import (
    FileContext,
    Finding,
    Severity,
    all_rules,
    assign_fingerprints,
)
from ray_tpu.devtools.lint.output import RENDERERS

_SKIP_DIRS = {"__pycache__", ".git", "node_modules", ".eggs", "build"}


def repo_root() -> str:
    """Parent of the installed ray_tpu package — the repo checkout."""
    import ray_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(
        ray_tpu.__file__)))


def iter_py_files(paths: list[str]) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(
                d for d in dirnames if d not in _SKIP_DIRS
            )
            for name in sorted(filenames):
                if name.endswith(".py"):
                    out.append(os.path.join(dirpath, name))
    return out


@dataclass
class RunResult:
    findings: list[Finding] = field(default_factory=list)   # not baselined
    baselined: list[Finding] = field(default_factory=list)
    stale: list[dict] = field(default_factory=list)
    suppressed: int = 0
    stats: dict = field(default_factory=dict)
    project: object = None          # callgraph.ProjectGraph of the run

    @property
    def exit_code(self) -> int:
        return 1 if (self.findings or self.stale) else 0


def build_project(
    ctxs: list[FileContext], root: str, cache: SummaryCache
):
    """Whole-program layer: per-file summaries (callgraph + comm sites)
    through the fingerprint cache, assembled into one ProjectGraph that
    every FileContext shares."""
    from ray_tpu.devtools.analysis import commgraph
    from ray_tpu.devtools.lint import callgraph

    project = callgraph.ProjectGraph(root=root)
    comm_sites: list[dict] = []
    for ctx in ctxs:
        ctx.fingerprint = fingerprint_source(ctx.source)
        ctx.module = callgraph.module_name(ctx.path) or ""
        summary = cache.get(ctx.path, ctx.fingerprint)
        if summary is None:
            summary = {
                "callgraph": callgraph.summarize_module(
                    ctx.tree, ctx.path),
                "comm": commgraph.extract_sites(ctx.tree, ctx.path),
            }
            cache.put(ctx.path, ctx.fingerprint, summary)
        project.add_summary(ctx.path, summary["callgraph"])
        comm_sites.extend(summary["comm"])
    project.comm_sites = comm_sites
    for ctx in ctxs:
        ctx.project = project
    return project


def run_paths(
    paths: list[str],
    *,
    root: str | None = None,
    select: set[str] | None = None,
    disable: set[str] | None = None,
    baseline: Baseline | None = None,
    cache_path: str | None = None,
    use_cache: bool = True,
) -> RunResult:
    root = root or repo_root()
    rule_classes = all_rules()
    active = {
        name: cls
        for name, cls in rule_classes.items()
        if (select is None or name in select)
        and (disable is None or name not in disable)
    }
    start = time.perf_counter()
    ctxs: list[FileContext] = []
    parse_errors: list[Finding] = []
    for abspath in iter_py_files(paths):
        rel = os.path.relpath(abspath, root).replace(os.sep, "/")
        try:
            with open(abspath, encoding="utf-8", errors="replace") as fh:
                source = fh.read()
            ctxs.append(FileContext.parse(rel, source))
        except SyntaxError as exc:
            parse_errors.append(Finding(
                rule="rtlint-parse", path=rel,
                line=exc.lineno or 1, col=(exc.offset or 0) + 1,
                severity=Severity.ERROR,
                message=f"file does not parse: {exc.msg}",
            ))

    if use_cache and cache_path is None:
        cache_path = os.path.join(root, DEFAULT_CACHE)
    cache = SummaryCache.load(cache_path if use_cache else None)
    project = build_project(ctxs, root, cache)
    cache.save()

    raw: list[Finding] = list(parse_errors)
    crashes = 0
    for name, cls in sorted(active.items()):
        rule = cls()
        try:
            raw.extend(rule.check_project(ctxs))
        except Exception as exc:  # one broken rule must not kill the gate
            crashes += 1
            raw.append(Finding(
                rule="rtlint-crash", path="<analyzer>", line=1, col=1,
                severity=Severity.ERROR,
                message=f"rule {name} crashed: {type(exc).__name__}: {exc}",
            ))

    # Inline suppressions.
    by_path = {c.path: c for c in ctxs}
    kept: list[Finding] = []
    suppressed = 0
    for f in raw:
        ctx = by_path.get(f.path)
        if ctx is not None and ctx.suppressions.is_suppressed(
                f.rule, f.line):
            suppressed += 1
            continue
        kept.append(f)

    assign_fingerprints(kept, {c.path: c.lines for c in ctxs})

    baseline = baseline or Baseline()
    new, matched, stale = baseline.split(kept)
    stats = {
        "files": len(ctxs),
        "rules": len(active),
        "rule_names": sorted(active),
        "suppressed_inline": suppressed,
        "rule_crashes": crashes,
        "cache_hits": cache.hits,
        "cache_misses": cache.misses,
        "comm_sites": len(getattr(project, "comm_sites", ())),
        "wall_s": round(time.perf_counter() - start, 3),
    }
    return RunResult(findings=new, baselined=matched, stale=stale,
                     suppressed=suppressed, stats=stats,
                     project=project)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def add_lint_arguments(p: argparse.ArgumentParser) -> None:
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: the ray_tpu "
                        "package + release/ in this checkout)")
    p.add_argument("--format", choices=sorted(RENDERERS),
                   default="human")
    p.add_argument("--out", default=None,
                   help="write the report to a file (atomic) instead "
                        "of stdout")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: <repo>/"
                        f"{DEFAULT_BASELINE} when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept all current findings into the baseline "
                        "(existing justifications are preserved; new "
                        "entries get a TODO you must fill in)")
    p.add_argument("--prune-baseline", action="store_true",
                   help="rewrite the baseline keeping only entries that "
                        "still match (justifications preserved); stale "
                        "entries stop failing the run")
    p.add_argument("--select", default=None,
                   help="comma-separated rule names to run exclusively")
    p.add_argument("--disable", default=None,
                   help="comma-separated rule names to skip")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--comm-graph", action="store_true",
                   help="print the communication-protocol certification "
                        "summary (channel graph + schedule grids)")
    p.add_argument("--comm-graph-out", default=None, metavar="FILE",
                   help="export the channel graph (.dot or .json by "
                        "extension); implies --comm-graph")
    p.add_argument("--no-cache", action="store_true",
                   help="skip the incremental summary cache "
                        "(.rtlint-cache.json)")


def default_paths(root: str) -> list[str]:
    paths = [os.path.join(root, "ray_tpu")]
    for extra in ("release", "bench.py"):
        cand = os.path.join(root, extra)
        if os.path.exists(cand):
            paths.append(cand)
    return paths


def _emit_comm_graph(result: RunResult, out: str | None) -> None:
    """Print the protocol-certification summary and optionally export
    the channel graph (DOT for graphviz, JSON otherwise)."""
    from ray_tpu.devtools.analysis.commgraph import graph_from_project

    graph = graph_from_project(result.project)
    channels = graph.channels()
    unmatched = [c for c in channels if not c.recvs]
    orphans = graph.unmatched_recvs()
    print(f"comm-graph: {len(graph.sites)} sites "
          f"({len(graph.sends)} send / {len(graph.recvs)} recv), "
          f"{len(channels)} channels, "
          f"{len(unmatched)} unmatched send(s), "
          f"{len(orphans)} orphan recv(s)")
    grids = getattr(result.project, "certified_grids", None)
    if grids is None:
        print("comm-graph: schedule grids not checked "
              "(schedule-deadlock rule disabled)")
    else:
        ok = [g for g in grids if g["ok"]]
        bad = [g for g in grids if not g["ok"]]
        desc = ", ".join(
            f"S={g['stages']}xM={g['microbatches']}xv={g['virtual']}"
            for g in ok
        ) or "none declared"
        print(f"comm-graph: {len(ok)} schedule grid(s) certified "
              f"deadlock-free ({desc})"
              + (f"; {len(bad)} FAILED" if bad else ""))
    if out:
        from ray_tpu._private.atomic_io import atomic_write_text

        text = graph.to_dot() if out.endswith(".dot") else \
            json.dumps(graph.to_json(), indent=2) + "\n"
        atomic_write_text(out, text)
        print(f"comm-graph: exported to {out}")


def cmd_lint(args: argparse.Namespace) -> int:
    root = repo_root()
    if args.list_rules:
        for name, cls in sorted(all_rules().items()):
            print(f"{name:28s} {cls.severity:8s} {cls.description}")
        return 0
    paths = [os.path.abspath(p) for p in args.paths] or \
        default_paths(root)
    for p in paths:
        if not os.path.exists(p):
            print(f"rtlint: no such path: {p}", file=sys.stderr)
            return 2

    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)
    baseline = Baseline() if args.no_baseline else \
        Baseline.load(baseline_path)

    select = set(args.select.split(",")) if args.select else None
    disable = set(args.disable.split(",")) if args.disable else None
    unknown = (set() if select is None else select - set(all_rules())) \
        | (set() if disable is None else disable - set(all_rules()))
    if unknown:
        print(f"rtlint: unknown rule(s): {', '.join(sorted(unknown))}",
              file=sys.stderr)
        return 2

    result = run_paths(paths, root=root, select=select, disable=disable,
                       baseline=baseline,
                       use_cache=not getattr(args, "no_cache", False))

    if args.prune_baseline:
        kept = result.baselined
        removed = len(result.stale)
        baseline.save(baseline_path, kept)
        print(f"rtlint: baseline pruned — {removed} stale entr"
              f"{'y' if removed == 1 else 'ies'} removed, "
              f"{len(kept)} kept at {baseline_path}")
        result.stale = []

    if args.comm_graph or args.comm_graph_out:
        _emit_comm_graph(result, args.comm_graph_out)

    if args.write_baseline:
        accepted = result.findings + result.baselined
        baseline.save(baseline_path, accepted)
        print(f"rtlint: baseline written to {baseline_path} "
              f"({len(accepted)} entries) — fill in every TODO "
              f"justification before committing")
        return 0

    text = RENDERERS[args.format](
        result.findings, result.baselined, result.stale, result.stats
    )
    if args.out:
        from ray_tpu._private.atomic_io import atomic_write_text

        atomic_write_text(args.out, text + "\n")
        if args.format == "human" or result.findings or result.stale:
            print(f"rtlint: report written to {args.out} "
                  f"({len(result.findings)} new finding(s))")
    else:
        print(text)
    return result.exit_code


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="rtlint")
    add_lint_arguments(parser)
    return cmd_lint(parser.parse_args(argv))


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())

"""rtlint output formats: human (default), JSON, and SARIF 2.1.0.

SARIF is the interchange format CI systems (GitHub code scanning,
Gerrit checks) ingest natively — `ci/run_lint.sh` uploads it as the
build artifact so findings annotate the diff, not a log file.
"""

from __future__ import annotations

import json

from ray_tpu.devtools.lint.core import Finding, Severity, all_rules

_SARIF_LEVEL = {Severity.ERROR: "error", Severity.WARNING: "warning"}


def render_human(new: list[Finding], baselined: list[Finding],
                 stale: list[dict], stats: dict) -> str:
    out = []
    for f in sorted(new, key=Finding.sort_key):
        out.append(
            f"{f.path}:{f.line}:{f.col}: {f.severity}: "
            f"[{f.rule}] {f.message}"
        )
    if stale:
        out.append("")
        for e in stale:
            out.append(
                f"stale baseline entry: {e['rule']} @ {e['path']} "
                f"({e['fingerprint']}) — finding is gone, prune it"
            )
    out.append("")
    out.append(
        f"rtlint: {stats['files']} files, {stats['rules']} rules, "
        f"{len(new)} new finding(s), {len(baselined)} baselined, "
        f"{len(stale)} stale baseline entr(y/ies)"
    )
    return "\n".join(out)


def render_json(new: list[Finding], baselined: list[Finding],
                stale: list[dict], stats: dict) -> str:
    return json.dumps({
        "tool": "rtlint",
        "stats": stats,
        "findings": [f.to_dict() for f in sorted(new, key=Finding.sort_key)],
        "baselined": [
            f.to_dict() for f in sorted(baselined, key=Finding.sort_key)
        ],
        "stale_baseline_entries": stale,
    }, indent=2)


def render_sarif(new: list[Finding], baselined: list[Finding],
                 stale: list[dict], stats: dict) -> str:
    rules_meta = [
        {
            "id": name,
            "shortDescription": {"text": cls.description},
            "defaultConfiguration": {
                "level": _SARIF_LEVEL[cls.severity],
            },
        }
        for name, cls in sorted(all_rules().items())
    ]
    results = []
    for f in sorted(new, key=Finding.sort_key):
        results.append({
            "ruleId": f.rule,
            "level": _SARIF_LEVEL[f.severity],
            "message": {"text": f.message},
            "partialFingerprints": {"rtlint/v1": f.fingerprint},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {
                        "startLine": f.line, "startColumn": f.col,
                    },
                },
            }],
        })
    sarif = {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "rtlint",
                    "informationUri":
                        "docs/devtools.md",
                    "rules": rules_meta,
                },
            },
            "results": results,
            "properties": {"stats": stats,
                           "baselined": len(baselined),
                           "stale_baseline_entries": len(stale)},
        }],
    }
    return json.dumps(sarif, indent=2)


RENDERERS = {
    "human": render_human,
    "json": render_json,
    "sarif": render_sarif,
}

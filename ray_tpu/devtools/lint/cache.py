"""Fingerprint-keyed incremental cache for per-file analysis summaries.

The whole-program passes (ProjectGraph import resolution, commgraph
communication-site extraction) cost one extra AST walk per file on top
of the parse the rules already need. This cache stores each file's
extracted summary keyed by a sha1 of its CONTENT, so an unchanged file
costs one hash instead of one walk — the full-repo lint in CI stays
within its wall-time budget as the analysis suite grows (the ISSUE-12
acceptance bound: ≤ 2x the pre-commgraph run).

The cache is a plain JSON file at ``<repo>/.rtlint-cache.json`` (git-
ignored); a missing, torn, or version-skewed cache simply means a cold
run. Entries for files that left the scan set are dropped on save, so
the file tracks the checkout instead of growing without bound.
"""

from __future__ import annotations

import hashlib
import json
import os

# Bump when the summary schema changes — a stale schema must miss, not
# feed the graph malformed entries.
CACHE_VERSION = 2

DEFAULT_CACHE = ".rtlint-cache.json"


def fingerprint_source(source: str) -> str:
    return hashlib.sha1(source.encode("utf-8", "replace")).hexdigest()


class SummaryCache:
    def __init__(self, path: str | None = None):
        self.path = path
        self.entries: dict[str, dict] = {}  # relpath -> {fp, summary}
        self.hits = 0
        self.misses = 0
        self._touched: set[str] = set()

    @classmethod
    def load(cls, path: str | None) -> "SummaryCache":
        cache = cls(path=path)
        if not path or not os.path.exists(path):
            return cache
        try:
            with open(path, encoding="utf-8") as fh:
                data = json.load(fh)
            if data.get("version") == CACHE_VERSION:
                cache.entries = data.get("files", {})
        except (OSError, ValueError):
            pass  # torn/corrupt cache == cold run
        return cache

    def get(self, relpath: str, fingerprint: str) -> dict | None:
        self._touched.add(relpath)
        entry = self.entries.get(relpath)
        if entry and entry.get("fp") == fingerprint:
            self.hits += 1
            return entry["summary"]
        self.misses += 1
        return None

    def put(self, relpath: str, fingerprint: str, summary: dict) -> None:
        self._touched.add(relpath)
        self.entries[relpath] = {"fp": fingerprint, "summary": summary}

    def save(self) -> None:
        if not self.path:
            return
        files = {
            rel: entry
            for rel, entry in self.entries.items()
            if rel in self._touched
        }
        try:
            from ray_tpu._private.atomic_io import atomic_write_json

            atomic_write_json(
                self.path, {"version": CACHE_VERSION, "files": files}
            )
        except OSError:
            pass  # read-only checkout: lint still works, just cold

"""rtlint core: findings, the rule registry, and per-file analysis context.

Design mirrors what large distributed codebases run in review (custom
clang-tidy / ErrorProne style): every rule is a small visitor over a
shared parsed context, findings carry *content-based* fingerprints so a
committed baseline survives line drift, and inline suppressions are
first-class so intentional exceptions are documented where they live.
"""

from __future__ import annotations

import ast
import hashlib
import re
from dataclasses import dataclass, field
from typing import Iterable, Iterator


class Severity:
    ERROR = "error"
    WARNING = "warning"

    ORDER = {ERROR: 0, WARNING: 1}


@dataclass
class Finding:
    rule: str
    path: str          # repo-relative, forward slashes
    line: int          # 1-indexed
    col: int
    severity: str
    message: str
    # Filled by the runner: sha1 over (rule, path, normalized source line,
    # occurrence index among identical lines) — stable across unrelated
    # edits elsewhere in the file.
    fingerprint: str = ""

    def sort_key(self):
        return (self.path, self.line, self.col, self.rule)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "severity": self.severity,
            "message": self.message,
            "fingerprint": self.fingerprint,
        }


# ---------------------------------------------------------------------------
# Suppressions
#
#   x = foo()  # rtlint: disable=rule-a,rule-b - reason text
#   # rtlint: disable=rule-a - reason          (suppresses the next line)
#   # rtlint: disable-file=rule-a - reason     (suppresses the whole file)
#
# The free-form reason after the rule list is *expected*: a suppression
# is a documented decision, not an escape hatch.
# ---------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(r"#\s*rtlint:\s*disable(-file)?=([\w\-,]+)")


class Suppressions:
    def __init__(self, lines: list[str]):
        self.file_wide: set[str] = set()
        # line number -> set of rule names suppressed on that line
        self.by_line: dict[int, set[str]] = {}
        for i, text in enumerate(lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = {r.strip() for r in m.group(2).split(",") if r.strip()}
            if m.group(1):  # disable-file
                self.file_wide |= rules
                continue
            self.by_line.setdefault(i, set()).update(rules)
            # A standalone comment line suppresses the next source line.
            if text.lstrip().startswith("#"):
                self.by_line.setdefault(i + 1, set()).update(rules)

    def is_suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_wide or "all" in self.file_wide:
            return True
        rules = self.by_line.get(line, ())
        return rule in rules or "all" in rules


# ---------------------------------------------------------------------------
# Per-file context shared by all rules (parse once, analyze many).
# ---------------------------------------------------------------------------

@dataclass
class FileContext:
    path: str                       # repo-relative
    source: str
    tree: ast.Module
    lines: list[str] = field(default_factory=list)
    suppressions: Suppressions = None  # type: ignore[assignment]
    # Whole-program layer, attached by the runner before rules execute:
    # ``module`` is the dotted module name for files inside the ray_tpu
    # package ('' otherwise), ``fingerprint`` keys the incremental
    # summary cache, ``project`` is the shared callgraph.ProjectGraph
    # (carries the commgraph site list as ``project.comm_sites``). Rules
    # must tolerate ``project is None`` — unit tests parse files
    # directly without a runner.
    module: str = ""
    fingerprint: str = ""
    project: object = None
    # lazily-built shared analyses (see callgraph.py)
    _functions: dict = None         # type: ignore[assignment]
    _parents: dict = None           # type: ignore[assignment]

    @classmethod
    def parse(cls, path: str, source: str) -> "FileContext":
        tree = ast.parse(source)
        lines = source.splitlines()
        ctx = cls(path=path, source=source, tree=tree, lines=lines,
                  suppressions=Suppressions(lines))
        return ctx

    # -- shared analyses ------------------------------------------------

    def functions(self) -> dict:
        """Qualified name -> (Async)FunctionDef for every def in the file.

        Qualified as ``ClassName.method`` for methods, bare name for
        module-level functions, ``outer.inner`` for nested defs.
        """
        if self._functions is None:
            from ray_tpu.devtools.lint import callgraph

            self._functions = callgraph.collect_functions(self.tree)
        return self._functions

    def parent_map(self) -> dict:
        """ast node -> parent node, for lexical-enclosure queries."""
        if self._parents is None:
            parents: dict = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    parents[child] = node
            self._parents = parents
        return self._parents

    def enclosing_function(self, node: ast.AST):
        """Nearest enclosing (Async)FunctionDef, or None at module level."""
        parents = self.parent_map()
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = parents.get(cur)
        return None

    def in_path(self, *fragments: str) -> bool:
        """True when any fragment appears in the repo-relative path."""
        return any(frag in self.path for frag in fragments)


# ---------------------------------------------------------------------------
# Rule registry
# ---------------------------------------------------------------------------

class Rule:
    """Base class. Subclasses set ``name``/``severity``/``description``
    and implement ``check(ctx) -> Iterable[Finding]`` (per-file) or
    ``check_project(ctxs) -> Iterable[Finding]`` for cross-file passes.
    """

    name: str = ""
    severity: str = Severity.WARNING
    description: str = ""

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def check_project(
        self, ctxs: list[FileContext]
    ) -> Iterable[Finding]:
        for ctx in ctxs:
            yield from self.check(ctx)

    def finding(self, ctx: FileContext, node: ast.AST,
                message: str) -> Finding:
        return Finding(
            rule=self.name,
            path=ctx.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0) + 1,
            severity=self.severity,
            message=message,
        )


_REGISTRY: dict[str, type] = {}


def register_rule(cls: type) -> type:
    assert cls.name and cls.name not in _REGISTRY, cls
    _REGISTRY[cls.name] = cls
    return cls


def all_rules() -> dict[str, type]:
    """name -> rule class, importing the built-in rule modules once."""
    from ray_tpu.devtools.lint import rules as _rules  # noqa: F401

    return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# Fingerprints
# ---------------------------------------------------------------------------

_WS_RE = re.compile(r"\s+")


def assign_fingerprints(findings: list[Finding],
                        sources: dict[str, list[str]]) -> None:
    """Content-based identity: hash of rule + path + the normalized text
    of the flagged line + its occurrence index among identical
    (rule, path, line-text) findings. Line *numbers* are deliberately
    excluded so baselines survive edits elsewhere in the file.
    """
    seen: dict[tuple, int] = {}
    for f in sorted(findings, key=Finding.sort_key):
        lines = sources.get(f.path, [])
        text = lines[f.line - 1] if 0 < f.line <= len(lines) else ""
        norm = _WS_RE.sub(" ", text).strip()
        key = (f.rule, f.path, norm)
        idx = seen.get(key, 0)
        seen[key] = idx + 1
        raw = f"{f.rule}|{f.path}|{norm}|{idx}".encode()
        f.fingerprint = hashlib.sha1(raw).hexdigest()[:16]


def iter_calls(node: ast.AST) -> Iterator[ast.Call]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            yield sub


def call_name(call: ast.Call) -> str:
    """Dotted name of a call target, '' when not a simple name/attribute
    chain (subscripts, calls-of-calls)."""
    parts: list[str] = []
    cur: ast.AST = call.func
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    elif parts:
        parts.append("<expr>")
    else:
        return ""
    return ".".join(reversed(parts))

"""CFG-lite interprocedural helpers: module-local call graph, async
reachability, and the two-pass lockset analysis.

Deliberately *module-local*: ray_tpu's hazard surfaces (rpc lane,
controller, node agent, serve internals) each live in one module, so a
per-module graph catches the real bugs without whole-program aliasing —
the same scoping trade-off clang-tidy's bugprone-* checks make.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterator

from ray_tpu.devtools.lint.core import call_name


def collect_functions(tree: ast.Module) -> dict[str, ast.AST]:
    """Qualified name -> def node for every function in the module."""
    out: dict[str, ast.AST] = {}

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                out.setdefault(qual, child)
                visit(child, qual + ".")
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.")
            else:
                visit(child, prefix)

    visit(tree, "")
    return out


def _own_statements(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body, NOT descending into nested defs (their
    bodies execute on *their* call, not this one)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def local_callees(fn: ast.AST, functions: dict[str, ast.AST],
                  owner_class: str | None) -> set[str]:
    """Qualified names of module-local functions this function calls.

    ``self.m()`` / ``cls.m()`` resolve against the owning class;
    ``name()`` resolves to a module-level def of that name.
    """
    out: set[str] = set()
    for node in _own_statements(fn):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if not name:
            continue
        head, _, tail = name.partition(".")
        if head in ("self", "cls") and tail and owner_class:
            cand = f"{owner_class}.{tail}"
            if cand in functions:
                out.add(cand)
        elif name in functions:
            out.add(name)
    return out


def owner_class_of(qual: str) -> str | None:
    """'Cls.method' -> 'Cls'; bare functions -> None."""
    head, _, _tail = qual.rpartition(".")
    return head or None


def async_reachable(functions: dict[str, ast.AST]) -> dict[str, str]:
    """Map qualified-name -> the async entry point it is reachable from.

    Seeds every ``async def``; propagates over module-local *sync* calls
    (an awaited async callee runs on the loop too, but is flagged at its
    own seed). Value is the root async function's qualified name, for
    diagnostics.
    """
    reach: dict[str, str] = {}
    work: list[str] = []
    for qual, node in functions.items():
        if isinstance(node, ast.AsyncFunctionDef):
            reach[qual] = qual
            work.append(qual)
    while work:
        cur = work.pop()
        node = functions[cur]
        for callee in local_callees(node, functions, owner_class_of(cur)):
            if callee in reach:
                continue
            callee_node = functions[callee]
            if isinstance(callee_node, ast.AsyncFunctionDef):
                continue  # its own seed
            reach[callee] = reach[cur]
            work.append(callee)
    return reach


# ---------------------------------------------------------------------------
# Lockset analysis (two-pass)
# ---------------------------------------------------------------------------

_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "asyncio.Lock", "asyncio.Condition",
}


@dataclass
class LockSite:
    lock: str       # canonical lock id, e.g. "Controller.self._lock"
    line: int
    node: ast.AST


@dataclass
class LockOrderEdge:
    first: str
    second: str
    path: str
    line: int       # acquisition site of ``second`` while ``first`` held
    via: str        # human-readable chain, e.g. "A.f -> with a -> with b"


@dataclass
class ModuleLocks:
    """Pass 1 result: the module's named locks + every ordered pair."""
    locks: set[str] = field(default_factory=set)
    edges: list[LockOrderEdge] = field(default_factory=list)


def _lock_names(tree: ast.Module) -> set[str]:
    """Canonical ids of every variable/attribute assigned a lock ctor.

    ``self._lock = threading.Lock()`` inside class C -> ``C.self._lock``;
    module-level ``_LOCK = threading.Lock()`` -> ``_LOCK``.
    """
    names: set[str] = set()

    def canon(target: ast.AST, cls: str | None) -> str | None:
        try:
            txt = ast.unparse(target)
        except (ValueError, RecursionError):  # unparse of odd targets
            return None
        if cls and txt.startswith("self."):
            return f"{cls}.{txt}"
        if "." in txt and not txt.startswith("self."):
            return None  # foreign-object attr: not ours to track
        return txt if not txt.startswith("self.") else None

    def visit(node: ast.AST, cls: str | None) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                visit(child, child.name)
                continue
            if isinstance(child, ast.Assign):
                val = child.value
                if isinstance(val, ast.Call) and \
                        call_name(val) in _LOCK_CTORS:
                    for tgt in child.targets:
                        c = canon(tgt, cls)
                        if c:
                            names.add(c)
            visit(child, cls)

    visit(tree, None)
    return names


def _as_lock(expr: ast.AST, cls: str | None, locks: set[str]) -> str | None:
    """Resolve a with-item / .acquire() receiver to a canonical lock id."""
    try:
        txt = ast.unparse(expr)
    except (ValueError, RecursionError):
        return None
    if cls and txt.startswith("self."):
        cand = f"{cls}.{txt}"
        return cand if cand in locks else None
    return txt if txt in locks else None


def analyze_locks(tree: ast.Module, path: str) -> ModuleLocks:
    """Two-pass lockset: (1) find lock objects and record, per function,
    the ordered pairs of nested acquisitions — including one level of
    same-class calls made while a lock is held; (2) callers diff the
    edge set for inconsistent orderings (see the lockset-order rule).
    """
    result = ModuleLocks(locks=_lock_names(tree))
    if not result.locks:
        return result
    functions = collect_functions(tree)

    # Locks acquired anywhere inside each function (for call propagation).
    acquired_in: dict[str, list[LockSite]] = {}
    for qual, fn in functions.items():
        cls = owner_class_of(qual)
        sites: list[LockSite] = []
        for node in _own_statements(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lock = _as_lock(item.context_expr, cls, result.locks)
                    if lock:
                        sites.append(LockSite(lock, node.lineno, node))
            elif isinstance(node, ast.Call) and \
                    call_name(node).endswith(".acquire"):
                recv = node.func.value  # type: ignore[attr-defined]
                lock = _as_lock(recv, cls, result.locks)
                if lock:
                    sites.append(LockSite(lock, node.lineno, node))
        acquired_in[qual] = sites

    def walk_holding(node: ast.AST, held: list[str], qual: str,
                     cls: str | None) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, (ast.With, ast.AsyncWith)):
                inner = [
                    _as_lock(i.context_expr, cls, result.locks)
                    for i in child.items
                ]
                inner = [l for l in inner if l]
                for lock in inner:
                    for h in held:
                        if h != lock:
                            result.edges.append(LockOrderEdge(
                                h, lock, path, child.lineno,
                                via=f"{qual}: with {h} -> with {lock}",
                            ))
                walk_holding(child, held + inner, qual, cls)
                continue
            if isinstance(child, ast.Call) and held:
                name = call_name(child)
                head, _, tail = name.partition(".")
                callee = None
                if head in ("self", "cls") and tail and cls and \
                        f"{cls}.{tail}" in functions:
                    callee = f"{cls}.{tail}"
                elif name in functions:
                    callee = name
                if callee:
                    for site in acquired_in.get(callee, ()):
                        for h in held:
                            if h != site.lock:
                                result.edges.append(LockOrderEdge(
                                    h, site.lock, path, site.line,
                                    via=(f"{qual}: holds {h}, calls "
                                         f"{callee} which takes "
                                         f"{site.lock}"),
                                ))
            walk_holding(child, held, qual, cls)

    for qual, fn in functions.items():
        walk_holding(fn, [], qual, owner_class_of(qual))
    return result

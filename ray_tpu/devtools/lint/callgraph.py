"""CFG-lite interprocedural helpers: the module-local call graph, the
whole-program :class:`ProjectGraph`, async reachability, and the
two-pass lockset analysis.

ISSUE 9 shipped the module-local half (per-module functions + callees —
the clang-tidy scoping trade-off). ISSUE 12 adds the whole-program
layer: import resolution across the ``ray_tpu`` package turns every
``from x import f`` / ``import x as m; m.f()`` call into a cross-module
edge, so reachability rules (blocking-in-async, lockset-order,
sync-inside-overlap-window) follow a call from ``stage_runner.py`` into
``overlap.py`` into ``collective.py``. Per-file summaries are
fingerprint-keyed and cached (see :mod:`cache`), so a full-repo lint
only re-extracts files whose content changed.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterator

from ray_tpu.devtools.lint.core import call_name


def collect_functions(tree: ast.Module) -> dict[str, ast.AST]:
    """Qualified name -> def node for every function in the module."""
    out: dict[str, ast.AST] = {}

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                out.setdefault(qual, child)
                visit(child, qual + ".")
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.")
            else:
                visit(child, prefix)

    visit(tree, "")
    return out


def _own_statements(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body, NOT descending into nested defs (their
    bodies execute on *their* call, not this one)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def local_callees(fn: ast.AST, functions: dict[str, ast.AST],
                  owner_class: str | None) -> set[str]:
    """Qualified names of module-local functions this function calls.

    ``self.m()`` / ``cls.m()`` resolve against the owning class;
    ``name()`` resolves to a module-level def of that name.
    """
    out: set[str] = set()
    for node in _own_statements(fn):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if not name:
            continue
        head, _, tail = name.partition(".")
        if head in ("self", "cls") and tail and owner_class:
            cand = f"{owner_class}.{tail}"
            if cand in functions:
                out.add(cand)
        elif name in functions:
            out.add(name)
    return out


def owner_class_of(qual: str) -> str | None:
    """'Cls.method' -> 'Cls'; bare functions -> None."""
    head, _, _tail = qual.rpartition(".")
    return head or None


def async_reachable(functions: dict[str, ast.AST]) -> dict[str, str]:
    """Map qualified-name -> the async entry point it is reachable from.

    Seeds every ``async def``; propagates over module-local *sync* calls
    (an awaited async callee runs on the loop too, but is flagged at its
    own seed). Value is the root async function's qualified name, for
    diagnostics.
    """
    reach: dict[str, str] = {}
    work: list[str] = []
    for qual, node in functions.items():
        if isinstance(node, ast.AsyncFunctionDef):
            reach[qual] = qual
            work.append(qual)
    while work:
        cur = work.pop()
        node = functions[cur]
        for callee in local_callees(node, functions, owner_class_of(cur)):
            if callee in reach:
                continue
            callee_node = functions[callee]
            if isinstance(callee_node, ast.AsyncFunctionDef):
                continue  # its own seed
            reach[callee] = reach[cur]
            work.append(callee)
    return reach


# ---------------------------------------------------------------------------
# Whole-program callgraph (ISSUE 12)
# ---------------------------------------------------------------------------

def module_name(relpath: str) -> str | None:
    """Dotted module name of a repo-relative ``.py`` path.

    ``ray_tpu/util/gang.py`` -> ``ray_tpu.util.gang``;
    ``ray_tpu/data/__init__.py`` -> ``ray_tpu.data``. Top-level scripts
    (``bench.py``) map to their bare stem.
    """
    if not relpath.endswith(".py"):
        return None
    parts = relpath[:-3].split("/")
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) if parts else None


def summarize_module(tree: ast.Module, relpath: str) -> dict:
    """The JSON-serializable per-file summary the ProjectGraph is built
    from — and the unit the fingerprint-keyed cache stores. Everything
    reachability rules need lives here, so a cache hit skips the whole
    extraction walk:

    * ``functions``: qual -> {async, line, calls [(name, line, col)],
      return_calls [names]} over the function's OWN statements;
    * ``imports``: local binding -> ("module", dotted) for
      ``import x [as m]`` / ``from p import submodule``, or
      ("symbol", module, attr) for ``from p.m import f``.
    """
    mod = module_name(relpath) or ""
    package = mod.rsplit(".", 1)[0] if "." in mod else ""
    is_pkg = relpath.endswith("__init__.py")

    imports: dict[str, list] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                bind = alias.asname or alias.name.split(".")[0]
                target = alias.name if alias.asname else \
                    alias.name.split(".")[0]
                imports[bind] = ["module", target]
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                # Relative: level 1 is this file's package, each extra
                # level pops one more component.
                base = mod if is_pkg else package
                for _ in range(node.level - 1):
                    base = base.rsplit(".", 1)[0] if "." in base else ""
                src = f"{base}.{node.module}" if node.module else base
            else:
                src = node.module or ""
            for alias in node.names:
                if alias.name == "*":
                    continue
                bind = alias.asname or alias.name
                imports[bind] = ["symbol", src, alias.name]

    functions: dict[str, dict] = {}
    for qual, fn in collect_functions(tree).items():
        calls: list[list] = []
        return_calls: list[str] = []
        for node in _own_statements(fn):
            if isinstance(node, ast.Call):
                name = call_name(node)
                if name:
                    calls.append([name, node.lineno, node.col_offset])
            elif isinstance(node, ast.Return) and \
                    isinstance(node.value, ast.Call):
                name = call_name(node.value)
                if name:
                    return_calls.append(name)
        functions[qual] = {
            "async": isinstance(fn, ast.AsyncFunctionDef),
            "line": getattr(fn, "lineno", 1),
            "calls": calls,
            "return_calls": return_calls,
        }
    return {"module": mod, "functions": functions, "imports": imports}


class ProjectGraph:
    """Whole-program callgraph over every scanned file.

    Function ids are ``(module, qual)`` tuples (rendered
    ``module:qual`` in messages). Call edges resolve through each
    module's import bindings, so the graph crosses module boundaries:
    ``from x import f; f()``, ``import x.y as m; m.f()``,
    ``collective.get_group(...)`` after
    ``from ... import collective`` — plus absolute dotted references
    via longest-known-module-prefix. ``self.m()`` stays class-local
    (no type inference, same trade-off as the module-local graph).
    """

    def __init__(self, root: str = ""):
        self.root = root
        # module -> {"path", "functions", "imports"}
        self.modules: dict[str, dict] = {}
        self.path_of: dict[str, str] = {}      # module -> relpath
        self.module_of: dict[str, str] = {}    # relpath -> module
        self._callee_cache: dict[tuple, list] = {}
        self._async_reach: dict | None = None

    # -- construction ---------------------------------------------------

    def add_summary(self, relpath: str, summary: dict) -> None:
        mod = summary.get("module") or module_name(relpath)
        if not mod:
            return
        self.modules[mod] = summary
        self.path_of[mod] = relpath
        self.module_of[relpath] = mod

    # -- queries --------------------------------------------------------

    def functions(self) -> Iterator[tuple[tuple[str, str], dict]]:
        for mod, summary in self.modules.items():
            for qual, info in summary["functions"].items():
                yield (mod, qual), info

    def info(self, fid: tuple[str, str]) -> dict | None:
        summary = self.modules.get(fid[0])
        return summary["functions"].get(fid[1]) if summary else None

    def path(self, fid: tuple[str, str]) -> str:
        return self.path_of.get(fid[0], "?")

    @staticmethod
    def render(fid: tuple[str, str]) -> str:
        return f"{fid[0]}:{fid[1]}"

    def _lookup(self, mod: str, name: str):
        """Resolve dotted ``name`` inside module ``mod`` — a function
        qual, or a re-exported submodule attribute."""
        summary = self.modules.get(mod)
        if summary is None:
            return None
        if name in summary["functions"]:
            return (mod, name)
        # one level of module re-export: from pkg import submod
        head, _, tail = name.partition(".")
        bound = summary["imports"].get(head)
        if bound and tail:
            if bound[0] == "module":
                return self._lookup(bound[1], tail)
            if bound[0] == "symbol" and \
                    f"{bound[1]}.{bound[2]}" in self.modules:
                return self._lookup(f"{bound[1]}.{bound[2]}", tail)
        return None

    def resolve_call(
        self, mod: str, owner_class: str | None, name: str
    ):
        """Raw dotted call name -> fid, or None (builtin / foreign /
        dynamic receiver)."""
        summary = self.modules.get(mod)
        if summary is None or not name:
            return None
        head, _, tail = name.partition(".")
        if head in ("self", "cls"):
            if tail and owner_class:
                cand = f"{owner_class}.{tail}"
                if cand in summary["functions"]:
                    return (mod, cand)
            return None
        if name in summary["functions"]:        # module-local
            return (mod, name)
        bound = summary["imports"].get(head)
        if bound is not None:
            if bound[0] == "module":
                target = self._lookup(bound[1], tail) if tail \
                    else None
                if target:
                    return target
            else:  # symbol
                src, attr = bound[1], bound[2]
                full = f"{attr}.{tail}" if tail else attr
                target = self._lookup(src, full)
                if target:
                    return target
                # the imported symbol may itself be a module
                if f"{src}.{attr}" in self.modules and tail:
                    return self._lookup(f"{src}.{attr}", tail)
        # absolute dotted reference: longest known-module prefix
        parts = name.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            prefix = ".".join(parts[:cut])
            if prefix in self.modules:
                return self._lookup(prefix, ".".join(parts[cut:]))
        return None

    def callees(self, fid: tuple[str, str]) -> list[tuple[str, str]]:
        cached = self._callee_cache.get(fid)
        if cached is not None:
            return cached
        info = self.info(fid)
        out: list[tuple[str, str]] = []
        if info:
            owner = owner_class_of(fid[1])
            seen: set = set()
            for name, _line, _col in info["calls"]:
                target = self.resolve_call(fid[0], owner, name)
                if target and target != fid and target not in seen:
                    seen.add(target)
                    out.append(target)
        self._callee_cache[fid] = out
        return out

    def async_reachable(self) -> dict:
        """fid -> the async root fid it is reachable from, across every
        module (the whole-program version of :func:`async_reachable`)."""
        if self._async_reach is not None:
            return self._async_reach
        reach: dict = {}
        work: list = []
        for fid, info in self.functions():
            if info["async"]:
                reach[fid] = fid
                work.append(fid)
        while work:
            cur = work.pop()
            for callee in self.callees(cur):
                if callee in reach:
                    continue
                info = self.info(callee)
                if info is None or info["async"]:
                    continue  # an async callee is its own seed
                reach[callee] = reach[cur]
                work.append(callee)
        self._async_reach = reach
        return reach

    def returning_closure(self, tails: set[str]) -> set:
        """Fids that (transitively) return the result of a call whose
        name ends in one of ``tails`` — e.g. every helper that forwards
        a ``begin_gradient_sync`` handle to its caller."""
        out: set = set()
        changed = True
        while changed:
            changed = False
            for fid, info in self.functions():
                if fid in out:
                    continue
                owner = owner_class_of(fid[1])
                for name in info["return_calls"]:
                    if name.rsplit(".", 1)[-1] in tails:
                        out.add(fid)
                        changed = True
                        break
                    target = self.resolve_call(fid[0], owner, name)
                    if target in out:
                        out.add(fid)
                        changed = True
                        break
        return out


# ---------------------------------------------------------------------------
# Lockset analysis (two-pass)
# ---------------------------------------------------------------------------

_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "asyncio.Lock", "asyncio.Condition",
}


@dataclass
class LockSite:
    lock: str       # canonical lock id, e.g. "Controller.self._lock"
    line: int
    node: ast.AST


@dataclass
class LockOrderEdge:
    first: str
    second: str
    path: str
    line: int       # acquisition site of ``second`` while ``first`` held
    via: str        # human-readable chain, e.g. "A.f -> with a -> with b"


@dataclass
class CallUnderLock:
    """A call made while a lock is held — the raw material for the
    cross-module lock-order pass (resolved through the ProjectGraph)."""
    lock: str       # canonical lock id held at the call
    callee: str     # raw dotted call name (unresolved)
    qual: str       # calling function
    line: int


@dataclass
class ModuleLocks:
    """Pass 1 result: the module's named locks + every ordered pair."""
    locks: set[str] = field(default_factory=set)
    edges: list[LockOrderEdge] = field(default_factory=list)
    # qual -> every lock acquisition inside that function
    acquired: dict[str, list[LockSite]] = field(default_factory=dict)
    calls_under_lock: list[CallUnderLock] = field(default_factory=list)


def _lock_names(tree: ast.Module) -> set[str]:
    """Canonical ids of every variable/attribute assigned a lock ctor.

    ``self._lock = threading.Lock()`` inside class C -> ``C.self._lock``;
    module-level ``_LOCK = threading.Lock()`` -> ``_LOCK``.
    """
    names: set[str] = set()

    def canon(target: ast.AST, cls: str | None) -> str | None:
        try:
            txt = ast.unparse(target)
        except (ValueError, RecursionError):  # unparse of odd targets
            return None
        if cls and txt.startswith("self."):
            return f"{cls}.{txt}"
        if "." in txt and not txt.startswith("self."):
            return None  # foreign-object attr: not ours to track
        return txt if not txt.startswith("self.") else None

    def visit(node: ast.AST, cls: str | None) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                visit(child, child.name)
                continue
            if isinstance(child, ast.Assign):
                val = child.value
                if isinstance(val, ast.Call) and \
                        call_name(val) in _LOCK_CTORS:
                    for tgt in child.targets:
                        c = canon(tgt, cls)
                        if c:
                            names.add(c)
            visit(child, cls)

    visit(tree, None)
    return names


def _as_lock(expr: ast.AST, cls: str | None, locks: set[str]) -> str | None:
    """Resolve a with-item / .acquire() receiver to a canonical lock id."""
    try:
        txt = ast.unparse(expr)
    except (ValueError, RecursionError):
        return None
    if cls and txt.startswith("self."):
        cand = f"{cls}.{txt}"
        return cand if cand in locks else None
    return txt if txt in locks else None


def analyze_locks(tree: ast.Module, path: str) -> ModuleLocks:
    """Two-pass lockset: (1) find lock objects and record, per function,
    the ordered pairs of nested acquisitions — including one level of
    same-class calls made while a lock is held; (2) callers diff the
    edge set for inconsistent orderings (see the lockset-order rule).
    """
    result = ModuleLocks(locks=_lock_names(tree))
    if not result.locks:
        return result
    functions = collect_functions(tree)

    # Locks acquired anywhere inside each function (for call propagation).
    acquired_in: dict[str, list[LockSite]] = {}
    for qual, fn in functions.items():
        cls = owner_class_of(qual)
        sites: list[LockSite] = []
        for node in _own_statements(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lock = _as_lock(item.context_expr, cls, result.locks)
                    if lock:
                        sites.append(LockSite(lock, node.lineno, node))
            elif isinstance(node, ast.Call) and \
                    call_name(node).endswith(".acquire"):
                recv = node.func.value  # type: ignore[attr-defined]
                lock = _as_lock(recv, cls, result.locks)
                if lock:
                    sites.append(LockSite(lock, node.lineno, node))
        acquired_in[qual] = sites
    result.acquired = acquired_in

    def walk_holding(node: ast.AST, held: list[str], qual: str,
                     cls: str | None) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, (ast.With, ast.AsyncWith)):
                inner = [
                    _as_lock(i.context_expr, cls, result.locks)
                    for i in child.items
                ]
                inner = [l for l in inner if l]
                for lock in inner:
                    for h in held:
                        if h != lock:
                            result.edges.append(LockOrderEdge(
                                h, lock, path, child.lineno,
                                via=f"{qual}: with {h} -> with {lock}",
                            ))
                walk_holding(child, held + inner, qual, cls)
                continue
            if isinstance(child, ast.Call) and held:
                name = call_name(child)
                if name and not name.endswith((".acquire", ".release")):
                    for h in held:
                        result.calls_under_lock.append(
                            CallUnderLock(h, name, qual, child.lineno)
                        )
                head, _, tail = name.partition(".")
                callee = None
                if head in ("self", "cls") and tail and cls and \
                        f"{cls}.{tail}" in functions:
                    callee = f"{cls}.{tail}"
                elif name in functions:
                    callee = name
                if callee:
                    for site in acquired_in.get(callee, ()):
                        for h in held:
                            if h != site.lock:
                                result.edges.append(LockOrderEdge(
                                    h, site.lock, path, site.line,
                                    via=(f"{qual}: holds {h}, calls "
                                         f"{callee} which takes "
                                         f"{site.lock}"),
                                ))
            walk_holding(child, held, qual, cls)

    for qual, fn in functions.items():
        walk_holding(fn, [], qual, owner_class_of(qual))
    return result

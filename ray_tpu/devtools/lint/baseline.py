"""Committed-baseline workflow for rtlint.

The baseline is the *documented debt ledger*: every entry is a known
finding with an in-file ``justification`` explaining why it stays. The
gate (`ci/run_lint.sh`, the `lint_clean` release entry) fails on any
finding NOT in the baseline — new hazards cannot land — and reports
stale entries so the ledger shrinks instead of rotting.

Matching is by content fingerprint (see core.assign_fingerprints), so
entries survive unrelated edits and line drift.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from ray_tpu.devtools.lint.core import Finding

DEFAULT_BASELINE = ".rtlint-baseline.json"


@dataclass
class Baseline:
    path: str | None = None
    # fingerprint -> entry dict (rule/path/justification/...)
    entries: dict[str, dict] = field(default_factory=dict)

    @classmethod
    def load(cls, path: str | None) -> "Baseline":
        if not path or not os.path.exists(path):
            return cls(path=path)
        with open(path) as fh:
            data = json.load(fh)
        entries = {e["fingerprint"]: e for e in data.get("entries", [])}
        return cls(path=path, entries=entries)

    def save(self, path: str, findings: list[Finding],
             justification: str = "") -> None:
        """Write the given findings as the new baseline. Existing
        justifications are preserved; new entries get ``justification``
        (or a TODO marker that the self-check test rejects until a real
        reason is written)."""
        from ray_tpu._private.atomic_io import atomic_write_json

        entries = []
        for f in sorted(findings, key=Finding.sort_key):
            old = self.entries.get(f.fingerprint, {})
            entries.append({
                "rule": f.rule,
                "path": f.path,
                "line": f.line,          # advisory; matching is by print
                "summary": f.message.split(":")[0],
                "fingerprint": f.fingerprint,
                "justification": old.get("justification")
                or justification
                or "TODO: justify or fix",
            })
        atomic_write_json(
            path,
            {"version": 1, "tool": "rtlint", "entries": entries},
            indent=2, sort_keys=False,
        )

    def split(self, findings: list[Finding]):
        """(new, baselined, stale_entries): findings not in the ledger,
        findings matched by it, and ledger entries nothing matched."""
        new, matched = [], []
        seen: set[str] = set()
        for f in findings:
            if f.fingerprint in self.entries:
                matched.append(f)
                seen.add(f.fingerprint)
            else:
                new.append(f)
        stale = [e for fp, e in self.entries.items() if fp not in seen]
        return new, matched, stale

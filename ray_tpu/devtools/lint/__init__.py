"""rtlint — framework-aware static analysis for ray_tpu.

A pluggable AST + CFG-lite analysis suite encoding the distributed-
systems invariants the runtime layers fight for (idempotent mutations,
atomic state writes, rank-uniform collective order, non-blocking async
lanes) as review-time checks instead of must-hit-the-bug tests.

Entry points:
  * ``ray_tpu lint`` (CLI, see ``ray_tpu/scripts.py``)
  * :func:`ray_tpu.devtools.lint.runner.run_paths` (programmatic)

Rule catalog and suppression syntax: ``docs/devtools.md``.
"""

from ray_tpu.devtools.lint.core import (  # noqa: F401
    Finding,
    Rule,
    Severity,
    all_rules,
    register_rule,
)
from ray_tpu.devtools.lint.runner import run_paths  # noqa: F401

"""Autoscaler monitor — the bootstrap-launched scaling loop.

Role-equivalent of python/ray/autoscaler/_private/monitor.py :: Monitor
(SURVEY §2.3): the process/thread the HEAD starts so a cluster
autoscales without any user code constructing an autoscaler. Wired from
``ray_tpu.init(autoscaling=...)`` and ``ray_tpu start --head
--autoscaler=v2`` (scripts.py); publishes its status to the controller
KV (namespace ``_autoscaler``) where the dashboard's /api/autoscaler
reads it.

Providers: "podslice" (AutoscalerV2 over PodSliceProvider — the TPU
slice-granular policy) or "v1" (StandardAutoscaler over NodeProvider).
In this image the capacity backend is the in-process LocalCluster (real
node agents); a cloud deployment subclasses the provider and everything
above it is unchanged.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Optional


class _LocalClusterBackend:
    """Adapts _private.node.LocalCluster to the add/remove surface the
    providers expect (cluster_utils.Cluster keeps its own wrapper —
    this one exists so init() can hand the monitor its OWN head
    cluster without import cycles)."""

    def __init__(self, local_cluster):
        self._cluster = local_cluster
        self._agents: dict[str, Any] = {}

    def add_node(self, resources=None, num_cpus=None, **_kw) -> str:
        merged = dict(resources or {})
        if num_cpus is not None and "CPU" not in merged:
            merged["CPU"] = num_cpus
        node_id = self._cluster.add_node(resources=merged)
        self._agents[node_id] = self._cluster.agents[-1]
        return node_id

    def remove_node(self, node_id: str) -> None:
        handle = self._agents.pop(node_id, None)
        if handle is not None:
            handle.kill()


class AutoscalerMonitor:
    """Runs the chosen autoscaler on an interval + reports its status."""

    def __init__(
        self,
        *,
        version: str = "v2",
        provider: Any = "podslice",
        cluster: Any = None,
        idle_timeout_s: float = 60.0,
        max_slices: int = 8,
        update_interval_s: float = 1.0,
        call_fn=None,
        node_types: list | None = None,
    ):
        self.version = version
        self.update_interval_s = update_interval_s
        self._call = call_fn or _driver_call
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_status: dict = {}

        load_fn = lambda: self._call("get_load", {})  # noqa: E731
        if version == "v2":
            from ray_tpu.autoscaler.v2 import AutoscalerV2, PodSliceProvider

            if provider == "podslice" or provider is None:
                provider = PodSliceProvider(cluster=cluster)
            self.autoscaler = AutoscalerV2(
                provider,
                idle_timeout_s=idle_timeout_s,
                max_slices=max_slices,
                load_fn=load_fn,
            )
        elif version == "v1":
            from ray_tpu.autoscaler.autoscaler import (
                AutoscalerConfig, NodeProvider, StandardAutoscaler,
            )

            if provider in ("podslice", None):
                provider = NodeProvider(cluster=cluster)
            config = AutoscalerConfig(
                node_types=node_types or [],
                idle_timeout_s=idle_timeout_s,
            )
            self.autoscaler = StandardAutoscaler(
                config, provider, load_fn=load_fn
            )
        else:
            raise ValueError(f"unknown autoscaler version {version!r}")

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "AutoscalerMonitor":
        self._thread = threading.Thread(
            target=self._loop, name="autoscaler-monitor", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _loop(self) -> None:
        while not self._stopped.is_set():
            try:
                report = self.autoscaler.update()
                self.last_status = {
                    "version": self.version,
                    "ts": time.time(),
                    **report,
                }
            except Exception as exc:  # cluster shutting down, load race…
                self.last_status = {
                    "version": self.version,
                    "ts": time.time(),
                    "error": str(exc)[:500],
                }
            # Publish error statuses too: an operator watching
            # /api/autoscaler must see a broken autoscaler, not the last
            # healthy snapshot with an old timestamp.
            self._publish(self.last_status)
            self._stopped.wait(self.update_interval_s)

    def _publish(self, status: dict) -> None:
        try:
            self._call(
                "kv_put",
                {
                    "namespace": "_autoscaler",
                    "key": "status",
                    "value": json.dumps(status).encode(),
                },
            )
        except Exception:  # rtlint: disable=swallowed-exception - status push is advisory; retried next tick
            pass


def _driver_call(method: str, payload: dict):
    from ray_tpu._private import worker as worker_mod

    ctx = worker_mod.get_global_context()
    return ctx.io.run(ctx.controller.call(method, payload))


def start_monitor_from_config(
    autoscaling, local_cluster=None
) -> AutoscalerMonitor:
    """Build + start a monitor from init()/scripts bootstrap config:
    ``autoscaling`` is "v1"/"v2" or a dict of AutoscalerMonitor kwargs
    (version/provider/idle_timeout_s/max_slices/update_interval_s)."""
    if isinstance(autoscaling, str):
        autoscaling = {"version": autoscaling}
    kwargs = dict(autoscaling or {})
    kwargs.setdefault("version", "v2")
    cluster = kwargs.pop("cluster", None)
    if cluster is None and local_cluster is not None:
        cluster = _LocalClusterBackend(local_cluster)
    return AutoscalerMonitor(cluster=cluster, **kwargs).start()

from ray_tpu.autoscaler.autoscaler import (
    AutoscalerConfig,
    FakeNodeProvider,
    NodeProvider,
    NodeTypeConfig,
    StandardAutoscaler,
    bin_pack_unmet_demand,
)
from ray_tpu.autoscaler.v2 import (
    AutoscalerV2,
    InstanceManagerV2,
    PodSliceProvider,
)

__all__ = [
    "StandardAutoscaler",
    "AutoscalerConfig",
    "NodeTypeConfig",
    "NodeProvider",
    "FakeNodeProvider",
    "bin_pack_unmet_demand",
    "AutoscalerV2",
    "InstanceManagerV2",
    "PodSliceProvider",
]

from ray_tpu.autoscaler.autoscaler import (
    AutoscalerConfig,
    FakeNodeProvider,
    NodeProvider,
    NodeTypeConfig,
    StandardAutoscaler,
    bin_pack_unmet_demand,
)

__all__ = [
    "StandardAutoscaler",
    "AutoscalerConfig",
    "NodeTypeConfig",
    "NodeProvider",
    "FakeNodeProvider",
    "bin_pack_unmet_demand",
]

"""Autoscaler v2 — instance lifecycle state machine + pod-slice provider.

Role-equivalent of python/ray/autoscaler/v2/ :: instance_manager +
instance lifecycle (SURVEY §2.3 autoscaler v2 row), redesigned around
the TPU-native unit of scale: a POD SLICE. Chips in one slice share an
ICI domain, so capacity comes and goes slice-at-a-time — the v2 policy
reads pending pod-slice placement groups (bundles carrying a
``TPU-<slice_spec>`` resource, produced by
``ray_tpu.util.placement_group.tpu_slice_bundles``) and allocates WHOLE
slices; scale-down likewise drains a slice atomically once every host in
it has been idle past the timeout (terminating one host of a live slice
would break the ICI mesh for the rest).

Every instance (one TPU host VM) moves through an explicit, audited FSM:

    REQUESTED -> ALLOCATED -> RUNNING -> DRAINING -> TERMINATED
         \\-> ALLOCATION_FAILED (terminal; slice retried as a whole)

Illegal transitions raise — the reconciler's reasoning is table-testable
exactly like the reference's InstanceManager transition tests.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ray_tpu._private import worker as worker_mod

# -- instance lifecycle -----------------------------------------------------
REQUESTED = "REQUESTED"
ALLOCATED = "ALLOCATED"
RUNNING = "RUNNING"
DRAINING = "DRAINING"
TERMINATED = "TERMINATED"
ALLOCATION_FAILED = "ALLOCATION_FAILED"

_LEGAL_TRANSITIONS = {
    REQUESTED: {ALLOCATED, ALLOCATION_FAILED},
    ALLOCATED: {RUNNING, TERMINATED},
    RUNNING: {DRAINING, TERMINATED},
    DRAINING: {TERMINATED, RUNNING},  # RUNNING: drain cancelled (new load)
    TERMINATED: set(),
    ALLOCATION_FAILED: set(),
}

_ids = itertools.count(1)


@dataclass
class Instance:
    """One TPU host VM of a slice, with its audited lifecycle."""

    instance_id: str
    slice_id: str
    slice_type: str
    host_index: int
    resources: dict
    state: str = REQUESTED
    cloud_node_id: Optional[str] = None  # provider node once ALLOCATED
    history: list = field(default_factory=list)

    def transition(self, new_state: str, reason: str = "") -> None:
        if new_state not in _LEGAL_TRANSITIONS[self.state]:
            raise ValueError(
                f"illegal instance transition {self.state} -> {new_state} "
                f"({self.instance_id})"
            )
        self.history.append((time.time(), self.state, new_state, reason))
        self.state = new_state


class PodSliceProvider:
    """Dry-run TPU pod-slice provider.

    The cloud-CRM role (reference: node_provider implementations), shaped
    for TPU: allocation is per SLICE, hosts come with the slice's
    ``TPU``/``TPU-<spec>`` resources and a slice-id label. Backed by an
    in-process ``cluster_utils.Cluster`` when one is given (tests get
    REAL nodes); otherwise it only records the dry-run inventory.
    """

    def __init__(self, cluster=None):
        self.cluster = cluster
        self._slices: dict[str, list[str]] = {}

    def slice_shape(self, slice_type: str, bundles: list[dict]) -> list[dict]:
        """Per-host resource dicts for one slice serving these bundles.
        The PG's OWN bundles define the shape (extra per-bundle resources
        and bundle counts are honored); the canonical tpu_slice_bundles
        layout is only the fallback."""
        shape = [
            dict(bundle)
            for bundle in bundles
            if any(key.startswith("TPU-") for key in bundle)
        ]
        if shape:
            return shape
        from ray_tpu.util.placement_group import tpu_slice_bundles

        return tpu_slice_bundles(slice_type)

    def create_slice_host(
        self, slice_id: str, slice_type: str, host_index: int, resources: dict
    ) -> str:
        """Allocate ONE host VM of a slice; returns the cloud node id."""
        labeled = dict(resources)
        labeled[f"tpu-slice:{slice_id}"] = 1.0
        if self.cluster is not None:
            node_id = self.cluster.add_node(resources=labeled, num_cpus=2)
        else:
            node_id = f"dryrun-{slice_id}-h{host_index}"
        self._slices.setdefault(slice_id, []).append(node_id)
        return node_id

    def terminate_slice(self, slice_id: str) -> None:
        for node_id in self._slices.pop(slice_id, []):
            if self.cluster is not None:
                try:
                    self.cluster.remove_node(node_id)
                except Exception:  # rtlint: disable=swallowed-exception - node already removed
                    pass

    def non_terminated_slices(self) -> dict[str, list[str]]:
        return {sid: list(nodes) for sid, nodes in self._slices.items()}


class InstanceManagerV2:
    """Owns every Instance and drives the FSM from observed cluster state
    (reference: autoscaler/v2 instance_manager reconciler)."""

    def __init__(self, provider: PodSliceProvider):
        self.provider = provider
        self.instances: dict[str, Instance] = {}

    def request_slice(self, slice_type: str, shape: list[dict]) -> str:
        """Admit a whole slice's hosts as REQUESTED instances."""
        slice_id = f"slice-{next(_ids)}"
        for host_index, resources in enumerate(shape):
            inst = Instance(
                instance_id=f"inst-{next(_ids)}",
                slice_id=slice_id,
                slice_type=slice_type,
                host_index=host_index,
                resources=dict(resources),
            )
            self.instances[inst.instance_id] = inst
        return slice_id

    def by_slice(self) -> dict[str, list[Instance]]:
        out: dict[str, list[Instance]] = {}
        for inst in self.instances.values():
            out.setdefault(inst.slice_id, []).append(inst)
        return out

    def reconcile(self, alive_node_ids: set[str]) -> None:
        """One reconciliation pass: allocate requested hosts, promote
        allocated hosts whose node registered, terminate drained hosts."""
        for slice_id, members in self.by_slice().items():
            for inst in members:
                if inst.state == REQUESTED:
                    try:
                        inst.cloud_node_id = self.provider.create_slice_host(
                            slice_id, inst.slice_type, inst.host_index,
                            inst.resources,
                        )
                        inst.transition(ALLOCATED, "provider created host")
                    except Exception as exc:
                        inst.transition(ALLOCATION_FAILED, str(exc))
                elif inst.state == ALLOCATED:
                    if inst.cloud_node_id in alive_node_ids:
                        inst.transition(RUNNING, "node registered")
                elif inst.state == RUNNING:
                    if (
                        inst.cloud_node_id is not None
                        and inst.cloud_node_id not in alive_node_ids
                        and not inst.cloud_node_id.startswith("dryrun-")
                    ):
                        inst.transition(TERMINATED, "node lost")

    def drain_slice(self, slice_id: str, reason: str) -> None:
        for inst in self.by_slice().get(slice_id, []):
            if inst.state == RUNNING:
                inst.transition(DRAINING, reason)

    def cancel_drain(self, slice_id: str, reason: str) -> None:
        for inst in self.by_slice().get(slice_id, []):
            if inst.state == DRAINING:
                inst.transition(RUNNING, reason)

    def finish_drain(self, slice_id: str) -> None:
        self.provider.terminate_slice(slice_id)
        for inst in self.by_slice().get(slice_id, []):
            if inst.state == DRAINING:
                inst.transition(TERMINATED, "slice drained")

    def abort_slice(self, slice_id: str, reason: str) -> None:
        """Tear a slice down wholesale (allocation failure / lost host —
        a partial slice's ICI mesh is broken, its survivors are useless)."""
        self.provider.terminate_slice(slice_id)
        for inst in self.by_slice().get(slice_id, []):
            if inst.state in (ALLOCATED, RUNNING, DRAINING):
                inst.transition(TERMINATED, reason)


class AutoscalerV2:
    """Slice-granular scaling policy over the instance manager.

    Scale-up: every pending pod-slice placement group (bundles carrying
    a ``TPU-<spec>`` resource) gets one whole slice REQUESTED. Scale-down:
    a slice whose hosts are ALL fully idle past ``idle_timeout_s`` drains
    atomically.
    """

    def __init__(
        self,
        provider: PodSliceProvider,
        idle_timeout_s: float = 60.0,
        max_slices: int = 8,
        update_interval_s: float = 1.0,
        load_fn=None,
    ):
        self.manager = InstanceManagerV2(provider)
        self.idle_timeout_s = idle_timeout_s
        self.max_slices = max_slices
        self.update_interval_s = update_interval_s
        # Load source: default reads through the driver's global context;
        # a standalone monitor (bootstrap-launched, no driver) injects its
        # own controller client here.
        self._load_fn = load_fn
        self._pg_slices: dict[str, str] = {}  # pg_id -> slice_id
        self._slice_idle_since: dict[str, float] = {}
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _slice_type_of(bundles: list[dict]) -> Optional[str]:
        for bundle in bundles:
            for key in bundle:
                if key.startswith("TPU-"):
                    return key[len("TPU-"):]
        return None

    def update(self) -> dict:
        if self._load_fn is not None:
            load = self._load_fn()
        else:
            ctx = worker_mod.get_global_context()
            load = ctx.io.run(ctx.controller.call("get_load", {}))
        alive = {n["node_id"] for n in load["nodes"] if n["alive"]}
        node_info = {n["node_id"]: n for n in load["nodes"] if n["alive"]}

        requested = 0
        # -- scale up: one whole slice per pending pod-slice PG ----------
        pending_pg_ids = set()
        for pg in load.get("pending_pgs", []):
            slice_type = self._slice_type_of(pg["bundles"])
            if slice_type is None:
                continue
            pending_pg_ids.add(pg["pg_id"])
            if pg["pg_id"] in self._pg_slices:
                continue  # slice already on the way
            live = {
                sid
                for sid, members in self.manager.by_slice().items()
                if any(i.state not in (TERMINATED, ALLOCATION_FAILED)
                       for i in members)
            }
            if len(live) >= self.max_slices:
                continue
            shape = self.manager.provider.slice_shape(
                slice_type, pg["bundles"]
            )
            slice_id = self.manager.request_slice(slice_type, shape)
            self._pg_slices[pg["pg_id"]] = slice_id
            requested += 1
        for pg_id in list(self._pg_slices):
            if pg_id not in pending_pg_ids:
                self._pg_slices.pop(pg_id)  # pg placed or removed

        self.manager.reconcile(alive)

        # -- failure repair: a partial slice is a broken ICI mesh --------
        # Any slice with a failed allocation or a lost host is torn down
        # wholesale; its PG mapping drops so the NEXT update requests a
        # fresh slice (retry-as-a-whole).
        for slice_id, members in self.manager.by_slice().items():
            states = {i.state for i in members}
            broken = ALLOCATION_FAILED in states or (
                TERMINATED in states and states != {TERMINATED}
            )
            if broken:
                self.manager.abort_slice(slice_id, "partial slice failure")
                for pg_id, sid in list(self._pg_slices.items()):
                    if sid == slice_id:
                        self._pg_slices.pop(pg_id)
                self._slice_idle_since.pop(slice_id, None)

        def _slice_idle(members) -> bool:
            return all(
                (info := node_info.get(i.cloud_node_id)) is not None
                and info["resources_available"] == info["resources_total"]
                for i in members
            )

        # -- scale down: atomically drain fully-idle slices --------------
        drained = 0
        now = time.monotonic()
        for slice_id, members in self.manager.by_slice().items():
            states = {i.state for i in members}
            if states == {DRAINING}:
                # Re-verify against the CURRENT load report: anything
                # scheduled in the drain window cancels the drain (the
                # FSM's DRAINING -> RUNNING path) instead of losing its
                # nodes.
                if _slice_idle(members):
                    self.manager.finish_drain(slice_id)
                    drained += 1
                else:
                    self.manager.cancel_drain(slice_id, "new load arrived")
                continue
            if states != {RUNNING}:
                self._slice_idle_since.pop(slice_id, None)
                continue
            if not _slice_idle(members):
                self._slice_idle_since.pop(slice_id, None)
                continue
            since = self._slice_idle_since.setdefault(slice_id, now)
            if now - since > self.idle_timeout_s:
                self.manager.drain_slice(slice_id, "idle past timeout")
                self._slice_idle_since.pop(slice_id, None)
        states = [i.state for i in self.manager.instances.values()]
        return {
            "slices_requested": requested,
            "slices_drained": drained,
            "instances": {s: states.count(s) for s in set(states)},
        }

    def start(self) -> None:
        def loop():
            while not self._stopped.is_set():
                try:
                    self.update()
                except Exception:
                    # One failed reconcile must not kill the loop, but an
                    # autoscaler that is silently broken every tick is a
                    # stuck cluster — log each failure.
                    logging.getLogger(__name__).warning(
                        "autoscaler update failed", exc_info=True
                    )
                self._stopped.wait(self.update_interval_s)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()

"""Autoscaler — demand-driven node provisioning.

Role-equivalent of python/ray/autoscaler/_private/autoscaler.py ::
StandardAutoscaler + resource_demand_scheduler.py (SURVEY §2.3): reads
aggregated load (queued demands + per-node availability) from the
controller, bin-packs unmet demand onto configured node types, asks the
NodeProvider to launch/terminate, enforces min/max workers and idle
timeout. The FakeNodeProvider (reference: _private/fake_multi_node)
launches real in-process nodes via cluster_utils.Cluster so the whole
loop is testable on one machine — and TPU pod-slice node types are just
resource dicts ({"TPU": 4, "tpu-slice-v4-8": 1}).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ray_tpu._private import worker as worker_mod


@dataclass
class NodeTypeConfig:
    name: str
    resources: dict
    min_workers: int = 0
    max_workers: int = 10


@dataclass
class AutoscalerConfig:
    node_types: list[NodeTypeConfig] = field(default_factory=list)
    idle_timeout_s: float = 60.0
    update_interval_s: float = 1.0
    max_launch_batch: int = 4


class NodeProvider:
    """Provider interface (reference: autoscaler/node_provider.py)."""

    def create_node(self, node_type: NodeTypeConfig) -> str:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> list[str]:
        raise NotImplementedError


class FakeNodeProvider(NodeProvider):
    """Launches in-process nodes on the running local cluster."""

    def __init__(self, cluster=None):
        if cluster is None:
            from ray_tpu._private.worker import _local_cluster

            cluster = _local_cluster
        if cluster is None:
            raise RuntimeError("FakeNodeProvider needs a local cluster")
        self.cluster = cluster
        self._nodes: dict[str, object] = {}

    def create_node(self, node_type: NodeTypeConfig) -> str:
        node_id = self.cluster.add_node(resources=dict(node_type.resources))
        self._nodes[node_id] = node_id
        return node_id

    def terminate_node(self, node_id: str) -> None:
        if self._nodes.pop(node_id, None) is not None:
            self.cluster.remove_node(node_id)

    def non_terminated_nodes(self) -> list[str]:
        return list(self._nodes)


def _fits(avail: dict, demand: dict) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in demand.items() if v > 0)


def _consume(avail: dict, demand: dict) -> None:
    for key, value in demand.items():
        avail[key] = avail.get(key, 0.0) - value


def bin_pack_unmet_demand(
    demands: list[dict], node_avail: list[dict], node_types: list[NodeTypeConfig]
) -> dict[str, int]:
    """Pure planning math (table-testable like the reference's
    resource_demand_scheduler tests): returns {node_type: count} to launch."""
    avail = [dict(a) for a in node_avail]
    unmet: list[dict] = []
    for demand in demands:
        placed = False
        for slot in avail:
            if _fits(slot, demand):
                _consume(slot, demand)
                placed = True
                break
        if not placed:
            unmet.append(dict(demand))
    to_launch: dict[str, int] = {}
    virtual: list[tuple[str, dict]] = []
    for demand in unmet:
        placed = False
        for name, slot in virtual:
            if _fits(slot, demand):
                _consume(slot, demand)
                placed = True
                break
        if placed:
            continue
        for nt in node_types:
            if _fits(dict(nt.resources), demand):
                slot = dict(nt.resources)
                _consume(slot, demand)
                virtual.append((nt.name, slot))
                to_launch[nt.name] = to_launch.get(nt.name, 0) + 1
                placed = True
                break
        # Demands no node type can ever satisfy are dropped (reported
        # as infeasible by the controller's lease path).
    return to_launch


class StandardAutoscaler:
    def __init__(
        self,
        config: AutoscalerConfig,
        provider: NodeProvider,
        load_fn=None,
    ):
        self.config = config
        self.provider = provider
        self._stopped = threading.Event()
        self._idle_since: dict[str, float] = {}
        self._owned_types: dict[str, str] = {}  # node_id -> node_type name
        self._thread: Optional[threading.Thread] = None
        # Load source: default reads through the driver's global context;
        # a standalone monitor injects its own controller client.
        self._load_fn = load_fn

    # -- one reconciliation step (pure-ish, test-drivable) ---------------
    def update(self) -> dict:
        if self._load_fn is not None:
            load = self._load_fn()
        else:
            ctx = worker_mod.get_global_context()
            load = ctx.io.run(ctx.controller.call("get_load", {}))
        demands = load["pending_demands"]
        alive = [n for n in load["nodes"] if n["alive"]]
        node_avail = [dict(n["resources_available"]) for n in alive]

        # scale up for unmet demand
        to_launch = bin_pack_unmet_demand(
            demands, node_avail, self.config.node_types
        )
        launched = 0
        for nt in self.config.node_types:
            want = to_launch.get(nt.name, 0)
            have = sum(
                1 for t in self._owned_types.values() if t == nt.name
            )
            want = min(want, nt.max_workers - have, self.config.max_launch_batch)
            for _ in range(max(0, want)):
                node_id = self.provider.create_node(nt)
                self._owned_types[node_id] = nt.name
                launched += 1

        # enforce min_workers
        for nt in self.config.node_types:
            have = sum(1 for t in self._owned_types.values() if t == nt.name)
            for _ in range(nt.min_workers - have):
                node_id = self.provider.create_node(nt)
                self._owned_types[node_id] = nt.name
                launched += 1

        # scale down idle owned nodes (fully-available == idle)
        terminated = 0
        now = time.monotonic()
        by_id = {n["node_id"]: n for n in alive}
        for node_id in list(self._owned_types):
            info = by_id.get(node_id)
            if info is None:
                continue
            idle = info["resources_available"] == info["resources_total"]
            if not idle:
                self._idle_since.pop(node_id, None)
                continue
            since = self._idle_since.setdefault(node_id, now)
            nt_name = self._owned_types[node_id]
            nt = next(
                (t for t in self.config.node_types if t.name == nt_name), None
            )
            have = sum(1 for t in self._owned_types.values() if t == nt_name)
            if (
                now - since > self.config.idle_timeout_s
                and nt is not None
                and have > nt.min_workers
            ):
                self.provider.terminate_node(node_id)
                self._owned_types.pop(node_id, None)
                self._idle_since.pop(node_id, None)
                terminated += 1
        return {
            "launched": launched,
            "terminated": terminated,
            "pending_demands": len(demands),
        }

    # -- background loop --------------------------------------------------
    def start(self) -> None:
        def loop():
            while not self._stopped.is_set():
                try:
                    self.update()
                except Exception:
                    # One failed reconcile must not kill the loop, but an
                    # autoscaler that is silently broken every tick is a
                    # stuck cluster — log each failure.
                    logging.getLogger(__name__).warning(
                        "autoscaler update failed", exc_info=True
                    )
                self._stopped.wait(self.config.update_interval_s)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()

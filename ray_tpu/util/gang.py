"""WorkerGang — atomic SPMD groups of actors (the TPU-first actor concept).

SURVEY §7.0.2: Ray is MPMD; TPUs want SPMD gangs. A WorkerGang is one actor
per TPU host of a slice, gang-scheduled via a placement group, sharing a
collective group (and, on real multi-host slices, one jax.distributed
runtime so in-jit collectives span the slice's ICI).

Failure semantics (SURVEY §5.3): ICI makes failure correlated — one dead
member wedges every member's collectives. The gang is therefore the failure
domain: any member death surfaces as GangDiedError, and recovery means
restart-the-gang-from-checkpoint (JaxTrainer builds exactly that on top).

The reference's closest analogue is Train's WorkerGroup
(python/ray/train/_internal/worker_group.py) — but gangs are a core
primitive here, reused by train, rllib learners, and serve TPU replicas.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Sequence

import ray_tpu
from ray_tpu import exceptions
from ray_tpu.util.placement_group import placement_group, remove_placement_group
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy


class GangContext:
    """Handed to every function a gang runs: rank identity + scratch state
    that persists across run() calls on the same member."""

    def __init__(self, rank: int, world_size: int, group_name: str, node_id: str):
        self.rank = rank
        self.world_size = world_size
        self.group_name = group_name
        self.node_id = node_id
        self.state: dict[str, Any] = {}

    def collective(self):
        from ray_tpu.util.collective import collective

        return collective.get_group(self.group_name)


class _GangMember:
    """Actor hosting one rank of the gang."""

    def __init__(
        self,
        rank: int,
        world_size: int,
        group_name: str,
        backend: str,
        env_vars: dict | None,
        coordinator: str | None,
        collective_config=None,
    ):
        for key, value in (env_vars or {}).items():
            os.environ[str(key)] = str(value)
        if coordinator:
            # Real multi-host slice: one jax runtime across the gang, so
            # in-jit collectives ride ICI (jax.distributed replaces the
            # reference's NCCL-unique-id rendezvous, SURVEY §5.8).
            import jax

            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=world_size,
                process_id=rank,
            )
        from ray_tpu.util.collective import collective

        collective.init_collective_group(
            world_size, rank, backend=backend, group_name=group_name,
            config=collective_config,
        )
        self.gang_ctx = GangContext(
            rank, world_size, group_name,
            ray_tpu.get_runtime_context()["node_id"],
        )

    def run(self, fn: Callable, args: tuple, kwargs: dict) -> Any:
        return fn(self.gang_ctx, *args, **kwargs)

    def rank_info(self) -> dict:
        return {
            "rank": self.gang_ctx.rank,
            "node_id": self.gang_ctx.node_id,
            "pid": os.getpid(),
        }

    def ping(self) -> str:
        return "ok"


class WorkerGang:
    def __init__(
        self,
        num_workers: int,
        *,
        resources_per_worker: dict | None = None,
        backend: str = "ring",
        group_name: str | None = None,
        placement_strategy: str = "SPREAD",
        env_vars: dict | None = None,
        coordinator: str | None = None,
        ready_timeout: float = 120.0,
        collective_config=None,
    ):
        self.num_workers = num_workers
        self.backend = backend
        self.group_name = group_name or f"gang-{os.urandom(4).hex()}"
        if coordinator == "auto":
            # Single-host twin convenience: allocate a free port for the
            # jax.distributed coordinator. Real multi-host deployments pass
            # "<rank0-host>:<port>" explicitly (the coordinator must be
            # reachable from every gang member's node).
            import socket as _socket

            probe = _socket.socket()
            probe.bind(("127.0.0.1", 0))
            coordinator = f"127.0.0.1:{probe.getsockname()[1]}"
            probe.close()
        self.coordinator = coordinator
        resources = dict(resources_per_worker or {"CPU": 1})
        bundles = [dict(resources) for _ in range(num_workers)]
        self.pg = placement_group(bundles, strategy=placement_strategy)
        try:
            self.pg.ready(timeout=ready_timeout)
        except Exception:
            # A formation attempt that cannot place must not leave a
            # PENDING PG behind: the controller would keep trying to place
            # it (reserving bundles if capacity returns) and the orphan
            # demand feeds the autoscaler (elastic step-down loops form
            # gangs at several sizes in quick succession).
            try:
                remove_placement_group(self.pg)
            except Exception:  # rtlint: disable=swallowed-exception - PG may be gone; the placement error re-raises below
                pass
            raise
        member_cls = ray_tpu.remote(_GangMember)
        cpu = resources.pop("CPU", 1)
        self.members = [
            member_cls.options(
                num_cpus=cpu,
                resources=resources or None,
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=self.pg, placement_group_bundle_index=i
                ),
            ).remote(
                i, num_workers, self.group_name, backend, env_vars,
                self.coordinator, collective_config,
            )
            for i in range(num_workers)
        ]
        # Block until every member finished collective rendezvous.
        try:
            ray_tpu.get(
                [m.ping.remote() for m in self.members], timeout=ready_timeout
            )
        except Exception as exc:
            self.shutdown()
            raise exceptions.GangDiedError(
                f"gang failed to start: {exc}"
            ) from exc

    def run(
        self,
        fn: Callable,
        per_rank_args: Sequence[tuple] | None = None,
        timeout: float | None = None,
        **kwargs,
    ) -> list:
        """SPMD-execute fn(gang_ctx, *args, **kwargs) on every member."""
        if per_rank_args is not None and len(per_rank_args) != self.num_workers:
            raise ValueError(
                f"per_rank_args has {len(per_rank_args)} entries for "
                f"{self.num_workers} workers"
            )
        refs = [
            member.run.remote(
                fn, tuple(per_rank_args[i]) if per_rank_args else (), kwargs
            )
            for i, member in enumerate(self.members)
        ]
        try:
            return ray_tpu.get(refs, timeout=timeout)
        except (
            exceptions.ActorDiedError,
            exceptions.ActorUnavailableError,
            exceptions.WorkerCrashedError,
        ) as exc:
            raise exceptions.GangDiedError(
                f"gang member died during run: {exc}"
            ) from exc

    def run_async(self, fn: Callable, per_rank_args=None, **kwargs) -> list:
        if per_rank_args is not None and len(per_rank_args) != self.num_workers:
            raise ValueError(
                f"per_rank_args has {len(per_rank_args)} entries for "
                f"{self.num_workers} workers"
            )
        return [
            member.run.remote(
                fn, tuple(per_rank_args[i]) if per_rank_args else (), kwargs
            )
            for i, member in enumerate(self.members)
        ]

    def rank_infos(self) -> list[dict]:
        return ray_tpu.get(
            [m.rank_info.remote() for m in self.members], timeout=60
        )

    def healthy(self) -> bool:
        try:
            ray_tpu.get([m.ping.remote() for m in self.members], timeout=30)
            return True
        except Exception:  # rtlint: disable=swallowed-exception - any failure counts as unhealthy
            return False

    def shutdown(self) -> None:
        for member in self.members if hasattr(self, "members") else []:
            try:
                ray_tpu.kill(member)
            except Exception:  # rtlint: disable=swallowed-exception - member already dead
                pass
        try:
            remove_placement_group(self.pg)
        except Exception:  # rtlint: disable=swallowed-exception - PG already removed
            pass

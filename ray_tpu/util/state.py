"""State API — cluster introspection.

Role-equivalent of python/ray/util/state/ :: list_actors / list_tasks /
list_nodes / list_placement_groups / list_workers / summarize_tasks
(SURVEY §2.2, §5.5), backed by the controller's live tables + task-event
ring buffer [N5]. Each list_* supports simple {key: value} filters and a
limit, like the reference's predicate pushdown.
"""

from __future__ import annotations

import os
from typing import Any, Optional

from ray_tpu._private import worker as worker_mod


def _call(method: str, payload: dict | None = None) -> Any:
    ctx = worker_mod.get_global_context()
    return ctx.io.run(ctx.controller.call(method, payload or {}))


def _apply_filters(rows: list[dict], filters, limit: int) -> list[dict]:
    if filters:
        out = []
        for row in rows:
            ok = True
            for key, value in dict(filters).items():
                if row.get(key) != value:
                    ok = False
                    break
            if ok:
                out.append(row)
        rows = out
    return rows[:limit]


def list_actors(
    filters: dict | None = None, limit: int = 1000
) -> list[dict]:
    return _apply_filters(_call("list_actors"), filters, limit)


def list_nodes(filters: dict | None = None, limit: int = 1000) -> list[dict]:
    return _apply_filters(_call("list_nodes"), filters, limit)


def list_placement_groups(
    filters: dict | None = None, limit: int = 1000
) -> list[dict]:
    return _apply_filters(_call("list_placement_groups"), filters, limit)


def list_workers(filters: dict | None = None, limit: int = 1000) -> list[dict]:
    return _apply_filters(_call("list_workers"), filters, limit)


def list_jobs(filters: dict | None = None, limit: int = 1000) -> list[dict]:
    return _apply_filters(_call("list_jobs"), filters, limit)


def list_tasks(filters: dict | None = None, limit: int = 1000) -> list[dict]:
    """Latest state per task. The event→row reduction, filters, and limit
    all run controller-side (predicate pushdown) — the client receives at
    most ``limit`` rows instead of the raw 100k-event log."""
    return _call(
        "list_tasks", {"filters": dict(filters) if filters else None,
                       "limit": limit}
    )


def summarize_tasks() -> dict:
    """ray summary tasks — counts by (name, state)."""
    tasks = list_tasks(limit=100_000)
    summary: dict[str, dict] = {}
    for task in tasks:
        name = task.get("name") or "unknown"
        entry = summary.setdefault(name, {})
        state = task.get("state") or "UNKNOWN"
        entry[state] = entry.get(state, 0) + 1
    return summary


def summarize_actors() -> dict:
    actors = list_actors(limit=100_000)
    summary: dict[str, dict] = {}
    for actor in actors:
        name = actor.get("class_name") or "unknown"
        entry = summary.setdefault(name, {})
        state = actor.get("state") or "UNKNOWN"
        entry[state] = entry.get(state, 0) + 1
    return summary


def list_objects(limit: int = 1000) -> list[dict]:
    """Owner-side view of live objects in this process."""
    ctx = worker_mod.get_global_context()
    rows = []
    for object_id, state in list(ctx._objects.items())[:limit]:
        rows.append(
            {
                "object_id": object_id,
                "status": state.status,
                "size": getattr(state, "size", None),
            }
        )
    return rows


def get_actor(actor_id: str) -> Optional[dict]:
    for row in list_actors(limit=100_000):
        if row.get("actor_id") == actor_id:
            return row
    return None


def get_node(node_id: str) -> Optional[dict]:
    for row in list_nodes(limit=100_000):
        if row.get("node_id") == node_id:
            return row
    return None


# ---------------------------------------------------------------------------
# Latency breakdown over the span store (critical-path tracing, ISSUE 4):
# spans are reduced into per-phase percentiles so "where does task time go"
# is one call, not a debugger session.
# ---------------------------------------------------------------------------

# Lifecycle phases in causal order (for stable presentation; other span
# kinds — collective.*, serve.*, object_* — group under their own name).
LIFECYCLE_PHASES = (
    "submit", "lease_wait", "worker_start", "queue_wait",
    "fetch_args", "execute", "put_result",
)


def _session_dir() -> str | None:
    cluster = getattr(worker_mod, "_local_cluster", None)
    if cluster is not None and getattr(cluster, "session_dir", None):
        return cluster.session_dir
    return os.environ.get("RAYTPU_SESSION_DIR")


def _phase_of(span_name: str) -> str:
    return span_name.split(" ", 1)[0]


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile over an ascending list (0 for empty)."""
    if not sorted_vals:
        return 0.0
    idx = int(round(q * (len(sorted_vals) - 1)))
    return sorted_vals[min(len(sorted_vals) - 1, max(0, idx))]


def summarize_latency(session_dir: str | None = None) -> dict:
    """Per-phase latency breakdown over every recorded span.

    Returns ``{phase: {count, p50_ms, p95_ms, mean_ms, max_ms, errors}}``
    where phase is the first token of the span name (``submit``,
    ``lease_wait``, ``execute``, ``collective.allreduce``, …)."""
    from ray_tpu.util import tracing

    session_dir = session_dir or _session_dir()
    if not session_dir:
        return {}
    try:
        spans = tracing.read_spans(session_dir)
    except Exception:
        # Fresh cluster / tracing disabled / span file unreadable: an
        # empty breakdown, not a stack trace (ISSUE 8 satellite).
        return {}
    by_phase: dict[str, list[float]] = {}
    errors: dict[str, int] = {}
    for span in spans:
        if not span.get("end_ns") or not span.get("start_ns"):
            continue
        phase = _phase_of(span.get("name", ""))
        dur_ms = (span["end_ns"] - span["start_ns"]) / 1e6
        by_phase.setdefault(phase, []).append(dur_ms)
        if span.get("status") not in (None, "ok"):
            errors[phase] = errors.get(phase, 0) + 1
    out: dict[str, dict] = {}
    ordered = [p for p in LIFECYCLE_PHASES if p in by_phase] + sorted(
        p for p in by_phase if p not in LIFECYCLE_PHASES
    )
    for phase in ordered:
        durs = sorted(by_phase[phase])
        out[phase] = {
            "count": len(durs),
            "p50_ms": _percentile(durs, 0.50),
            "p95_ms": _percentile(durs, 0.95),
            "mean_ms": sum(durs) / len(durs),
            "max_ms": durs[-1],
            "errors": errors.get(phase, 0),
        }
    return out


def summarize_comm(session_dir: str | None = None) -> dict:
    """Communication breakdown over ``collective.*`` spans.

    Returns ``{(op, backend) -> {count, total_ms, p50_ms, p95_ms,
    bytes, wire_bytes, bytes_per_s}}`` keyed as ``"op/backend"`` —
    the comm-time complement to :func:`summarize_latency`'s per-phase
    view. ``bytes`` is the logical payload; ``wire_bytes`` is what the
    backend actually serialized (smaller under quantization, zero for
    in-device-mesh backends)."""
    from ray_tpu.util import tracing

    session_dir = session_dir or _session_dir()
    if not session_dir:
        return {}
    try:
        spans = tracing.read_spans(session_dir)
    except Exception:
        return {}
    # Watchdog-suspected stalls fold in as per-op columns (count + the
    # channel names blamed), so the comm table answers "slow or WEDGED"
    # in one view. Best-effort: no controller, no stall columns.
    stall_count: dict[str, int] = {}
    stall_channels: dict[str, set] = {}
    try:
        for ev in summarize_commflight().get("stalls", []):
            op = ev.get("kind", "?")
            stall_count[op] = stall_count.get(op, 0) + 1
            if ev.get("channel"):
                stall_channels.setdefault(op, set()).add(ev["channel"])
    except Exception:  # rtlint: disable=swallowed-exception - stall columns are optional; spans alone still summarize
        pass
    acc: dict[str, dict] = {}
    for span in spans:
        name = span.get("name", "")
        if not name.startswith("collective."):
            continue
        if not span.get("end_ns") or not span.get("start_ns"):
            continue
        attrs = span.get("attributes") or {}
        op = attrs.get("op", name.split(".", 1)[1])
        backend = attrs.get("backend", "?")
        key = f"{op}/{backend}"
        entry = acc.setdefault(
            key, {"durs": [], "bytes": 0, "wire_bytes": 0}
        )
        entry["durs"].append((span["end_ns"] - span["start_ns"]) / 1e6)
        entry["bytes"] += int(attrs.get("bytes") or 0)
        entry["wire_bytes"] += int(attrs.get("wire_bytes") or 0)
    out: dict[str, dict] = {}
    for key in sorted(acc):
        durs = sorted(acc[key]["durs"])
        total_ms = sum(durs)
        nbytes = acc[key]["bytes"]
        op = key.split("/", 1)[0]
        out[key] = {
            "count": len(durs),
            "total_ms": total_ms,
            "p50_ms": _percentile(durs, 0.50),
            "p95_ms": _percentile(durs, 0.95),
            "bytes": nbytes,
            "wire_bytes": acc[key]["wire_bytes"],
            "bytes_per_s": (
                nbytes / (total_ms / 1e3) if total_ms > 0 else 0.0
            ),
            "stalls": stall_count.get(op, 0),
            "stalled_channels": sorted(stall_channels.get(op, ())),
        }
    return out


def summarize_commflight() -> dict:
    """Live comm-plane flight-recorder view from the controller: recent
    watchdog ``comm_stall`` events, per-worker in-flight gauges (count +
    oldest-op age, overwritten each watchdog tick — snapshots, never
    drained), and the number of merged hang reports available. Empty
    structure — never an exception — on a fresh or absent cluster."""
    try:
        out = _call("comm_summary")
    except Exception:
        out = None
    if not isinstance(out, dict):
        out = {}
    out.setdefault("stall_total", 0)
    out.setdefault("stalls", [])
    out.setdefault("last_stall_age_s", None)
    out.setdefault("inflight", {})
    out.setdefault("hang_reports", 0)
    return out


def get_hang_report(fresh: bool = False, stacks: bool = True) -> dict:
    """The controller's latest merged hang report (see
    ``ray_tpu._private.hang_doctor.build_report``); ``fresh=True`` forces
    a cluster-wide evidence harvest right now (the `ray_tpu doctor
    --hang` path when nothing has auto-fired yet)."""
    out = _call("hang_report", {"fresh": bool(fresh), "stacks": bool(stacks)})
    return out.get("report", {}) if isinstance(out, dict) else {}


def collect_cluster_stacks() -> dict:
    """Native Python stack dump of every worker on every alive node,
    keyed node -> worker (the `ray_tpu stacks` CLI; no py-spy needed)."""
    out = _call("cluster_stacks")
    return out.get("nodes", {}) if isinstance(out, dict) else {}


# ---------------------------------------------------------------------------
# Cluster step profiler (ISSUE 20)
# ---------------------------------------------------------------------------


def capture_profile(
    steps: int = 3,
    ranks: list | None = None,
    reason: str = "manual",
    wait: bool = True,
    timeout_s: float = 300.0,
) -> dict:
    """Run one coordinated, step-aligned profile capture across the
    train gang (the `ray_tpu profile` CLI). Arms every selected rank at
    the same upcoming step boundary, captures ``steps`` steps of device
    trace + host sampling profiler + annotation slices, and merges the
    pile into ONE Perfetto trace under the session dir.

    ``wait=True`` polls the controller until the capture record lands
    (captures span live train steps, so this outlives a single RPC
    deadline by design); ``wait=False`` returns the capture id
    immediately."""
    started = _call(
        "profile_capture",
        {"steps": int(steps), "ranks": ranks, "reason": reason},
    )
    if not isinstance(started, dict) or started.get("status") != "ok":
        return started if isinstance(started, dict) else {"status": "error"}
    capture_id = started.get("capture_id")
    if not wait:
        return started
    import time as _time

    deadline = _time.monotonic() + timeout_s
    while _time.monotonic() < deadline:
        out = _call("profile_status", {"capture_id": capture_id})
        if isinstance(out, dict) and out.get("state") == "done":
            return out.get("record") or {}
        _time.sleep(0.5)
    return {
        "status": "error",
        "code": "timeout",
        "capture_id": capture_id,
        "error": f"capture did not finish within {timeout_s}s",
    }


def list_profiles() -> list[dict]:
    """Completed capture records (manual + auto), oldest first. Empty
    list — never an exception — on a fresh or absent cluster."""
    try:
        out = _call("profile_list")
    except Exception:
        return []
    if not isinstance(out, dict):
        return []
    return [r for r in out.get("profiles", []) if isinstance(r, dict)]


# ---------------------------------------------------------------------------
# Resource telemetry (ISSUE 5): the controller's tiered time-series store
# answers "what is the cluster eating" the way summarize_latency answers
# "where does task time go".
# ---------------------------------------------------------------------------


def summarize_resources() -> dict:
    """Cluster resource-utilization summary from the controller's
    telemetry store.

    Returns ``{"nodes": {node_id: {latest, points, last_ts, dropped,
    alive}}, "total_ingested": N, "total_dropped": N, "oom_risk_events":
    N}`` where ``latest`` is the node's freshest sample (cpu_percent,
    mem_used/total, per-worker RSS, object-store bytes, HBM when on TPU)
    and ``points`` gives the depth of each retention tier
    (raw / 10s / 60s)."""
    return _call("resource_summary")


def get_node_timeline(node_id: str, tier: str | None = None) -> dict:
    """One node's resource time-series, per retention tier.

    ``tier`` of ``"raw"``, ``"10s"``, or ``"60s"`` selects one ring;
    None returns all three. Buckets carry mean for rate-like fields
    (cpu_percent) and max for footprints (RSS, object-store bytes, HBM),
    plus a trailing ``partial`` bucket aggregating samples not yet old
    enough to close."""
    return _call("resource_timeline", {"node_id": node_id, "tier": tier})


def summarize_task_memory(limit: int = 100_000) -> list[dict]:
    """Which tasks ate the memory: finished/failed tasks ranked by the
    amount they raised their worker's RSS high-water mark (``rss_delta``,
    recorded per execution by the worker), with ``peak_rss`` and
    ``hbm_delta`` alongside when present."""
    rows = [
        row for row in list_tasks(limit=limit)
        if row.get("rss_delta") is not None or row.get("peak_rss") is not None
    ]
    rows.sort(key=lambda r: (r.get("rss_delta") or 0), reverse=True)
    return rows


def get_task_timeline(
    task_id: str, session_dir: str | None = None
) -> list[dict]:
    """Every span of one task's lifecycle, in causal/start order — the
    single-task drill-down companion of :func:`summarize_latency`."""
    from ray_tpu.util import tracing

    session_dir = session_dir or _session_dir()
    if not session_dir:
        return []
    all_spans = tracing.read_spans(session_dir)
    # The task's own spans, plus causally-linked spans of the same traces
    # that don't carry the task_id attribute (e.g. lease_wait attributed
    # via trace context only, or a parent serve.request).
    trace_ids = {
        s["trace_id"] for s in all_spans
        if (s.get("attributes") or {}).get("task_id") == task_id
    }
    spans = [s for s in all_spans if s.get("trace_id") in trace_ids]
    spans.sort(key=lambda s: (s.get("start_ns") or 0))
    out = []
    for s in spans:
        out.append(
            {
                "phase": _phase_of(s.get("name", "")),
                "name": s.get("name"),
                "trace_id": s.get("trace_id"),
                "span_id": s.get("span_id"),
                "parent_id": s.get("parent_id"),
                "start_ns": s.get("start_ns"),
                "end_ns": s.get("end_ns"),
                "duration_ms": (
                    ((s.get("end_ns") or 0) - (s.get("start_ns") or 0)) / 1e6
                ),
                "status": s.get("status", "ok"),
                "attributes": s.get("attributes") or {},
            }
        )
    return out


# ---------------------------------------------------------------------------
# Workload flight recorder (ISSUE 8): per-run training breakdown, goodput
# accounting, and serve SLO series land in the controller's workload store;
# these are the read-side entry points for `diagnose`, the dashboard, and
# user code.
# ---------------------------------------------------------------------------


def summarize_workload() -> dict:
    """All workload flight-recorder series known to the controller.

    Returns ``{"series": {key: {latest, points, last_ts, dropped}},
    "total_ingested": N, "total_dropped": N}`` where keys look like
    ``train/<experiment>`` (gang-level StepStats rollup),
    ``train/<experiment>/rank<k>`` (per-rank step records),
    ``train/<experiment>/goodput`` (wall-clock bucket snapshots), and
    ``serve/<route>`` (latency histogram snapshots). Empty structure —
    never an exception — on a fresh cluster."""
    try:
        summary = _call("workload_summary")
    except Exception:
        summary = None
    if not isinstance(summary, dict):
        return {"series": {}, "total_ingested": 0, "total_dropped": 0}
    summary.setdefault("series", {})
    summary.setdefault("total_ingested", 0)
    summary.setdefault("total_dropped", 0)
    return summary


def get_workload_timeline(key: str, tier: str | None = None) -> dict:
    """One workload series' tiered time-series (same raw/10s/60s rings
    and partial-bucket semantics as :func:`get_node_timeline`). Unknown
    keys return ``{}``."""
    try:
        out = _call("workload_timeline", {"key": key, "tier": tier})
    except Exception:
        return {}
    return out if isinstance(out, dict) else {}


def summarize_goodput() -> dict:
    """Wall-clock goodput accounting per training run.

    Returns ``{"runs": {experiment: {wall_s, productive_s, checkpoint_s,
    restart_s, stalled_s, goodput_fraction, ts}}}`` from the latest
    ``train/<experiment>/goodput`` sample each run pushed (finalized runs
    push once more on exit, so completed runs keep their final numbers).
    ``{"runs": {}}`` on a fresh cluster — never an exception."""
    runs: dict[str, dict] = {}
    try:
        series = summarize_workload().get("series", {})
        for key, entry in series.items():
            if not key.startswith("train/") or not key.endswith("/goodput"):
                continue
            experiment = key[len("train/"):-len("/goodput")]
            latest = (entry or {}).get("latest")
            if isinstance(latest, dict):
                runs[experiment] = dict(latest)
    except Exception:
        return {"runs": {}}
    return {"runs": runs}


def summarize_sequences(session_dir: str | None = None,
                        limit: int = 200) -> dict:
    """Token-level serving observability rollup (ISSUE 19).

    Reads the per-sequence timeline records the decode engines exported
    beside the span files (``<session>/tracing/sequences-*.jsonl``) and
    returns::

        {"count": N, "by_outcome": {outcome: n},
         "ttft_p50_s": .., "ttft_p99_s": ..,
         "tpot_p50_s": .., "tpot_p99_s": ..,
         "ledger": {issued, productive, shed, evicted,
                    replay_discarded},
         "kv_history": [(ts, kv_free_frac), ...],   # trend input
         "sequences": [... newest ``limit`` seq records ...]}

    Empty structure — never an exception — on a fresh cluster or with
    sequence sampling off."""
    empty = {
        "count": 0, "by_outcome": {}, "ttft_p50_s": 0.0,
        "ttft_p99_s": 0.0, "tpot_p50_s": 0.0, "tpot_p99_s": 0.0,
        "ledger": {}, "kv_history": [], "sequences": [],
    }
    session_dir = session_dir or _session_dir()
    if not session_dir:
        return empty
    try:
        from ray_tpu.serve.llm import observability as seq_obs

        records = seq_obs.read_sequences(session_dir)
    except Exception:
        return empty
    seqs = [r for r in records if r.get("kind") == "seq"]
    kv = [r for r in records if r.get("kind") == "kv"]
    by_outcome: dict[str, int] = {}
    ttfts: list[float] = []
    tpots: list[float] = []
    ledger = {
        "productive": 0, "shed": 0, "evicted": 0, "replay_discarded": 0,
    }
    for rec in seqs:
        outcome = str(rec.get("outcome", ""))
        by_outcome[outcome] = by_outcome.get(outcome, 0) + 1
        if rec.get("tokens"):
            ttfts.append(float(rec.get("ttft_s", 0.0)))
            tpots.append(float(rec.get("tpot_p50_s", 0.0)))
        if outcome in ledger:
            ledger[outcome] += int(rec.get("tokens", 0))
        ledger["replay_discarded"] += int(rec.get("replay_discarded", 0))
    ledger["issued"] = sum(ledger.values())
    ttfts.sort()
    tpots.sort()
    seqs.sort(key=lambda r: r.get("ts", 0.0))
    return {
        "count": len(seqs),
        "by_outcome": by_outcome,
        "ttft_p50_s": _percentile(ttfts, 0.50),
        "ttft_p99_s": _percentile(ttfts, 0.99),
        "tpot_p50_s": _percentile(tpots, 0.50),
        "tpot_p99_s": _percentile(tpots, 0.99),
        "ledger": ledger,
        "kv_history": [
            (float(r.get("ts", 0.0)), float(r.get("kv_free_frac", 0.0)))
            for r in kv
        ],
        "sequences": seqs[-limit:],
    }


def collect_diagnose_snapshot(session_dir: str | None = None) -> dict:
    """Assemble the cross-subsystem snapshot that feeds
    ``ray_tpu._private.workload.diagnose`` (and the `ray_tpu diagnose`
    CLI): span latency + comm breakdowns, node resource telemetry,
    goodput buckets, workload series, and the raw per-rank step records
    needed for straggler attribution. Every section degrades to an empty
    structure independently, so a partially-up cluster still diagnoses
    whatever it has."""
    snapshot: dict[str, Any] = {
        "latency": {},
        "comm": {},
        "resources": {},
        "goodput": {"runs": {}},
        "workload": {"series": {}},
        "rank_records": {},
        "commflight": {},
        "serve_llm": {},
        "profiles": [],
    }
    try:
        snapshot["profiles"] = list_profiles()
    except Exception:  # rtlint: disable=swallowed-exception - summaries are independent; a failed one keeps its default
        pass
    try:
        snapshot["serve_llm"] = summarize_sequences(session_dir)
    except Exception:  # rtlint: disable=swallowed-exception - summaries are independent; a failed one keeps its default
        pass
    try:
        snapshot["latency"] = summarize_latency(session_dir)
    except Exception:  # rtlint: disable=swallowed-exception - summaries are independent; a failed one keeps its default
        pass
    try:
        snapshot["comm"] = summarize_comm(session_dir)
    except Exception:  # rtlint: disable=swallowed-exception - summaries are independent; a failed one keeps its default
        pass
    try:
        snapshot["resources"] = summarize_resources()
    except Exception:  # rtlint: disable=swallowed-exception - summaries are independent; a failed one keeps its default
        pass
    try:
        snapshot["commflight"] = summarize_commflight()
    except Exception:  # rtlint: disable=swallowed-exception - summaries are independent; a failed one keeps its default
        pass
    snapshot["workload"] = summarize_workload()
    snapshot["goodput"] = summarize_goodput()
    # Raw per-rank step records, grouped by experiment, for the
    # straggler detector's replay in diagnose().
    try:
        for key in snapshot["workload"].get("series", {}):
            if not key.startswith("train/") or "/rank" not in key:
                continue
            experiment = key[len("train/"):].rsplit("/rank", 1)[0]
            timeline = get_workload_timeline(key, "raw")
            records = [
                r for r in timeline.get("raw", []) if isinstance(r, dict)
            ]
            if records:
                snapshot["rank_records"].setdefault(
                    experiment, []
                ).extend(records)
    except Exception:  # rtlint: disable=swallowed-exception - workload timeline is optional in the snapshot
        pass
    return snapshot

"""State API — cluster introspection.

Role-equivalent of python/ray/util/state/ :: list_actors / list_tasks /
list_nodes / list_placement_groups / list_workers / summarize_tasks
(SURVEY §2.2, §5.5), backed by the controller's live tables + task-event
ring buffer [N5]. Each list_* supports simple {key: value} filters and a
limit, like the reference's predicate pushdown.
"""

from __future__ import annotations

from typing import Any, Optional

from ray_tpu._private import worker as worker_mod


def _call(method: str, payload: dict | None = None) -> Any:
    ctx = worker_mod.get_global_context()
    return ctx.io.run(ctx.controller.call(method, payload or {}))


def _apply_filters(rows: list[dict], filters, limit: int) -> list[dict]:
    if filters:
        out = []
        for row in rows:
            ok = True
            for key, value in dict(filters).items():
                if row.get(key) != value:
                    ok = False
                    break
            if ok:
                out.append(row)
        rows = out
    return rows[:limit]


def list_actors(
    filters: dict | None = None, limit: int = 1000
) -> list[dict]:
    return _apply_filters(_call("list_actors"), filters, limit)


def list_nodes(filters: dict | None = None, limit: int = 1000) -> list[dict]:
    return _apply_filters(_call("list_nodes"), filters, limit)


def list_placement_groups(
    filters: dict | None = None, limit: int = 1000
) -> list[dict]:
    return _apply_filters(_call("list_placement_groups"), filters, limit)


def list_workers(filters: dict | None = None, limit: int = 1000) -> list[dict]:
    return _apply_filters(_call("list_workers"), filters, limit)


def list_jobs(filters: dict | None = None, limit: int = 1000) -> list[dict]:
    return _apply_filters(_call("list_jobs"), filters, limit)


def list_tasks(filters: dict | None = None, limit: int = 1000) -> list[dict]:
    """Latest state per task, reduced from the task-event log."""
    events = _call("list_task_events", {"limit": 100_000})
    latest: dict[str, dict] = {}
    for event in events:
        task_id = event.get("task_id")
        if not task_id:
            continue
        row = latest.setdefault(
            task_id,
            {
                "task_id": task_id,
                "name": event.get("name"),
                "state": None,
                "node_id": event.get("node_id"),
                "start_time": None,
                "end_time": None,
            },
        )
        state = event.get("state")
        row["state"] = state
        if event.get("name"):
            row["name"] = event["name"]
        ts = event.get("ts")
        if state in ("RUNNING",) and ts:
            row["start_time"] = ts
        if event.get("start_ts"):
            # terminal events carry the span start (single-event form)
            row["start_time"] = event["start_ts"]
        if state in ("FINISHED", "FAILED") and ts:
            row["end_time"] = ts
    return _apply_filters(list(latest.values()), filters, limit)


def summarize_tasks() -> dict:
    """ray summary tasks — counts by (name, state)."""
    tasks = list_tasks(limit=100_000)
    summary: dict[str, dict] = {}
    for task in tasks:
        name = task.get("name") or "unknown"
        entry = summary.setdefault(name, {})
        state = task.get("state") or "UNKNOWN"
        entry[state] = entry.get(state, 0) + 1
    return summary


def summarize_actors() -> dict:
    actors = list_actors(limit=100_000)
    summary: dict[str, dict] = {}
    for actor in actors:
        name = actor.get("class_name") or "unknown"
        entry = summary.setdefault(name, {})
        state = actor.get("state") or "UNKNOWN"
        entry[state] = entry.get(state, 0) + 1
    return summary


def list_objects(limit: int = 1000) -> list[dict]:
    """Owner-side view of live objects in this process."""
    ctx = worker_mod.get_global_context()
    rows = []
    for object_id, state in list(ctx._objects.items())[:limit]:
        rows.append(
            {
                "object_id": object_id,
                "status": state.status,
                "size": getattr(state, "size", None),
            }
        )
    return rows


def get_actor(actor_id: str) -> Optional[dict]:
    for row in list_actors(limit=100_000):
        if row.get("actor_id") == actor_id:
            return row
    return None


def get_node(node_id: str) -> Optional[dict]:
    for row in list_nodes(limit=100_000):
        if row.get("node_id") == node_id:
            return row
    return None

"""Collective communication for actors (ring / xla / hierarchical).

Convenience re-exports so callers can write
``from ray_tpu.util.collective import CollectiveConfig`` without
reaching into the submodules.
"""

from ray_tpu.util.collective import flight  # noqa: F401
from ray_tpu.util.collective.quantization import (  # noqa: F401
    CollectiveConfig,
    ErrorFeedback,
    fp8_supported,
)

"""Process-group collectives for actors.

Role-equivalent of python/ray/util/collective/collective.py
(:: init_collective_group, allreduce, allgather, reducescatter, broadcast,
barrier, send, recv) with the reference's NCCL/Gloo backends replaced by
(SURVEY §5.8):

  * "xla"  — the TPU data plane: collectives compile into XLA programs over
    the caller's jax device mesh (psum/all_gather/... on ICI). Multi-host
    gangs share one global jax runtime via jax.distributed (rendezvous
    coordinates come from the gang, §gang.py); a single host's chips work
    out of the box.
  * "ring" — host-memory ring collectives over the framework's own RPC p2p
    (reduce-scatter + all-gather ring), the Gloo-equivalent CPU fallback
    AND the hostless test twin (SURVEY §4.4.4).

Rendezvous replaces the reference's NCCL-unique-id "Info" actor with the
controller KV [N6].
"""

from __future__ import annotations

import asyncio
import functools
import inspect
import os
import pickle
import threading
import time
from typing import Any

import numpy as np

from ray_tpu._private import chaos
from ray_tpu._private import worker as worker_mod
from ray_tpu.util import tracing
from ray_tpu.util.collective import flight
from ray_tpu.util.collective.quantization import (
    CollectiveConfig,
    ErrorFeedback,
    decode as _q_decode,
)

_groups: dict[str, "BaseGroup"] = {}

SUM, PRODUCT, MIN, MAX = "sum", "product", "min", "max"
_REDUCERS = {SUM: np.add, PRODUCT: np.multiply, MIN: np.minimum, MAX: np.maximum}


class BaseGroup:
    #: short backend label stamped on spans/metrics ("ring"/"xla"/"hier")
    backend_name = "base"

    def __init__(
        self,
        world_size: int,
        rank: int,
        group_name: str,
        config: CollectiveConfig | None = None,
    ):
        self.world_size = world_size
        self.rank = rank
        self.group_name = group_name
        self.config = config or CollectiveConfig()
        # Cumulative wire accounting (payload bytes actually serialized for
        # the network; device-mesh backends leave it at zero).
        self.wire_stats: dict[str, int] = {
            "bytes_sent": 0,
            "msgs_sent": 0,
        }

    # subclasses implement: allreduce, allgather, reducescatter, broadcast,
    # barrier, send, destroy — and recv with THIS unified signature
    # (``like`` is the shape/dtype template shape-static backends need;
    # host-memory backends accept and ignore it).
    def recv(self, src_rank: int, tag: str = "", timeout: float = 60.0,
             like=None):
        raise NotImplementedError

    def p2p(self, array, src_rank: int, dst_rank: int):
        """Group-wide p2p entry point: every rank calls with the same
        (src, dst); returns the array on dst, None elsewhere. Host-memory
        backends only involve the endpoints; the xla backend overrides
        this with a true all-rank ppermute collective."""
        if self.rank == src_rank:
            self.send(np.asarray(array), dst_rank)
            return None
        if self.rank == dst_rank:
            return self.recv(src_rank)
        return None


# ---------------------------------------------------------------------------
# ring backend (host memory over RPC p2p)
# ---------------------------------------------------------------------------
class RingGroup(BaseGroup):
    backend_name = "ring"

    def __init__(
        self,
        world_size: int,
        rank: int,
        group_name: str,
        config: CollectiveConfig | None = None,
    ):
        super().__init__(world_size, rank, group_name, config=config)
        self.ctx = worker_mod.get_global_context()
        self._ef = ErrorFeedback()
        self._mailbox: dict[tuple, Any] = {}
        self._mailbox_events: dict[tuple, asyncio.Event] = {}
        self.ctx.core_server.route(
            f"coll_send/{group_name}", self._rpc_coll_send
        )
        self._register()
        self._peer_addrs = self._resolve_peers()
        self._barrier_epoch = 0
        self._send_seq: dict[tuple, int] = {}
        self._recv_seq: dict[tuple, int] = {}

    # -- rendezvous via controller KV ----------------------------------
    def _kv(self, method: str, payload: dict) -> Any:
        return self.ctx.io.run(self.ctx.controller.call(method, payload))

    def _register(self) -> None:
        self._kv(
            "kv_put",
            {
                "namespace": "collective",
                "key": f"{self.group_name}/rank/{self.rank}",
                "value": pickle.dumps(tuple(self.ctx.address)),
            },
        )

    def _resolve_peers(self) -> dict[int, tuple]:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            keys = self._kv(
                "kv_keys",
                {"namespace": "collective", "prefix": f"{self.group_name}/rank/"},
            )
            if len(keys) >= self.world_size:
                peers = {}
                for r in range(self.world_size):
                    resp = self._kv(
                        "kv_get",
                        {
                            "namespace": "collective",
                            "key": f"{self.group_name}/rank/{r}",
                        },
                    )
                    peers[r] = pickle.loads(resp["value"])
                return peers
            time.sleep(0.05)
        raise TimeoutError(
            f"collective group {self.group_name}: only {len(keys)}/"
            f"{self.world_size} ranks registered"
        )

    # -- p2p ------------------------------------------------------------
    async def _rpc_coll_send(self, conn, payload) -> dict:
        key = (payload["src"], payload["tag"])
        self._mailbox[key] = payload["data"]
        event = self._mailbox_events.setdefault(key, asyncio.Event())
        event.set()
        return {"status": "ok"}

    def send(self, array, dst_rank: int, tag: str = "") -> None:
        self.send_async(array, dst_rank, tag=tag).result()

    def send_async(self, payload, dst_rank: int, tag: str = ""):
        """Issue a p2p send and return its concurrent Future — the ring
        collectives double-buffer hops with this (next chunk's send goes
        out while the previous recv is still in flight on the shared
        async RPC lane). Sequence numbers are assigned at ISSUE time, so
        two in-flight sends to the same (dst, tag) stay ordered for the
        receiver's mailbox even if their frames interleave. ``payload``
        is any picklable object: an ndarray or a quantized wire tuple.
        """
        seq_key = (dst_rank, tag)
        seq = self._send_seq.get(seq_key, 0)
        self._send_seq[seq_key] = seq + 1
        data = pickle.dumps(
            np.asarray(payload) if isinstance(payload, (list, int, float))
            else payload
        )
        self.wire_stats["bytes_sent"] += len(data)
        self.wire_stats["msgs_sent"] += 1
        # Flight recorder (ISSUE 14): the wire-level record carries the
        # REAL mailbox (tag, seq) a hang report names; enqueued here at
        # issue time, launched when the frame goes out, completed when
        # the peer acks.
        rec = flight.p2p_started(
            self.group_name, "send", tag, seq, self.rank, dst_rank,
            self.world_size, nbytes=len(data),
        )

        async def _send():
            flight.launched(rec)
            client = await self.ctx._client_for(self._peer_addrs[dst_rank])
            await client.call(
                f"coll_send/{self.group_name}",
                {"src": self.rank, "tag": f"{tag}#{seq}", "data": data},
            )

        fut = asyncio.run_coroutine_threadsafe(_send(), self.ctx.io.loop)
        if rec is not None:
            fut.add_done_callback(
                lambda f: flight.completed(rec, ok=f.exception() is None)
            )
        return fut

    def recv(self, src_rank: int, tag: str = "", timeout: float = 60.0,
             like=None) -> np.ndarray:
        # `like` is the xla backend's static-shape template; host-memory
        # transfers carry their own metadata, so it is accepted and
        # ignored here for backend-portable call sites.
        seq_key = (src_rank, tag)
        seq = self._recv_seq.get(seq_key, 0)
        key = (src_rank, f"{tag}#{seq}")
        # Flight recorder (ISSUE 14): a recv blocked here is exactly what
        # the hang watchdog watches — the record names (group, tag, seq)
        # and the peer rank being waited on.
        rec = flight.p2p_started(
            self.group_name, "recv", tag, seq, self.rank, src_rank,
            self.world_size,
        )
        flight.launched(rec)

        async def _recv():
            event = self._mailbox_events.setdefault(key, asyncio.Event())
            await asyncio.wait_for(event.wait(), timeout)
            return self._mailbox.pop(key)

        try:
            data = self.ctx.io.run(_recv())
        except BaseException:
            flight.completed(rec, ok=False)
            raise
        flight.completed(rec)
        # Advance the stream only on success: a timed-out recv can be retried
        # for the SAME sequence number (otherwise every later message would be
        # delivered shifted by one).
        self._recv_seq[seq_key] = seq + 1
        self._mailbox_events.pop(key, None)
        return pickle.loads(data)

    # -- collectives ----------------------------------------------------
    def barrier(self) -> None:
        epoch = self._barrier_epoch
        self._barrier_epoch += 1
        token = np.zeros(1)
        tag = f"__barrier{epoch}"
        # Dissemination barrier: log2 rounds of peer notifications.
        round_num, step = 0, 1
        while step < self.world_size:
            dst = (self.rank + step) % self.world_size
            src = (self.rank - step) % self.world_size
            self.send(token, dst, tag=f"{tag}/r{round_num}")
            self.recv(src, tag=f"{tag}/r{round_num}")
            step *= 2
            round_num += 1

    def broadcast(self, array: np.ndarray, src_rank: int = 0, tag: str = "__bc") -> np.ndarray:
        if self.world_size == 1:
            return np.asarray(array)
        if self.rank == src_rank:
            for r in range(self.world_size):
                if r != src_rank:
                    self.send(array, r, tag=tag)
            return np.asarray(array)
        return self.recv(src_rank, tag=tag)

    def allgather(self, array: np.ndarray, tag: str = "__ag") -> list[np.ndarray]:
        """Ring all-gather: world_size-1 double-buffered neighbor hops."""
        if self.world_size == 1:
            return [np.asarray(array)]
        chunks: list[Any] = [None] * self.world_size
        chunks[self.rank] = np.asarray(array)
        next_rank = (self.rank + 1) % self.world_size
        prev_rank = (self.rank - 1) % self.world_size
        current = self.rank
        pending = None
        for _ in range(self.world_size - 1):
            if pending is not None:
                pending.result()
            pending = self.send_async(chunks[current], next_rank, tag=tag)
            current = (current - 1) % self.world_size
            chunks[current] = self.recv(prev_rank, tag=tag)
        pending.result()
        return chunks

    def _quantized(self, op: str, array: np.ndarray) -> bool:
        """The quantized wire only applies to SUM over floats (partial
        sums of dequantized blocks; min/max/product and integer arrays
        take the exact wire)."""
        return (
            self.config.enabled
            and op == SUM
            and array.dtype.kind == "f"
            and self.world_size > 1
        )

    def allreduce(self, array: np.ndarray, op: str = SUM, tag: str = "__ar") -> np.ndarray:
        """Ring reduce-scatter + all-gather (bandwidth-optimal).

        The wire carries the INPUT dtype (or the quantized encoding) —
        never an upcast; wide (f64) accumulation of float partial sums
        stays local to each hop's reduction.
        """
        array = np.asarray(array)
        if self.world_size == 1:
            return array
        if self._quantized(op, array):
            return self._allreduce_quantized(array, tag)
        reducer = _REDUCERS[op]
        wire_dtype = array.dtype
        acc_dtype = np.float64 if array.dtype.kind == "f" else array.dtype
        chunks = np.array_split(array.reshape(-1), self.world_size)
        # reduce-scatter, then all-gather of the reduced chunks
        self._ring_reduce_scatter(
            chunks, reducer, f"{tag}/rs", start_idx=self.rank,
            acc_dtype=acc_dtype, wire_dtype=wire_dtype,
        )
        next_rank = (self.rank + 1) % self.world_size
        prev_rank = (self.rank - 1) % self.world_size
        send_idx = (self.rank + 1) % self.world_size
        # The owned chunk goes back to wire dtype BEFORE the all-gather so
        # every rank reconstructs bitwise-identical values (the owner must
        # not keep a wider-precision copy the others never saw).
        chunks[send_idx] = chunks[send_idx].astype(wire_dtype, copy=False)
        pending = None
        for step in range(self.world_size - 1):
            if pending is not None:
                pending.result()
            pending = self.send_async(chunks[send_idx], next_rank, tag=f"{tag}/ag")
            recv_idx = (send_idx - 1) % self.world_size
            chunks[recv_idx] = self.recv(prev_rank, tag=f"{tag}/ag")
            send_idx = recv_idx
        pending.result()
        out = np.concatenate(chunks).astype(array.dtype)
        return out.reshape(array.shape)

    def _ring_reduce_scatter(
        self, chunks, reducer, tag, start_idx: int,
        acc_dtype=None, wire_dtype=None,
    ) -> int:
        """N-1 double-buffered ring rounds; afterwards this rank holds the
        fully-reduced chunk at index (start_idx + 1) % world_size
        (returned). Outgoing partials are cast to ``wire_dtype``; the
        local reduction runs in ``acc_dtype`` (wide accumulation never
        crosses the wire)."""
        next_rank = (self.rank + 1) % self.world_size
        prev_rank = (self.rank - 1) % self.world_size
        send_idx = start_idx
        pending = None
        for step in range(self.world_size - 1):
            out = chunks[send_idx]
            if wire_dtype is not None and out.dtype != wire_dtype:
                out = out.astype(wire_dtype)
            if pending is not None:
                pending.result()
            pending = self.send_async(out, next_rank, tag=tag)
            recv_idx = (send_idx - 1) % self.world_size
            incoming = self.recv(prev_rank, tag=tag)
            local = chunks[recv_idx]
            if acc_dtype is not None:
                local = local.astype(acc_dtype, copy=False)
                incoming = incoming.astype(acc_dtype, copy=False)
            chunks[recv_idx] = reducer(local, incoming)
            send_idx = recv_idx
        if pending is not None:
            pending.result()
        return send_idx

    def _allreduce_quantized(self, array: np.ndarray, tag: str) -> np.ndarray:
        """Block-scaled quantized ring allreduce (SUM only, EQuARX-style).

        Reduce-scatter: each hop's outgoing chunk is quantized through
        the persistent error-feedback residual for that (tag, step) site;
        the receiver dequantizes and accumulates in f32. All-gather: the
        owner of each fully-reduced chunk encodes it ONCE (again through
        error feedback), and downstream ranks forward the encoded tuple
        VERBATIM — no re-quantization error per hop, and every rank
        decodes the same bytes, so results are identical group-wide.
        """
        next_rank = (self.rank + 1) % self.world_size
        prev_rank = (self.rank - 1) % self.world_size
        flat = array.reshape(-1).astype(np.float32)
        chunks = np.array_split(flat, self.world_size)
        send_idx = self.rank
        pending = None
        for step in range(self.world_size - 1):
            enc = self._ef.encode(
                ("rs", tag, step), chunks[send_idx], self.config
            )
            if pending is not None:
                pending.result()
            pending = self.send_async(enc, next_rank, tag=f"{tag}/rs")
            recv_idx = (send_idx - 1) % self.world_size
            incoming = _q_decode(self.recv(prev_rank, tag=f"{tag}/rs"))
            chunks[recv_idx] = chunks[recv_idx] + incoming
            send_idx = recv_idx
        if pending is not None:
            pending.result()
            pending = None
        owned = (self.rank + 1) % self.world_size
        encoded: dict[int, tuple] = {
            owned: self._ef.encode(("ag", tag), chunks[owned], self.config)
        }
        send_idx = owned
        for step in range(self.world_size - 1):
            if pending is not None:
                pending.result()
            pending = self.send_async(encoded[send_idx], next_rank, tag=f"{tag}/ag")
            recv_idx = (send_idx - 1) % self.world_size
            encoded[recv_idx] = self.recv(prev_rank, tag=f"{tag}/ag")
            send_idx = recv_idx
        pending.result()
        out = np.concatenate(
            [_q_decode(encoded[i]) for i in range(self.world_size)]
        )
        return out.astype(array.dtype).reshape(array.shape)

    def reducescatter(self, array: np.ndarray, op: str = SUM) -> np.ndarray:
        """Each rank gets its 1/world_size slice of the reduction. Runs ONLY
        the reduce-scatter phase (half an allreduce's communication)."""
        array = np.asarray(array)
        if self.world_size == 1:
            return array.reshape(-1)
        reducer = _REDUCERS[op]
        wire_dtype = array.dtype
        acc_dtype = np.float64 if array.dtype.kind == "f" else array.dtype
        chunks = np.array_split(array.reshape(-1), self.world_size)
        # Starting one chunk earlier makes the fully-reduced chunk land on
        # index == self.rank, matching the allreduce-based semantics.
        owned = self._ring_reduce_scatter(
            chunks, reducer, "__rsc/rs",
            start_idx=(self.rank - 1) % self.world_size,
            acc_dtype=acc_dtype, wire_dtype=wire_dtype,
        )
        assert owned == self.rank
        return chunks[self.rank].astype(array.dtype)

    def destroy(self) -> None:
        self._kv(
            "kv_del",
            {"namespace": "collective", "key": f"{self.group_name}/rank/{self.rank}"},
        )


# ---------------------------------------------------------------------------
# xla backend (device collectives over the local / global jax mesh)
# ---------------------------------------------------------------------------
class XlaGroup(BaseGroup):
    """Elementwise collectives ACROSS RANKS, executed as XLA programs.

    Semantics match RingGroup (each rank contributes one array, every rank
    gets the reduction). Requirements: either world_size == 1 (trivial), or
    every gang member shares one jax.distributed runtime
    (jax.process_count() == world_size) so the collective rides ICI/DCN
    between processes. Single-process multi-device reductions are NOT group
    collectives — use jax.lax.psum inside your own jit for those (the in-jit
    fusion path, SURVEY §7.0.4).
    """

    backend_name = "xla"

    def __init__(
        self,
        world_size: int,
        rank: int,
        group_name: str,
        config: CollectiveConfig | None = None,
    ):
        # `config` is accepted for signature parity; the XLA data plane
        # has its own on-wire formats (quantization would fight the
        # compiler), so it is ignored here.
        super().__init__(world_size, rank, group_name, config=config)
        import jax

        self._jax = jax
        if world_size > 1 and jax.process_count() != world_size:
            raise RuntimeError(
                "xla backend needs one jax.distributed runtime spanning the "
                f"gang (jax.process_count()={jax.process_count()} != "
                f"world_size={world_size}); use backend='ring' for plain "
                "actor groups"
            )
        # One device per process carries that rank's contribution.
        if world_size > 1:
            per_process = {}
            for device in jax.devices():
                per_process.setdefault(device.process_index, device)
            self._rank_devices = [per_process[i] for i in range(world_size)]
        self._p2p_cache: dict = {}

    def _cross_rank(self, array, reducer):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.array(self._rank_devices), ("ranks",))
        sharding = NamedSharding(mesh, P("ranks"))
        local = jnp.asarray(array)[None]
        global_arr = jax.make_array_from_single_device_arrays(
            (self.world_size, *local.shape[1:]),
            sharding,
            [jax.device_put(local, self._rank_devices[self.rank])],
        )
        out = jax.jit(
            reducer, out_shardings=NamedSharding(mesh, P())
        )(global_arr)
        return np.asarray(out.addressable_data(0))

    def allreduce(self, array, op: str = SUM):
        import jax.numpy as jnp

        reducers = {
            SUM: lambda a: jnp.sum(a, axis=0),
            MAX: lambda a: jnp.max(a, axis=0),
            MIN: lambda a: jnp.min(a, axis=0),
            PRODUCT: lambda a: jnp.prod(a, axis=0),
        }
        if op not in reducers:
            raise ValueError(f"xla backend does not support op={op}")
        if self.world_size == 1:
            return np.asarray(array)
        return self._cross_rank(array, reducers[op])

    def allgather(self, array):
        if self.world_size == 1:
            return [np.asarray(array)]
        stacked = self._cross_rank(array, lambda a: a)
        return list(stacked)

    def broadcast(self, array, src_rank: int = 0):
        if self.world_size == 1:
            return np.asarray(array)
        return self.allgather(array)[src_rank]

    def reducescatter(self, array, op: str = SUM):
        reduced = self.allreduce(array, op=op)
        return np.array_split(reduced.reshape(-1), self.world_size)[self.rank]

    def barrier(self):
        self.allreduce(np.zeros((1,), np.float32))

    def p2p(self, array, src_rank: int, dst_rank: int):
        """Point-to-point as an XLA collective: ONE ppermute over the rank
        mesh moves src's block to dst over ICI/DCN (device-to-device — no
        host round trip). SPMD contract: EVERY rank in the group calls
        p2p with the SAME (src, dst) pair (bystanders pass a zeros
        template; their block is discarded) — exactly like the
        reference's NCCL send/recv, which is also a paired collective.
        Returns the transferred array on dst; None elsewhere."""
        import jax

        if src_rank == dst_rank:
            raise ValueError("p2p with src_rank == dst_rank is a local copy")
        array = np.asarray(array)
        key = (array.shape, array.dtype.str, src_rank, dst_rank)
        shift = self._p2p_cache.get(key)
        if shift is None:
            import jax.numpy as jnp
            from jax.experimental.shard_map import shard_map
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            mesh = Mesh(np.array(self._rank_devices), ("ranks",))
            sharding = NamedSharding(mesh, P("ranks"))

            def permute(block):
                return jax.lax.ppermute(
                    block, "ranks", perm=[(src_rank, dst_rank)]
                )

            jitted = jax.jit(
                shard_map(
                    permute, mesh=mesh, in_specs=P("ranks"),
                    out_specs=P("ranks"),
                )
            )

            def shift(local_np):
                local = jnp.asarray(local_np)[None]
                global_arr = jax.make_array_from_single_device_arrays(
                    (self.world_size, *local.shape[1:]),
                    sharding,
                    [jax.device_put(local, self._rank_devices[self.rank])],
                )
                return jitted(global_arr)

            # Cache the jitted program: a per-step halo exchange must not
            # retrace/recompile on every call.
            self._p2p_cache[key] = shift
        out = shift(array)
        if self.rank != dst_rank:
            return None
        return np.asarray(out.addressable_data(0))[0]

    def send(self, array, dst_rank: int, tag: str = ""):
        """p2p send over the XLA mesh. The destination must concurrently
        call ``recv(src_rank=<this rank>, like=<same shape/dtype>)`` and,
        for world_size > 2, every OTHER rank must enter
        ``p2p(zeros_template, src, dst)`` — one ppermute program across
        the whole group (paired-collective semantics, like NCCL p2p)."""
        if dst_rank == self.rank:
            raise ValueError("xla send to self is unsupported")
        self.p2p(np.asarray(array), self.rank, dst_rank)

    def recv(
        self, src_rank: int, tag: str = "", timeout: float = 60.0,
        like=None,
    ):
        """p2p receive: ``like`` supplies the shape/dtype of the incoming
        array (XLA programs are shape-static; the reference's NCCL recv
        takes a pre-allocated tensor the same way)."""
        if like is None:
            raise ValueError(
                "xla recv needs like=<array of the incoming shape/dtype> "
                "(shape-static paired collective)"
            )
        if src_rank == self.rank:
            raise ValueError("xla recv from self is unsupported")
        return self.p2p(np.zeros_like(like), src_rank, self.rank)

    def destroy(self):
        pass


# ---------------------------------------------------------------------------
# hierarchical backend (two tiers: in-jit ICI reduce, then DCN ring)
# ---------------------------------------------------------------------------
class HierarchicalGroup(BaseGroup):
    """Two-tier collectives (SURVEY §5.8 "reduce within the slice, then
    across"): tier 1 reduces this host's device shards in ONE jit via
    shard_map+psum over the local jax mesh (the ICI tier — XLA fuses and
    keeps it on-chip); tier 2 reduces the per-host partials across gang
    members over the framework's RPC ring (the DCN tier). Unlike the "xla"
    backend this needs NO global jax.distributed runtime — each host runs
    its own jax, so it is the multi-SLICE shape where ICI does not span
    hosts and traffic must cross the data-center network.
    """

    _TIER1 = {"sum": "psum", "max": "pmax", "min": "pmin"}
    _TIER1_HOST = {
        "sum": np.add.reduce,
        "max": np.maximum.reduce,
        "min": np.minimum.reduce,
    }
    # Below this many TOTAL bytes across the local shards, tier-1 reduces
    # on host: device dispatch (transfer + program launch) has a fixed
    # cost that dwarfs the reduction itself for tiny gradients, while the
    # DCN tier still carries the single collapsed partial either way.
    _TIER1_HOST_BYTES = int(
        os.environ.get("RAY_TPU_TIER1_HOST_BYTES", 1 << 20)
    )

    backend_name = "hier"

    def __init__(
        self,
        world_size: int,
        rank: int,
        group_name: str,
        config: CollectiveConfig | None = None,
    ):
        super().__init__(world_size, rank, group_name, config=config)
        # The DCN tier rides the ring group's controller-KV rendezvous +
        # p2p — and inherits this group's CollectiveConfig, so quantized
        # wire compression applies exactly where bandwidth is scarce
        # (cross-host), never to the in-jit ICI tier.
        self._ring = RingGroup(
            world_size, rank, group_name + "@dcn", config=config
        )
        # Surface the DCN tier's wire accounting as this group's own.
        self.wire_stats = self._ring.wire_stats
        # Tier-1 programs cached per (ndev, shape, dtype, op): a per-step
        # gradient sync must not retrace/recompile on every call.
        self._tier1_cache: dict = {}

    def _local_reduce(self, per_device_arrays: list, op: str) -> np.ndarray:
        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        if op not in self._TIER1:
            raise ValueError(
                f"hierarchical backend supports ops {sorted(self._TIER1)}"
            )
        devices = jax.local_devices()[: len(per_device_arrays)]
        if len(devices) < len(per_device_arrays):
            raise ValueError(
                f"{len(per_device_arrays)} shards for {len(devices)} local devices"
            )
        shape = np.asarray(per_device_arrays[0]).shape
        dtype = np.asarray(per_device_arrays[0]).dtype
        total_bytes = int(dtype.itemsize * np.prod(shape)) * len(
            per_device_arrays
        )
        if total_bytes <= self._TIER1_HOST_BYTES:
            stacked = np.stack(
                [np.asarray(a) for a in per_device_arrays]
            )
            return self._TIER1_HOST[op](stacked, axis=0)
        key = (len(devices), shape, dtype.str, op)
        cached = self._tier1_cache.get(key)
        if cached is None:
            mesh = Mesh(np.array(devices), ("local",))
            sharding = NamedSharding(mesh, P("local"))
            prim = getattr(jax.lax, self._TIER1[op])
            jitted = jax.jit(
                shard_map(
                    # each device's block is (1, *shape): reduce over the
                    # mesh axis, then drop the block dim.
                    lambda x: prim(x, "local")[0],
                    mesh=mesh,
                    in_specs=P("local"),
                    out_specs=P(),
                )
            )
            cached = (devices, sharding, jitted)
            self._tier1_cache[key] = cached
        devices, sharding, jitted = cached
        # ONE sharded transfer (the sharding routes each row to its
        # device) — far cheaper than a device_put per shard.
        stacked = jax.device_put(
            np.stack([np.asarray(a) for a in per_device_arrays]), sharding
        )
        return np.asarray(jitted(stacked))

    def allreduce_sharded(
        self, per_device_arrays: list, op: str = SUM, tag: str = "__hier"
    ) -> np.ndarray:
        """Reduce one shard per local device across ALL hosts' devices:
        tier-1 in-jit psum over the local mesh, tier-2 ring across hosts.
        ``tag`` isolates concurrent reductions (the overlap path runs one
        per bucket in flight) and keys the DCN tier's EF residuals."""
        partial = self._local_reduce(per_device_arrays, op)
        return self._ring.allreduce(partial, op=op, tag=tag)

    # Host-level (single array per rank) collectives delegate to the ring:
    # the hierarchy only matters when device shards are in play.
    def allreduce(self, array, op: str = SUM, tag: str = "__ar"):
        return self._ring.allreduce(np.asarray(array), op=op, tag=tag)

    def allgather(self, array):
        return self._ring.allgather(np.asarray(array))

    def reducescatter(self, array, op: str = SUM):
        return self._ring.reducescatter(np.asarray(array), op=op)

    def broadcast(self, array, src_rank: int = 0):
        return self._ring.broadcast(np.asarray(array), src_rank=src_rank)

    def barrier(self):
        self._ring.barrier()

    def send(self, array, dst_rank: int, tag: str = ""):
        self._ring.send(array, dst_rank, tag=tag)

    def recv(self, src_rank: int, tag: str = "", timeout: float = 60.0,
             like=None):
        # Forward `like` too: the parameter is part of the unified
        # BaseGroup signature and backend-portable call sites pass it
        # positionally-equivalently on every backend.
        return self._ring.recv(src_rank, tag=tag, timeout=timeout, like=like)

    def destroy(self):
        self._ring.destroy()


# ---------------------------------------------------------------------------
# public API (reference signatures)
# ---------------------------------------------------------------------------
def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "ring",
    group_name: str = "default",
    config: CollectiveConfig | None = None,
) -> None:
    if group_name in _groups:
        raise ValueError(f"collective group {group_name!r} already initialized")
    if backend in ("ring", "gloo"):
        cls = RingGroup
    elif backend == "xla":
        cls = XlaGroup
    elif backend in ("hier", "hierarchical"):
        cls = HierarchicalGroup
    else:
        raise ValueError(
            f"unknown backend {backend!r} (use 'ring', 'xla', or 'hier')"
        )
    _groups[group_name] = cls(world_size, rank, group_name, config=config)


def get_group(group_name: str = "default") -> BaseGroup:
    if group_name not in _groups:
        raise ValueError(f"collective group {group_name!r} not initialized")
    return _groups[group_name]


_op_tls = threading.local()


# Default tags the group methods use when the caller passes none — the
# flight-recorder channel id must match what actually rides the wire.
_DEFAULT_TAGS = {
    "allreduce": "__ar",
    "allreduce_sharded": "__ar",
    "allgather": "__ag",
    "reducescatter": "__rs",
    "broadcast": "__bc",
    "barrier": "__barrier",
}


def _instrumented(op: str, group: BaseGroup, array, call, tag=None):
    """Run one collective op with full observability: the collective.*
    span carries op + backend + logical bytes + measured wire bytes, and
    the op feeds the rt_collective_* Prometheus series (bytes total +
    latency histogram) so summarize_latency()/summarize_comm() can break
    out comm time per backend.

    Reentrant calls (module wrapper -> group method, hierarchical ->
    inner DCN ring, broadcast -> send/recv) record NOTHING — one span
    and one metrics sample per user-visible op, attributed to the
    outermost backend."""
    if getattr(_op_tls, "active", False):
        return call()
    _op_tls.active = True
    try:
        return _instrumented_outer(op, group, array, call, tag=tag)
    finally:
        _op_tls.active = False


def _instrumented_outer(op: str, group: BaseGroup, array, call, tag=None):
    backend = getattr(group, "backend_name", type(group).__name__)
    if isinstance(array, (list, tuple)):  # allreduce_sharded: shard list
        nbytes = sum(getattr(a, "nbytes", 0) for a in array) or None
    else:
        nbytes = getattr(array, "nbytes", None)
    wire = getattr(group, "wire_stats", None)
    wire_before = wire["bytes_sent"] if wire else 0
    # Chaos (ISSUE 14): a windowed per-rank latency point simulates a
    # straggler that hasn't REACHED the collective yet — it sleeps before
    # the flight record exists, so the laggard's evidence is an absent
    # record, exactly what the hang report keys on.
    stall_delay = chaos.latency_delay(f"collective.{op}.rank{group.rank}")
    if stall_delay > 0:
        time.sleep(stall_delay)
    tag = tag if tag is not None else _DEFAULT_TAGS.get(op, "")
    rec = flight.op_started(
        group.group_name, op, tag, group.rank, group.world_size,
        nbytes=nbytes or 0, backend=backend,
    )
    start = time.perf_counter()
    if tracing.enabled():
        attrs = {
            "group": group.group_name,
            "world_size": group.world_size,
            "rank": group.rank,
            "backend": backend,
            "op": op,
        }
        if nbytes is not None:
            attrs["bytes"] = int(nbytes)
        if rec is not None:
            # Joinable observability (ISSUE 14 satellite): the span
            # carries the flight (seq, channel); the ring entry carries
            # the trace id — hang reports and `ray_tpu timeline` meet
            # on either key.
            attrs["comm_seq"] = rec.seq
            attrs["comm_channel"] = rec.channel
        with tracing.span(f"collective.{op}", **attrs) as span:
            if span is not None and rec is not None:
                rec.trace_id = span.trace_id
            ok = False
            try:
                result = _chaos_uniform_then(call)
                ok = True
            finally:
                flight.completed(rec, ok=ok)
            if span is not None and wire is not None:
                span.attributes["wire_bytes"] = (
                    wire["bytes_sent"] - wire_before
                )
    else:
        ok = False
        try:
            result = _chaos_uniform_then(call)
            ok = True
        finally:
            flight.completed(rec, ok=ok)
    elapsed = time.perf_counter() - start
    wire_delta = (wire["bytes_sent"] - wire_before) if wire else 0
    # Flight recorder (ISSUE 8): inside a train session this wall time is
    # the step's "collective" phase; outside one it's a no-op bool check.
    from ray_tpu.train._internal import step_stats

    step_stats.record_phase("collective", elapsed)
    from ray_tpu.util import metrics

    metrics.record_collective_op(
        op=op,
        backend=backend,
        # Ring-family backends report true serialized wire bytes; the
        # device-mesh backend reports the logical payload instead.
        nbytes=wire_delta if wire_delta else int(nbytes or 0),
        seconds=elapsed,
    )
    return result


def _chaos_uniform_then(call):
    """Uniform-slowness injection point (false-positive guard, ISSUE 14):
    unlike the per-rank point above, this sleeps INSIDE the flight
    record on every rank that arms it, so completed-op durations carry
    the slowness and the adaptive p95 deadline must absorb it."""
    delay = chaos.latency_delay("collective.op.uniform")
    if delay > 0:
        time.sleep(delay)
    return call()


def allreduce(array, group_name: str = "default", op: str = SUM):
    group = get_group(group_name)
    return _instrumented(
        "allreduce", group, array, lambda: group.allreduce(array, op=op)
    )


def allgather(array, group_name: str = "default"):
    group = get_group(group_name)
    return _instrumented(
        "allgather", group, array, lambda: group.allgather(array)
    )


def reducescatter(array, group_name: str = "default", op: str = SUM):
    group = get_group(group_name)
    return _instrumented(
        "reducescatter", group, array,
        lambda: group.reducescatter(array, op=op),
    )


def broadcast(array, src_rank: int = 0, group_name: str = "default"):
    group = get_group(group_name)
    return _instrumented(
        "broadcast", group, array,
        lambda: group.broadcast(array, src_rank=src_rank),
    )


def barrier(group_name: str = "default"):
    group = get_group(group_name)
    return _instrumented("barrier", group, None, group.barrier)


def send(array, dst_rank: int, group_name: str = "default"):
    group = get_group(group_name)
    return _instrumented(
        "send", group, array, lambda: group.send(array, dst_rank)
    )


def recv(
    src_rank: int, group_name: str = "default", timeout: float = 60.0,
    like=None,
):
    group = get_group(group_name)
    if like is not None:
        return group.recv(src_rank, timeout=timeout, like=like)
    return group.recv(src_rank, timeout=timeout)


def _traced_method(op: str, fn):
    # Where the method's ``tag`` parameter sits positionally (past
    # ``self``), resolved once at wrap time — op strings ("max") and
    # tags are both str, so a scan-for-str heuristic would misfire.
    try:
        params = list(inspect.signature(fn).parameters)
        tag_pos = params.index("tag") - 1
    except ValueError:
        tag_pos = None

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        payload = args[0] if args else None
        tag = kwargs.get("tag")
        if tag is None and tag_pos is not None and len(args) > tag_pos:
            candidate = args[tag_pos]
            if isinstance(candidate, str):
                tag = candidate
        return _instrumented(
            op, self, payload, lambda: fn(self, *args, **kwargs), tag=tag
        )
    return wrapper


# Instrument the GROUP methods themselves, not just the module-level
# wrappers above: trainers and gang code hold the group object
# (ctx.collective(), sync_gradients) and call it directly, and those
# calls must land in the same collective.* spans / rt_collective_*
# series. The thread-local guard in _instrumented collapses the nesting
# to one span per user-visible op.
for _cls in (RingGroup, XlaGroup, HierarchicalGroup):
    for _op in (
        "allreduce", "allreduce_sharded", "allgather", "reducescatter",
        "broadcast", "barrier", "send", "recv",
    ):
        _fn = _cls.__dict__.get(_op)
        if _fn is not None:
            setattr(_cls, _op, _traced_method(_op, _fn))


def destroy_collective_group(group_name: str = "default") -> None:
    group = _groups.pop(group_name, None)
    if group is not None:
        group.destroy()

"""Process-group collectives for actors.

Role-equivalent of python/ray/util/collective/collective.py
(:: init_collective_group, allreduce, allgather, reducescatter, broadcast,
barrier, send, recv) with the reference's NCCL/Gloo backends replaced by
(SURVEY §5.8):

  * "xla"  — the TPU data plane: collectives compile into XLA programs over
    the caller's jax device mesh (psum/all_gather/... on ICI). Multi-host
    gangs share one global jax runtime via jax.distributed (rendezvous
    coordinates come from the gang, §gang.py); a single host's chips work
    out of the box.
  * "ring" — host-memory ring collectives over the framework's own RPC p2p
    (reduce-scatter + all-gather ring), the Gloo-equivalent CPU fallback
    AND the hostless test twin (SURVEY §4.4.4).

Rendezvous replaces the reference's NCCL-unique-id "Info" actor with the
controller KV [N6].
"""

from __future__ import annotations

import asyncio
import contextlib
import pickle
import time
from typing import Any

import numpy as np

from ray_tpu._private import worker as worker_mod
from ray_tpu.util import tracing

_groups: dict[str, "BaseGroup"] = {}

SUM, PRODUCT, MIN, MAX = "sum", "product", "min", "max"
_REDUCERS = {SUM: np.add, PRODUCT: np.multiply, MIN: np.minimum, MAX: np.maximum}


class BaseGroup:
    def __init__(self, world_size: int, rank: int, group_name: str):
        self.world_size = world_size
        self.rank = rank
        self.group_name = group_name

    # subclasses implement: allreduce, allgather, reducescatter, broadcast,
    # barrier, send, recv, destroy

    def p2p(self, array, src_rank: int, dst_rank: int):
        """Group-wide p2p entry point: every rank calls with the same
        (src, dst); returns the array on dst, None elsewhere. Host-memory
        backends only involve the endpoints; the xla backend overrides
        this with a true all-rank ppermute collective."""
        if self.rank == src_rank:
            self.send(np.asarray(array), dst_rank)
            return None
        if self.rank == dst_rank:
            return self.recv(src_rank)
        return None


# ---------------------------------------------------------------------------
# ring backend (host memory over RPC p2p)
# ---------------------------------------------------------------------------
class RingGroup(BaseGroup):
    def __init__(self, world_size: int, rank: int, group_name: str):
        super().__init__(world_size, rank, group_name)
        self.ctx = worker_mod.get_global_context()
        self._mailbox: dict[tuple, Any] = {}
        self._mailbox_events: dict[tuple, asyncio.Event] = {}
        self.ctx.core_server.route(
            f"coll_send/{group_name}", self._rpc_coll_send
        )
        self._register()
        self._peer_addrs = self._resolve_peers()
        self._barrier_epoch = 0
        self._send_seq: dict[tuple, int] = {}
        self._recv_seq: dict[tuple, int] = {}

    # -- rendezvous via controller KV ----------------------------------
    def _kv(self, method: str, payload: dict) -> Any:
        return self.ctx.io.run(self.ctx.controller.call(method, payload))

    def _register(self) -> None:
        self._kv(
            "kv_put",
            {
                "namespace": "collective",
                "key": f"{self.group_name}/rank/{self.rank}",
                "value": pickle.dumps(tuple(self.ctx.address)),
            },
        )

    def _resolve_peers(self) -> dict[int, tuple]:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            keys = self._kv(
                "kv_keys",
                {"namespace": "collective", "prefix": f"{self.group_name}/rank/"},
            )
            if len(keys) >= self.world_size:
                peers = {}
                for r in range(self.world_size):
                    resp = self._kv(
                        "kv_get",
                        {
                            "namespace": "collective",
                            "key": f"{self.group_name}/rank/{r}",
                        },
                    )
                    peers[r] = pickle.loads(resp["value"])
                return peers
            time.sleep(0.05)
        raise TimeoutError(
            f"collective group {self.group_name}: only {len(keys)}/"
            f"{self.world_size} ranks registered"
        )

    # -- p2p ------------------------------------------------------------
    async def _rpc_coll_send(self, conn, payload) -> dict:
        key = (payload["src"], payload["tag"])
        self._mailbox[key] = payload["data"]
        event = self._mailbox_events.setdefault(key, asyncio.Event())
        event.set()
        return {"status": "ok"}

    def send(self, array: np.ndarray, dst_rank: int, tag: str = "") -> None:
        seq_key = (dst_rank, tag)
        seq = self._send_seq.get(seq_key, 0)
        self._send_seq[seq_key] = seq + 1

        async def _send():
            client = await self.ctx._client_for(self._peer_addrs[dst_rank])
            await client.call(
                f"coll_send/{self.group_name}",
                {
                    "src": self.rank,
                    "tag": f"{tag}#{seq}",
                    "data": pickle.dumps(np.asarray(array)),
                },
            )

        self.ctx.io.run(_send())

    def recv(self, src_rank: int, tag: str = "", timeout: float = 60.0,
             like=None) -> np.ndarray:
        # `like` is the xla backend's static-shape template; host-memory
        # transfers carry their own metadata, so it is accepted and
        # ignored here for backend-portable call sites.
        seq_key = (src_rank, tag)
        seq = self._recv_seq.get(seq_key, 0)
        key = (src_rank, f"{tag}#{seq}")

        async def _recv():
            event = self._mailbox_events.setdefault(key, asyncio.Event())
            await asyncio.wait_for(event.wait(), timeout)
            return self._mailbox.pop(key)

        data = self.ctx.io.run(_recv())
        # Advance the stream only on success: a timed-out recv can be retried
        # for the SAME sequence number (otherwise every later message would be
        # delivered shifted by one).
        self._recv_seq[seq_key] = seq + 1
        self._mailbox_events.pop(key, None)
        return pickle.loads(data)

    # -- collectives ----------------------------------------------------
    def barrier(self) -> None:
        epoch = self._barrier_epoch
        self._barrier_epoch += 1
        token = np.zeros(1)
        tag = f"__barrier{epoch}"
        # Dissemination barrier: log2 rounds of peer notifications.
        round_num, step = 0, 1
        while step < self.world_size:
            dst = (self.rank + step) % self.world_size
            src = (self.rank - step) % self.world_size
            self.send(token, dst, tag=f"{tag}/r{round_num}")
            self.recv(src, tag=f"{tag}/r{round_num}")
            step *= 2
            round_num += 1

    def broadcast(self, array: np.ndarray, src_rank: int = 0, tag: str = "__bc") -> np.ndarray:
        if self.world_size == 1:
            return np.asarray(array)
        if self.rank == src_rank:
            for r in range(self.world_size):
                if r != src_rank:
                    self.send(array, r, tag=tag)
            return np.asarray(array)
        return self.recv(src_rank, tag=tag)

    def allgather(self, array: np.ndarray, tag: str = "__ag") -> list[np.ndarray]:
        """Ring all-gather: world_size-1 neighbor hops."""
        if self.world_size == 1:
            return [np.asarray(array)]
        chunks: list[Any] = [None] * self.world_size
        chunks[self.rank] = np.asarray(array)
        next_rank = (self.rank + 1) % self.world_size
        prev_rank = (self.rank - 1) % self.world_size
        current = self.rank
        for _ in range(self.world_size - 1):
            self.send(chunks[current], next_rank, tag=tag)
            current = (current - 1) % self.world_size
            chunks[current] = self.recv(prev_rank, tag=tag)
        return chunks

    def allreduce(self, array: np.ndarray, op: str = SUM, tag: str = "__ar") -> np.ndarray:
        """Ring reduce-scatter + all-gather (bandwidth-optimal)."""
        reducer = _REDUCERS[op]
        array = np.asarray(array)
        if self.world_size == 1:
            return array
        flat = array.reshape(-1).astype(np.float64 if array.dtype.kind == "f" else array.dtype)
        chunks = np.array_split(flat, self.world_size)
        next_rank = (self.rank + 1) % self.world_size
        # reduce-scatter, then all-gather of the reduced chunks
        self._ring_reduce_scatter(chunks, reducer, f"{tag}/rs", start_idx=self.rank)
        prev_rank = (self.rank - 1) % self.world_size
        send_idx = (self.rank + 1) % self.world_size
        for step in range(self.world_size - 1):
            self.send(chunks[send_idx], next_rank, tag=f"{tag}/ag")
            recv_idx = (send_idx - 1) % self.world_size
            chunks[recv_idx] = self.recv(prev_rank, tag=f"{tag}/ag")
            send_idx = recv_idx
        out = np.concatenate(chunks).astype(array.dtype)
        return out.reshape(array.shape)

    def _ring_reduce_scatter(self, chunks, reducer, tag, start_idx: int) -> int:
        """N-1 ring rounds; afterwards this rank holds the fully-reduced
        chunk at index (start_idx + 1) % world_size (returned)."""
        next_rank = (self.rank + 1) % self.world_size
        prev_rank = (self.rank - 1) % self.world_size
        send_idx = start_idx
        for step in range(self.world_size - 1):
            self.send(chunks[send_idx], next_rank, tag=tag)
            recv_idx = (send_idx - 1) % self.world_size
            incoming = self.recv(prev_rank, tag=tag)
            chunks[recv_idx] = reducer(chunks[recv_idx], incoming)
            send_idx = recv_idx
        return send_idx

    def reducescatter(self, array: np.ndarray, op: str = SUM) -> np.ndarray:
        """Each rank gets its 1/world_size slice of the reduction. Runs ONLY
        the reduce-scatter phase (half an allreduce's communication)."""
        if self.world_size == 1:
            return np.asarray(array).reshape(-1)
        reducer = _REDUCERS[op]
        flat = array.reshape(-1).astype(
            np.float64 if array.dtype.kind == "f" else array.dtype
        )
        chunks = np.array_split(flat, self.world_size)
        # Starting one chunk earlier makes the fully-reduced chunk land on
        # index == self.rank, matching the allreduce-based semantics.
        owned = self._ring_reduce_scatter(
            chunks, reducer, "__rsc/rs", start_idx=(self.rank - 1) % self.world_size
        )
        assert owned == self.rank
        return chunks[self.rank].astype(array.dtype)

    def destroy(self) -> None:
        self._kv(
            "kv_del",
            {"namespace": "collective", "key": f"{self.group_name}/rank/{self.rank}"},
        )


# ---------------------------------------------------------------------------
# xla backend (device collectives over the local / global jax mesh)
# ---------------------------------------------------------------------------
class XlaGroup(BaseGroup):
    """Elementwise collectives ACROSS RANKS, executed as XLA programs.

    Semantics match RingGroup (each rank contributes one array, every rank
    gets the reduction). Requirements: either world_size == 1 (trivial), or
    every gang member shares one jax.distributed runtime
    (jax.process_count() == world_size) so the collective rides ICI/DCN
    between processes. Single-process multi-device reductions are NOT group
    collectives — use jax.lax.psum inside your own jit for those (the in-jit
    fusion path, SURVEY §7.0.4).
    """

    def __init__(self, world_size: int, rank: int, group_name: str):
        super().__init__(world_size, rank, group_name)
        import jax

        self._jax = jax
        if world_size > 1 and jax.process_count() != world_size:
            raise RuntimeError(
                "xla backend needs one jax.distributed runtime spanning the "
                f"gang (jax.process_count()={jax.process_count()} != "
                f"world_size={world_size}); use backend='ring' for plain "
                "actor groups"
            )
        # One device per process carries that rank's contribution.
        if world_size > 1:
            per_process = {}
            for device in jax.devices():
                per_process.setdefault(device.process_index, device)
            self._rank_devices = [per_process[i] for i in range(world_size)]
        self._p2p_cache: dict = {}

    def _cross_rank(self, array, reducer):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.array(self._rank_devices), ("ranks",))
        sharding = NamedSharding(mesh, P("ranks"))
        local = jnp.asarray(array)[None]
        global_arr = jax.make_array_from_single_device_arrays(
            (self.world_size, *local.shape[1:]),
            sharding,
            [jax.device_put(local, self._rank_devices[self.rank])],
        )
        out = jax.jit(
            reducer, out_shardings=NamedSharding(mesh, P())
        )(global_arr)
        return np.asarray(out.addressable_data(0))

    def allreduce(self, array, op: str = SUM):
        import jax.numpy as jnp

        reducers = {
            SUM: lambda a: jnp.sum(a, axis=0),
            MAX: lambda a: jnp.max(a, axis=0),
            MIN: lambda a: jnp.min(a, axis=0),
            PRODUCT: lambda a: jnp.prod(a, axis=0),
        }
        if op not in reducers:
            raise ValueError(f"xla backend does not support op={op}")
        if self.world_size == 1:
            return np.asarray(array)
        return self._cross_rank(array, reducers[op])

    def allgather(self, array):
        if self.world_size == 1:
            return [np.asarray(array)]
        stacked = self._cross_rank(array, lambda a: a)
        return list(stacked)

    def broadcast(self, array, src_rank: int = 0):
        if self.world_size == 1:
            return np.asarray(array)
        return self.allgather(array)[src_rank]

    def reducescatter(self, array, op: str = SUM):
        reduced = self.allreduce(array, op=op)
        return np.array_split(reduced.reshape(-1), self.world_size)[self.rank]

    def barrier(self):
        self.allreduce(np.zeros((1,), np.float32))

    def p2p(self, array, src_rank: int, dst_rank: int):
        """Point-to-point as an XLA collective: ONE ppermute over the rank
        mesh moves src's block to dst over ICI/DCN (device-to-device — no
        host round trip). SPMD contract: EVERY rank in the group calls
        p2p with the SAME (src, dst) pair (bystanders pass a zeros
        template; their block is discarded) — exactly like the
        reference's NCCL send/recv, which is also a paired collective.
        Returns the transferred array on dst; None elsewhere."""
        import jax

        if src_rank == dst_rank:
            raise ValueError("p2p with src_rank == dst_rank is a local copy")
        array = np.asarray(array)
        key = (array.shape, array.dtype.str, src_rank, dst_rank)
        shift = self._p2p_cache.get(key)
        if shift is None:
            import jax.numpy as jnp
            from jax.experimental.shard_map import shard_map
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            mesh = Mesh(np.array(self._rank_devices), ("ranks",))
            sharding = NamedSharding(mesh, P("ranks"))

            def permute(block):
                return jax.lax.ppermute(
                    block, "ranks", perm=[(src_rank, dst_rank)]
                )

            jitted = jax.jit(
                shard_map(
                    permute, mesh=mesh, in_specs=P("ranks"),
                    out_specs=P("ranks"),
                )
            )

            def shift(local_np):
                local = jnp.asarray(local_np)[None]
                global_arr = jax.make_array_from_single_device_arrays(
                    (self.world_size, *local.shape[1:]),
                    sharding,
                    [jax.device_put(local, self._rank_devices[self.rank])],
                )
                return jitted(global_arr)

            # Cache the jitted program: a per-step halo exchange must not
            # retrace/recompile on every call.
            self._p2p_cache[key] = shift
        out = shift(array)
        if self.rank != dst_rank:
            return None
        return np.asarray(out.addressable_data(0))[0]

    def send(self, array, dst_rank: int, tag: str = ""):
        """p2p send over the XLA mesh. The destination must concurrently
        call ``recv(src_rank=<this rank>, like=<same shape/dtype>)`` and,
        for world_size > 2, every OTHER rank must enter
        ``p2p(zeros_template, src, dst)`` — one ppermute program across
        the whole group (paired-collective semantics, like NCCL p2p)."""
        if dst_rank == self.rank:
            raise ValueError("xla send to self is unsupported")
        self.p2p(np.asarray(array), self.rank, dst_rank)

    def recv(
        self, src_rank: int, tag: str = "", timeout: float = 60.0,
        like=None,
    ):
        """p2p receive: ``like`` supplies the shape/dtype of the incoming
        array (XLA programs are shape-static; the reference's NCCL recv
        takes a pre-allocated tensor the same way)."""
        if like is None:
            raise ValueError(
                "xla recv needs like=<array of the incoming shape/dtype> "
                "(shape-static paired collective)"
            )
        if src_rank == self.rank:
            raise ValueError("xla recv from self is unsupported")
        return self.p2p(np.zeros_like(like), src_rank, self.rank)

    def destroy(self):
        pass


# ---------------------------------------------------------------------------
# hierarchical backend (two tiers: in-jit ICI reduce, then DCN ring)
# ---------------------------------------------------------------------------
class HierarchicalGroup(BaseGroup):
    """Two-tier collectives (SURVEY §5.8 "reduce within the slice, then
    across"): tier 1 reduces this host's device shards in ONE jit via
    shard_map+psum over the local jax mesh (the ICI tier — XLA fuses and
    keeps it on-chip); tier 2 reduces the per-host partials across gang
    members over the framework's RPC ring (the DCN tier). Unlike the "xla"
    backend this needs NO global jax.distributed runtime — each host runs
    its own jax, so it is the multi-SLICE shape where ICI does not span
    hosts and traffic must cross the data-center network.
    """

    _TIER1 = {"sum": "psum", "max": "pmax", "min": "pmin"}

    def __init__(self, world_size: int, rank: int, group_name: str):
        super().__init__(world_size, rank, group_name)
        # The DCN tier rides the ring group's controller-KV rendezvous + p2p.
        self._ring = RingGroup(world_size, rank, group_name + "@dcn")

    def _local_reduce(self, per_device_arrays: list, op: str) -> np.ndarray:
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        if op not in self._TIER1:
            raise ValueError(
                f"hierarchical backend supports ops {sorted(self._TIER1)}"
            )
        devices = jax.local_devices()[: len(per_device_arrays)]
        if len(devices) < len(per_device_arrays):
            raise ValueError(
                f"{len(per_device_arrays)} shards for {len(devices)} local devices"
            )
        mesh = Mesh(np.array(devices), ("local",))
        shape = np.asarray(per_device_arrays[0]).shape
        shards = [
            jax.device_put(jnp.asarray(a)[None], d)
            for a, d in zip(per_device_arrays, devices)
        ]
        stacked = jax.make_array_from_single_device_arrays(
            (len(devices), *shape), NamedSharding(mesh, P("local")), shards
        )
        prim = getattr(jax.lax, self._TIER1[op])
        reduced = jax.jit(
            jax.shard_map(
                # each device's block is (1, *shape): reduce over the mesh
                # axis, then drop the block dim.
                lambda x: prim(x, "local")[0],
                mesh=mesh,
                in_specs=P("local"),
                out_specs=P(),
            )
        )(stacked)
        return np.asarray(reduced)

    def allreduce_sharded(self, per_device_arrays: list, op: str = SUM) -> np.ndarray:
        """Reduce one shard per local device across ALL hosts' devices:
        tier-1 in-jit psum over the local mesh, tier-2 ring across hosts."""
        partial = self._local_reduce(per_device_arrays, op)
        return self._ring.allreduce(partial, op=op, tag="__hier")

    # Host-level (single array per rank) collectives delegate to the ring:
    # the hierarchy only matters when device shards are in play.
    def allreduce(self, array, op: str = SUM):
        return self._ring.allreduce(np.asarray(array), op=op)

    def allgather(self, array):
        return self._ring.allgather(np.asarray(array))

    def reducescatter(self, array, op: str = SUM):
        return self._ring.reducescatter(np.asarray(array), op=op)

    def broadcast(self, array, src_rank: int = 0):
        return self._ring.broadcast(np.asarray(array), src_rank=src_rank)

    def barrier(self):
        self._ring.barrier()

    def send(self, array, dst_rank: int, tag: str = ""):
        self._ring.send(array, dst_rank, tag=tag)

    def recv(self, src_rank: int, tag: str = "", timeout: float = 60.0,
             like=None):
        return self._ring.recv(src_rank, tag=tag, timeout=timeout)

    def destroy(self):
        self._ring.destroy()


# ---------------------------------------------------------------------------
# public API (reference signatures)
# ---------------------------------------------------------------------------
def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "ring",
    group_name: str = "default",
) -> None:
    if group_name in _groups:
        raise ValueError(f"collective group {group_name!r} already initialized")
    if backend in ("ring", "gloo"):
        _groups[group_name] = RingGroup(world_size, rank, group_name)
    elif backend == "xla":
        _groups[group_name] = XlaGroup(world_size, rank, group_name)
    elif backend in ("hier", "hierarchical"):
        _groups[group_name] = HierarchicalGroup(world_size, rank, group_name)
    else:
        raise ValueError(
            f"unknown backend {backend!r} (use 'ring', 'xla', or 'hier')"
        )


def get_group(group_name: str = "default") -> BaseGroup:
    if group_name not in _groups:
        raise ValueError(f"collective group {group_name!r} not initialized")
    return _groups[group_name]


def _traced(op: str, group: BaseGroup, array=None):
    """Span scope for one collective op (bytes + participants as
    attributes); a plain nullcontext when tracing is off."""
    if not tracing.enabled():
        return contextlib.nullcontext()
    attrs = {
        "group": group.group_name,
        "world_size": group.world_size,
        "rank": group.rank,
        "backend": type(group).__name__,
    }
    nbytes = getattr(array, "nbytes", None)
    if nbytes is not None:
        attrs["bytes"] = int(nbytes)
    return tracing.span(f"collective.{op}", **attrs)


def allreduce(array, group_name: str = "default", op: str = SUM):
    group = get_group(group_name)
    with _traced("allreduce", group, array):
        return group.allreduce(array, op=op)


def allgather(array, group_name: str = "default"):
    group = get_group(group_name)
    with _traced("allgather", group, array):
        return group.allgather(array)


def reducescatter(array, group_name: str = "default", op: str = SUM):
    group = get_group(group_name)
    with _traced("reducescatter", group, array):
        return group.reducescatter(array, op=op)


def broadcast(array, src_rank: int = 0, group_name: str = "default"):
    group = get_group(group_name)
    with _traced("broadcast", group, array):
        return group.broadcast(array, src_rank=src_rank)


def barrier(group_name: str = "default"):
    group = get_group(group_name)
    with _traced("barrier", group):
        group.barrier()


def send(array, dst_rank: int, group_name: str = "default"):
    group = get_group(group_name)
    with _traced("send", group, array):
        group.send(array, dst_rank)


def recv(
    src_rank: int, group_name: str = "default", timeout: float = 60.0,
    like=None,
):
    group = get_group(group_name)
    if like is not None:
        return group.recv(src_rank, timeout=timeout, like=like)
    return group.recv(src_rank, timeout=timeout)


def destroy_collective_group(group_name: str = "default") -> None:
    group = _groups.pop(group_name, None)
    if group is not None:
        group.destroy()

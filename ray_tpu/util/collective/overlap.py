"""Async bucketed allreduce — overlap gradient sync with other work.

The launch/fence half of the ISSUE-11 overlap path (the partition math
lives in :mod:`ray_tpu.util.collective.bucketing`). Each bucket's
allreduce runs on a per-group background thread pool: the ring protocol
underneath is wait-dominated (every hop parks on the shared asyncio RPC
lane via ``send_async`` futures and mailbox events), so concurrent
buckets interleave their hops instead of queueing behind each other,
and the caller's thread is free to keep producing grads between
``launch`` and ``fence``.

Instrumentation contract (the flight recorder proves the overlap):

* each bucket op still runs through the group's ``_traced_method``
  wrapper on ITS OWN thread, so the step's total ``collective`` phase
  time is unchanged — the work didn't shrink, it moved off the
  critical path;
* the wall time the caller actually spends blocked in :func:`fence` is
  recorded as the new ``comm_exposed`` phase. A perfectly hidden sync
  shows ``comm_exposed_s`` ≈ 0 while ``collective_s`` stays put — and
  the StepRecorder subtracts the EXPOSED time (not the total) from the
  compute remainder when the phase is present.

Thread safety: concurrent ring ops are isolated by tag — sequence
numbers, mailbox events, and error-feedback residuals are all keyed by
(peer, tag) or (tag, step), and the per-bucket tags are distinct by
construction (``Bucket.tag``). Cross-rank bucket launch order is
deterministic (same partition on every rank), and even when a fast rank
races ahead, its sends land in the slow rank's tag-addressed mailbox
without blocking the slow rank's current bucket.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Sequence

import numpy as np

from ray_tpu.train._internal import step_stats
from ray_tpu.util.collective import bucketing
from ray_tpu.util.collective import flight

# Buckets in flight at once. More than a few saturates the shared RPC
# lane; fewer leaves the ring idle between hops.
_POOL_WORKERS = 8
_pool_lock = threading.Lock()


def _pool(group: Any) -> ThreadPoolExecutor:
    """The group's lazily-created overlap thread pool (one per group —
    pool lifetime matches group lifetime, torn down with the process)."""
    pool = getattr(group, "_overlap_pool", None)
    if pool is None:
        with _pool_lock:
            pool = getattr(group, "_overlap_pool", None)
            if pool is None:
                pool = ThreadPoolExecutor(
                    max_workers=_POOL_WORKERS,
                    thread_name_prefix=f"overlap-{group.group_name}",
                )
                group._overlap_pool = pool
    return pool


def supports_overlap(group: Any) -> bool:
    """Only the host-memory backends take tagged concurrent allreduces;
    the xla backend syncs in-jit where GSPMD already overlaps."""
    return getattr(group, "backend_name", "") in ("ring", "hier")


class SyncHandle:
    """In-flight bucketed sync: one future per bucket, fenced once."""

    def __init__(self, buckets: Sequence[bucketing.Bucket], group: Any = None):
        self.buckets = list(buckets)
        self.futures: list[Future] = []
        self.launched_at = time.perf_counter()
        self.stats: dict[str, float] = {}
        self._group = group

    def fence(self) -> list[np.ndarray]:
        """Block until every bucket's reduction lands. Returns reduced
        segments in bucket order and records the blocked wall time as
        the ``comm_exposed`` phase (floored at a tick so the recorder
        can tell "overlap ran and hid everything" from "no overlap")."""
        t0 = time.perf_counter()
        g = self._group
        rec = None
        if g is not None:
            # The fence itself is an in-flight comm op: if a bucket's
            # allreduce wedges on a pool thread, this record is what
            # ages past the watchdog deadline on the caller's behalf.
            rec = flight.op_started(
                g.group_name, "overlap.fence", f"b{len(self.buckets)}",
                g.rank, g.world_size,
                backend=getattr(g, "backend_name", ""),
            )
        try:
            # Per-bucket waits carry step_annotation scopes (ISSUE 20):
            # a straggling bucket shows up on the merged trace as ONE
            # named slice (fence.b<i>) instead of an opaque fence blob.
            # Accounting is untouched — comm_exposed still measures the
            # whole fence below.
            results = []
            for i, fut in enumerate(self.futures):
                with step_stats.step_annotation(f"fence.b{i}"):
                    results.append(fut.result())
        except BaseException:
            if rec is not None:
                flight.completed(rec, ok=False)
            raise
        if rec is not None:
            flight.completed(rec)
        exposed = time.perf_counter() - t0
        self.stats = {
            "comm_exposed_s": exposed,
            "collective_s": sum(sec for _, sec in results),
            "buckets": float(len(self.buckets)),
        }
        step_stats.record_phase("comm_exposed", max(exposed, 1e-9))
        return [seg for seg, _ in results]


def launch_bucketed_allreduce(
    group: Any,
    per_device_leaves: Sequence[Sequence[Any]],
    bucket_bytes: int | None = None,
) -> SyncHandle:
    """Partition per-device grad leaves into buckets and launch each
    bucket's allreduce asynchronously (bucket 0 — the last layers,
    first grads out of backward — flies first).

    ``per_device_leaves`` is a list of flattened leaf lists, one per
    local device (a single-device caller passes ``[leaves]``). Returns
    a :class:`SyncHandle`; the SUM-reduced (NOT averaged) segments come
    out of ``handle.fence()`` in bucket order.
    """
    if not supports_overlap(group):
        raise ValueError(
            f"backend {getattr(group, 'backend_name', '?')!r} has no "
            "tagged-allreduce overlap path (use the default sync)"
        )
    if bucket_bytes is None:
        bucket_bytes = int(
            getattr(group.config, "bucket_bytes", 0)
            or bucketing.DEFAULT_BUCKET_BYTES
        )
    template = per_device_leaves[0]
    buckets = bucketing.partition_buckets(template, bucket_bytes)
    handle = SyncHandle(buckets, group=group)
    pool = _pool(group)
    flight.note(
        group.group_name, "overlap.launch", f"b{len(buckets)}",
        rank=group.rank, world_size=group.world_size,
        nbytes=sum(b.nbytes for b in buckets),
        backend=getattr(group, "backend_name", ""),
    )
    for bucket in buckets:
        segments = [
            bucketing.gather_segment(leaves, bucket)
            for leaves in per_device_leaves
        ]
        handle.futures.append(
            pool.submit(_reduce_bucket, group, bucket, segments)
        )
    return handle


def _reduce_bucket(
    group: Any, bucket: bucketing.Bucket, segments: list[np.ndarray]
) -> tuple[np.ndarray, float]:
    """One bucket's SUM reduction across local devices + the gang.
    Runs on a pool thread; returns (reduced segment, op seconds)."""
    t0 = time.perf_counter()
    if segments[0].size == 0:
        return segments[0], 0.0
    if len(segments) > 1 and hasattr(group, "allreduce_sharded"):
        out = np.asarray(
            group.allreduce_sharded(segments, tag=bucket.tag)
        )
    else:
        local = (
            segments[0]
            if len(segments) == 1
            else np.sum(np.stack(segments), axis=0)
        )
        if group.world_size > 1:
            out = np.asarray(group.allreduce(local, tag=bucket.tag))
        else:
            out = local
    return out, time.perf_counter() - t0

"""Comm-plane flight recorder + adaptive hang watchdog (ISSUE 14).

The static half of the protocol story (rtgraph, ISSUE 12) certifies at
lint time that every channel's send/recv skeletons match; this module is
the *runtime* half: every collective op, bucketed-overlap launch/fence,
and stage-runner p2p send/recv appends a fixed-size record to a
per-process lock-free ring buffer —

    (group, kind, tag, seq, rank, peer, bytes,
     state enqueued -> launched -> completed, monotonic timestamps,
     trace_id)

— so when the cluster wedges, every rank can answer "what was the last
comm op you saw on that channel, and how long have you been waiting"
without a debugger attached.

A per-channel watchdog turns the ring into live stall detection: the
deadline for each channel adapts from a moving p95 of *completed*
same-channel ops (``max(min_s, k * p95)``), so a uniformly-slow cluster
(chaos latency injection on every rank, a cold interconnect) raises its
own deadlines instead of spraying false positives, while one straggler
rank leaves its peers' recv records aging far past the channel's own
history. On breach the watchdog publishes a ``comm_stall`` event to the
controller (PR-5 event channel) which coordinates the cluster-wide
evidence harvest (see ``ray_tpu._private.hang_doctor``).

Lock-free claim, precisely: the hot path (one record per op) is a slot
store into a preallocated ring addressed by ``next(itertools.count())``
— atomic under CPython — plus dict/deque mutations that are each a
single bytecode-protected operation. No path in ``start``/``launched``/
``completed`` takes a lock; only the watchdog thread (4 Hz) snapshots.

Tuning knobs (env, read at recorder creation):

=============================================  =======  ==============
``RAY_TPU_COMM_FLIGHT``                        ``1``    ``0`` disables recording entirely
``RAY_TPU_COMM_FLIGHT_CAPACITY``               4096     ring slots per process
``RAY_TPU_COMM_WATCHDOG``                      ``1``    ``0`` records but never watches
``RAY_TPU_COMM_WATCHDOG_TICK_S``               0.25     scan period
``RAY_TPU_COMM_WATCHDOG_MIN_S``                2.0      deadline floor
``RAY_TPU_COMM_WATCHDOG_K``                    4.0      deadline = k * p95(channel)
``RAY_TPU_COMM_WATCHDOG_MIN_SAMPLES``          8        completions before the p95 arms
``RAY_TPU_COMM_WATCHDOG_STARTUP_S``            30.0     deadline while unarmed (cold compile grace)
``RAY_TPU_COMM_WATCHDOG_COOLDOWN_S``           5.0      per-channel re-fire suppression
=============================================  =======  ==============
"""

from __future__ import annotations

import asyncio
import collections
import itertools
import os
import re
import threading
import time
from typing import Any, Callable, Optional

_DIGITS = re.compile(r"\d+")

# record states
ENQUEUED = "enqueued"
LAUNCHED = "launched"
COMPLETED = "completed"
FAILED = "failed"


def channel_skeleton(tag: str) -> str:
    """Digit runs collapse to ``{}`` so per-step/per-microbatch tags
    (``s3.f2v1``, ``__barrier7/r0``, ``b4:12``) fold into one channel
    family — the same hole convention rtgraph skeletons use, letting a
    runtime channel be reconciled against the static graph."""
    return _DIGITS.sub("{}", tag or "")


def channel_id(group: str, kind: str, tag: str) -> str:
    return f"{group}:{kind}:{channel_skeleton(tag)}"


_site_tls = threading.local()


class site:
    """Context manager labeling records created on this thread with a
    call-site hint (the stage runner wraps its activation wire in
    ``flight.site("pipeline")`` so a hang report can say *which* wire)."""

    def __init__(self, label: str):
        self.label = label
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_site_tls, "value", None)
        _site_tls.value = self.label
        return self

    def __exit__(self, *exc):
        _site_tls.value = self._prev
        return False


def _current_site() -> Optional[str]:
    return getattr(_site_tls, "value", None)


_trace_tls = threading.local()


class trace:
    """Context manager stamping records created on this thread with a
    trace id (ISSUE 19): channel push/pop call sites wrap their flight-
    recorded ops so ``dag``/``serve_llm`` ring entries join the span
    store on ``trace_id`` exactly like the collective sites do — the
    group-internal p2p records a DeviceChannel send/recv creates pick
    the ambient id up without the wire layer knowing about tracing."""

    def __init__(self, trace_id: Optional[str]):
        self.trace_id = trace_id
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_trace_tls, "value", None)
        _trace_tls.value = self.trace_id
        return self

    def __exit__(self, *exc):
        _trace_tls.value = self._prev
        return False


def _current_trace() -> Optional[str]:
    return getattr(_trace_tls, "value", None)


class CommRecord:
    """One fixed-shape ring entry. Mutated in place as the op advances
    (the inflight map and the ring share the object, so a snapshot sees
    the live state without any copy on the hot path)."""

    __slots__ = (
        "rid", "group", "kind", "tag", "seq", "rank", "world_size",
        "peer", "nbytes", "backend", "state", "t_wall", "t_enqueued",
        "t_launched", "t_completed", "trace_id", "site", "stalled",
    )

    def __init__(self, rid, group, kind, tag, seq, rank, world_size,
                 peer, nbytes, backend, now, wall):
        self.rid = rid
        self.group = group
        self.kind = kind
        self.tag = tag
        self.seq = seq
        self.rank = rank
        self.world_size = world_size
        self.peer = peer
        self.nbytes = nbytes
        self.backend = backend
        self.state = ENQUEUED
        self.t_wall = wall
        self.t_enqueued = now
        self.t_launched = 0.0
        self.t_completed = 0.0
        self.trace_id = _current_trace()
        self.site = _current_site()
        self.stalled = False

    @property
    def channel(self) -> str:
        return channel_id(self.group, self.kind, self.tag)

    def age_s(self, now: float) -> float:
        return now - self.t_enqueued

    def to_dict(self, now: Optional[float] = None) -> dict:
        out = {
            "rid": self.rid,
            "group": self.group,
            "kind": self.kind,
            "tag": self.tag,
            "channel": self.channel,
            "seq": self.seq,
            "rank": self.rank,
            "world_size": self.world_size,
            "peer": self.peer,
            "bytes": self.nbytes,
            "backend": self.backend,
            "state": self.state,
            "t_wall": self.t_wall,
            "trace_id": self.trace_id,
            "site": self.site,
            "stalled": self.stalled,
        }
        if self.state in (COMPLETED, FAILED):
            out["duration_s"] = max(0.0, self.t_completed - self.t_enqueued)
        elif now is not None:
            out["age_s"] = self.age_s(now)
        return out


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


class FlightRecorder:
    """Per-process ring buffer + per-channel completion stats + watchdog.

    ``clock`` is injectable for deterministic watchdog unit tests."""

    def __init__(
        self,
        capacity: Optional[int] = None,
        clock: Callable[[], float] = time.monotonic,
        publish: Optional[Callable[[dict], None]] = None,
        start_watchdog: bool = True,
    ):
        self.capacity = int(
            capacity
            if capacity is not None
            else _env_f("RAY_TPU_COMM_FLIGHT_CAPACITY", 4096)
        )
        self.clock = clock
        self._ring: list[Optional[CommRecord]] = [None] * self.capacity
        self._idx = itertools.count()
        self._rid = itertools.count()
        # channel -> thread-safe monotonic per-channel sequence
        self._chan_seq: dict[str, Any] = {}
        # channel -> recent completed durations (moving p95 window)
        self._chan_stats: dict[str, collections.deque] = {}
        # rid -> live record; the watchdog's scan set
        self._inflight: dict[int, CommRecord] = {}
        self._stalls: list[dict] = []
        self._publish = publish if publish is not None else _default_publish
        # watchdog tunables
        self.tick_s = _env_f("RAY_TPU_COMM_WATCHDOG_TICK_S", 0.25)
        self.min_deadline_s = _env_f("RAY_TPU_COMM_WATCHDOG_MIN_S", 2.0)
        self.k = _env_f("RAY_TPU_COMM_WATCHDOG_K", 4.0)
        self.min_samples = int(_env_f("RAY_TPU_COMM_WATCHDOG_MIN_SAMPLES", 8))
        self.startup_deadline_s = _env_f(
            "RAY_TPU_COMM_WATCHDOG_STARTUP_S", 30.0
        )
        self.cooldown_s = _env_f("RAY_TPU_COMM_WATCHDOG_COOLDOWN_S", 5.0)
        self._last_fire: dict[str, float] = {}
        self._watch_enabled = (
            start_watchdog
            and os.environ.get("RAY_TPU_COMM_WATCHDOG", "1") != "0"
        )
        self._watch_thread: Optional[threading.Thread] = None
        self._watch_lock = threading.Lock()

    # -- hot path --------------------------------------------------------
    def start(
        self,
        group: str,
        kind: str,
        tag: str = "",
        rank: int = 0,
        world_size: int = 1,
        peer: int = -1,
        nbytes: int = 0,
        backend: str = "",
        seq: Optional[int] = None,
    ) -> CommRecord:
        """Append an ``enqueued`` record and return it. ``seq`` defaults
        to the channel's own monotonic counter; p2p call sites pass the
        wire sequence so the record names the exact mailbox slot."""
        chan = channel_id(group, kind, tag)
        if seq is None:
            counter = self._chan_seq.get(chan)
            if counter is None:
                # setdefault is atomic; racing threads share one counter
                counter = self._chan_seq.setdefault(chan, itertools.count())
            seq = next(counter)
        rec = CommRecord(
            next(self._rid), group, kind, tag, seq, rank, world_size,
            peer, nbytes, backend, self.clock(), time.time(),
        )
        self._ring[next(self._idx) % self.capacity] = rec
        self._inflight[rec.rid] = rec
        self._ensure_watchdog()
        return rec

    def launched(self, rec: Optional[CommRecord]) -> None:
        if rec is not None and rec.state == ENQUEUED:
            rec.state = LAUNCHED
            rec.t_launched = self.clock()

    def completed(self, rec: Optional[CommRecord], ok: bool = True) -> None:
        if rec is None:
            return
        rec.t_completed = self.clock()
        rec.state = COMPLETED if ok else FAILED
        self._inflight.pop(rec.rid, None)
        if ok:
            stats = self._chan_stats.get(rec.channel)
            if stats is None:
                stats = self._chan_stats.setdefault(
                    rec.channel, collections.deque(maxlen=64)
                )
            stats.append(rec.t_completed - rec.t_enqueued)

    def note(self, group: str, kind: str, tag: str = "", **kw) -> CommRecord:
        """An instantaneous event (e.g. overlap launch): enqueued and
        completed in one append, still visible in the ring."""
        rec = self.start(group, kind, tag, **kw)
        self.completed(rec)
        return rec

    # -- read side -------------------------------------------------------
    def snapshot(self, last_n: int = 256) -> list[dict]:
        """Newest-last dicts of up to ``last_n`` ring entries. Reads the
        ring without draining it (PR-5 snapshot-don't-drain: a retried
        read returns the same records)."""
        now = self.clock()
        entries = [r for r in self._ring if r is not None]
        entries.sort(key=lambda r: r.rid)
        return [r.to_dict(now) for r in entries[-max(0, int(last_n)):]]

    def inflight_summary(self) -> dict:
        now = self.clock()
        recs = list(self._inflight.values())
        oldest = max((r.age_s(now) for r in recs), default=0.0)
        return {
            "count": len(recs),
            "oldest_age_s": oldest,
            "channels": sorted({r.channel for r in recs}),
        }

    def stall_events(self) -> list[dict]:
        return list(self._stalls)

    def stall_count(self) -> int:
        return len(self._stalls)

    # -- watchdog --------------------------------------------------------
    def deadline_s(self, channel: str) -> float:
        stats = self._chan_stats.get(channel)
        if stats is not None and len(stats) >= self.min_samples:
            durs = sorted(stats)
            idx = min(len(durs) - 1, int(round(0.95 * (len(durs) - 1))))
            return max(self.min_deadline_s, self.k * durs[idx])
        return max(self.min_deadline_s, self.startup_deadline_s)

    def check_once(self, now: Optional[float] = None) -> list[dict]:
        """One watchdog scan; returns the stall events fired this pass.
        Called by the watchdog thread each tick, and directly (with an
        injected clock) by deterministic tests."""
        now = self.clock() if now is None else now
        fired = []
        for rec in list(self._inflight.values()):
            if rec.stalled:
                continue
            deadline = self.deadline_s(rec.channel)
            age = rec.age_s(now)
            if age <= deadline:
                continue
            last = self._last_fire.get(rec.channel, -1e18)
            if now - last < self.cooldown_s:
                # Another record on this channel already fired recently;
                # mark it so the hang report still counts it as stalled.
                rec.stalled = True
                continue
            self._last_fire[rec.channel] = now
            rec.stalled = True
            event = rec.to_dict(now)
            event.update({
                "age_s": age,
                "deadline_s": deadline,
                "samples": len(self._chan_stats.get(rec.channel) or ()),
            })
            self._stalls.append(event)
            fired.append(event)
            try:
                self._publish(event)
            except Exception:  # rtlint: disable=swallowed-exception - stall publication is best-effort; local ring + mark already hold the evidence
                pass
            _notify_stall_listeners(event)
        return fired

    def _ensure_watchdog(self) -> None:
        if not self._watch_enabled or self._watch_thread is not None:
            return
        with self._watch_lock:
            if self._watch_thread is not None:
                return
            thread = threading.Thread(
                target=self._watch_loop, name="comm-watchdog", daemon=True
            )
            self._watch_thread = thread
            thread.start()

    def _watch_loop(self) -> None:
        while True:
            time.sleep(self.tick_s)
            try:
                self.check_once()
                _export_inflight_gauge(self)
            except Exception:  # rtlint: disable=swallowed-exception - the watchdog must outlive transient metric/controller failures
                pass


# ---------------------------------------------------------------------------
# in-process stall listeners (watchdog -> rtdag supervisor wiring)
# ---------------------------------------------------------------------------
# A listener is (group_prefix, callback): the watchdog invokes the
# callback for every stall event whose group starts with the prefix. The
# rtdag supervisor registers its dag_id here so a stall on any of the
# graph's channels (any recovery epoch — per-epoch group names share the
# dag_id prefix) wakes the blocked driver reader into an immediate
# liveness probe instead of waiting out its probe interval. Callbacks
# run on the watchdog thread and must not block.

_stall_listeners: list[tuple[str, Callable[[dict], None]]] = []


def register_stall_listener(prefix: str, cb: Callable[[dict], None]) -> None:
    _stall_listeners.append((prefix, cb))


def unregister_stall_listener(cb: Callable[[dict], None]) -> None:
    _stall_listeners[:] = [
        (p, c) for (p, c) in _stall_listeners if c is not cb
    ]


def _notify_stall_listeners(event: dict) -> None:
    group = str(event.get("group") or "")
    for prefix, cb in list(_stall_listeners):
        if group.startswith(prefix):
            try:
                cb(event)
            except Exception:  # rtlint: disable=swallowed-exception - a broken listener must not kill the watchdog
                pass


# ---------------------------------------------------------------------------
# stall publication (worker -> controller event channel + Prometheus)
# ---------------------------------------------------------------------------

def _default_publish(event: dict) -> None:
    try:
        from ray_tpu.util import metrics

        metrics.record_comm_stall(event.get("group", "?"),
                                  event.get("channel", "?"))
    except Exception:  # rtlint: disable=swallowed-exception - metrics uplink is optional outside a cluster
        pass
    try:
        from ray_tpu._private import worker as worker_mod

        ctx = worker_mod.get_global_context()
        payload = dict(event)
        payload["identity"] = getattr(ctx, "worker_id", None) or "driver"
        fut = asyncio.run_coroutine_threadsafe(
            ctx.controller.call("report_comm_stall", payload, timeout=5.0),
            ctx.io.loop,
        )
        fut.result(timeout=6.0)
    except Exception:  # rtlint: disable=swallowed-exception - no controller (unit test / torn-down cluster): the local ring still holds the stall
        pass


def _export_inflight_gauge(rec: FlightRecorder) -> None:
    """rt_comm_inflight rides the existing 2s metrics flush — the gauge
    is overwritten each tick (snapshot, never drained), so a retried
    flush re-sends the same value instead of losing it."""
    try:
        from ray_tpu._private import worker as worker_mod
        from ray_tpu.util import metrics

        summary = rec.inflight_summary()
        try:
            identity = worker_mod.get_global_context().worker_id or "driver"
        except Exception:
            identity = "driver"
        metrics.set_comm_inflight(
            summary["count"], summary["oldest_age_s"], identity
        )
    except Exception:  # rtlint: disable=swallowed-exception - gauge export is advisory; the ring is the source of truth
        pass


# ---------------------------------------------------------------------------
# module-level singleton facade (what the collective plane calls)
# ---------------------------------------------------------------------------

_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def enabled() -> bool:
    return os.environ.get("RAY_TPU_COMM_FLIGHT", "1") != "0"


def get_recorder() -> FlightRecorder:
    global _recorder
    rec = _recorder
    if rec is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = FlightRecorder()
            rec = _recorder
    return rec


def reset() -> None:
    """Forget the process recorder (tests)."""
    global _recorder
    with _recorder_lock:
        _recorder = None


def op_started(group, op, tag, rank, world_size, nbytes=0,
               backend="") -> Optional[CommRecord]:
    """One user-visible collective op begins (``_instrumented_outer``)."""
    if not enabled():
        return None
    rec = get_recorder().start(
        group, op, tag, rank=rank, world_size=world_size,
        nbytes=int(nbytes or 0), backend=backend,
    )
    rec.state = LAUNCHED
    rec.t_launched = rec.t_enqueued
    return rec


def p2p_started(group, direction, tag, seq, rank, peer, world_size,
                nbytes=0) -> Optional[CommRecord]:
    """A ring-wire send/recv begins; ``seq`` is the mailbox sequence, so
    the record names the exact ``(group, tag, seq)`` slot a hang report
    blames."""
    if not enabled():
        return None
    return get_recorder().start(
        group, direction, tag, rank=rank, world_size=world_size,
        peer=peer, nbytes=int(nbytes or 0), backend="ring", seq=seq,
    )


def launched(rec: Optional[CommRecord]) -> None:
    if rec is not None:
        get_recorder().launched(rec)


def completed(rec: Optional[CommRecord], ok: bool = True) -> None:
    if rec is not None:
        get_recorder().completed(rec, ok=ok)


def note(group, kind, tag="", **kw) -> Optional[CommRecord]:
    if not enabled():
        return None
    return get_recorder().note(group, kind, tag, **kw)


def snapshot(last_n: int = 256) -> list[dict]:
    if _recorder is None:
        return []
    return get_recorder().snapshot(last_n)


def inflight_summary() -> dict:
    if _recorder is None:
        return {"count": 0, "oldest_age_s": 0.0, "channels": []}
    return get_recorder().inflight_summary()


def stall_events() -> list[dict]:
    return [] if _recorder is None else get_recorder().stall_events()


def stall_count() -> int:
    return 0 if _recorder is None else get_recorder().stall_count()

"""Block-scaled wire quantization for host-memory collectives.

EQuARX-style (PAPERS.md) lossy compression of the DCN gradient-sync
path: a flat f32 vector is split into fixed-size blocks, each block
quantized against its own absmax-derived scale — int8 (4x fewer wire
bytes than f32, plus one f32 scale per block) or fp8-e4m3 where the
runtime ships ``ml_dtypes``. Quantization error is NOT discarded:
:class:`ErrorFeedback` keeps a persistent per-site residual that is
added back into the next message from the same site, so the rounding
error of step *t* is corrected at step *t+1* and the training
trajectory converges to the fp32 one instead of drifting.

The codec is deliberately numpy-only (no jax import on the hot path):
it runs inside ring-backend gang members, including hostless CPU-twin
tests, and the whole encode is a handful of vectorized passes.

Wire format (pickle-friendly, self-describing)::

    ("q8"|"f8", q: np.ndarray, scales: np.ndarray(f32), n: int)

where ``q`` is the padded block matrix flattened and ``n`` the original
element count (padding is stripped on decode).
"""

from __future__ import annotations

import dataclasses

import numpy as np

try:  # fp8 rides ml_dtypes (a jax dependency); int8 needs only numpy.
    import ml_dtypes

    _FP8_DTYPE = np.dtype(ml_dtypes.float8_e4m3fn)
except Exception:  # pragma: no cover - ml_dtypes ships with jax
    _FP8_DTYPE = None

_INT8_MAX = 127.0
_FP8_E4M3_MAX = 448.0

_KINDS = (None, "int8", "fp8")


def fp8_supported() -> bool:
    return _FP8_DTYPE is not None


@dataclasses.dataclass(frozen=True)
class CollectiveConfig:
    """Opt-in knobs for the collective layer's wire path.

    quantize       — None (exact wire), "int8" (block-scaled int8), or
                     "fp8" (block-scaled float8_e4m3; falls back to int8
                     when ml_dtypes is unavailable).
    block_size     — elements per scale block. Smaller blocks track
                     outliers better (lower error) at more scale
                     overhead: 4 bytes per block, so int8 wire cost is
                     ``1 + 4/block_size`` bytes/element.
    error_feedback — keep per-site residuals so quantization error
                     telescopes across steps instead of accumulating
                     (leave on for training; off only for one-shot
                     reductions where drift cannot compound).
    quantize_activations — None (exact activation wire) or "int8"/"fp8"
                     to extend the block-scaled codec to the pipeline
                     stage runner's p2p activation/cotangent hand-offs,
                     with per-edge persistent EF residuals. The loss
                     broadcast and non-float payloads always stay exact.
    overlap        — default for the gradient-sync call sites: bucketed
                     async allreduce launched during backward, fenced at
                     the optimizer step (sync_gradients_sharded's
                     ``overlap=`` argument overrides per call).
    bucket_bytes   — target f32 payload per overlap bucket. Smaller
                     buckets start flying earlier and pipeline deeper;
                     larger buckets amortize per-op latency better.

    Only SUM reductions over float arrays take the quantized path;
    min/max/product and integer arrays silently use the exact wire.
    """

    quantize: str | None = None
    block_size: int = 256
    error_feedback: bool = True
    quantize_activations: str | None = None
    overlap: bool = False
    bucket_bytes: int = 25 * 1024 * 1024

    def __post_init__(self) -> None:
        if self.quantize not in _KINDS:
            raise ValueError(
                f"quantize must be one of {_KINDS}, got {self.quantize!r}"
            )
        if self.quantize_activations not in _KINDS:
            raise ValueError(
                f"quantize_activations must be one of {_KINDS}, got "
                f"{self.quantize_activations!r}"
            )
        if self.block_size <= 0:
            raise ValueError("block_size must be positive")
        if self.bucket_bytes <= 0:
            raise ValueError("bucket_bytes must be positive")

    @property
    def enabled(self) -> bool:
        return self.quantize is not None

    def wire_kind(self) -> str:
        """The codec actually used on this host ("q8" or "f8")."""
        if self.quantize == "fp8" and fp8_supported():
            return "f8"
        return "q8"

    def activation_wire_config(self) -> "CollectiveConfig":
        """The config the stage runner's activation codec encodes with:
        same block size / EF policy, but ``quantize`` set to the
        ACTIVATION kind (encode()/ErrorFeedback key off ``quantize``)."""
        return CollectiveConfig(
            quantize=self.quantize_activations,
            block_size=self.block_size,
            error_feedback=self.error_feedback,
        )


def _blocked(flat: np.ndarray, block_size: int) -> np.ndarray:
    """(nblocks, block_size) view of flat, zero-padded to a full block."""
    n = flat.size
    pad = (-n) % block_size
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, dtype=flat.dtype)])
    return flat.reshape(-1, block_size)


def encode(flat: np.ndarray, config: CollectiveConfig) -> tuple:
    """Encode a 1-D f32 vector into a block-scaled wire tuple."""
    flat = np.asarray(flat, dtype=np.float32).reshape(-1)
    kind = config.wire_kind()
    blocks = _blocked(flat, config.block_size)
    absmax = np.max(np.abs(blocks), axis=1) if blocks.size else np.zeros(
        blocks.shape[0], np.float32
    )
    qmax = _INT8_MAX if kind == "q8" else _FP8_E4M3_MAX
    scales = (absmax / qmax).astype(np.float32)
    # All-zero blocks get scale 1 so the divide is well-defined (q == 0).
    safe = np.where(scales > 0, scales, np.float32(1.0))[:, None]
    scaled = blocks / safe
    if kind == "q8":
        q = np.clip(np.rint(scaled), -_INT8_MAX, _INT8_MAX).astype(np.int8)
    else:
        q = scaled.astype(_FP8_DTYPE)
    return (kind, q.reshape(-1), scales, int(flat.size))


def decode(encoded: tuple) -> np.ndarray:
    """Decode a wire tuple back to a 1-D f32 vector (or pass through a
    plain ndarray — mixed exact/quantized call sites share one path)."""
    if isinstance(encoded, np.ndarray):
        return encoded
    kind, q, scales, n = encoded
    if n == 0:
        return np.zeros(0, dtype=np.float32)
    block_size = q.size // max(scales.size, 1)
    blocks = q.astype(np.float32).reshape(-1, block_size)
    safe = np.where(scales > 0, scales, np.float32(1.0))[:, None]
    return (blocks * safe).reshape(-1)[:n]


def wire_nbytes(encoded) -> int:
    """Payload bytes the encoding puts on the wire (q + scales)."""
    if isinstance(encoded, np.ndarray):
        return int(encoded.nbytes)
    _, q, scales, _ = encoded
    return int(q.nbytes + scales.nbytes)


class ErrorFeedback:
    """Persistent quantization residuals, keyed by call site.

    ``encode(key, x)`` adds the residual the same site left last time,
    quantizes, and stores the new rounding error ``x' - deq(enc(x'))``.
    Sites are (phase, tag, position) tuples the ring collectives derive
    deterministically, so residuals line up across training steps; a
    shape change (new array size / world size) resets that site's
    residual to zero rather than misapplying it.
    """

    def __init__(self) -> None:
        self._residuals: dict = {}

    def encode(self, key, x: np.ndarray, config: CollectiveConfig) -> tuple:
        x = np.asarray(x, dtype=np.float32).reshape(-1)
        if not config.error_feedback:
            return encode(x, config)
        residual = self._residuals.get(key)
        if residual is not None and residual.shape == x.shape:
            x = x + residual
        encoded = encode(x, config)
        self._residuals[key] = x - decode(encoded)
        return encoded

    def residual_norm(self) -> float:
        """Sum of |residual| over every site (tests assert boundedness)."""
        return float(
            sum(np.abs(r).sum() for r in self._residuals.values())
        )

    def reset(self) -> None:
        self._residuals.clear()

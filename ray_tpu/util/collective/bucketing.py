"""Size-targeted gradient buckets for overlapped allreduce (ISSUE 11).

"The Big Send-off" (PAPERS.md) observes that a monolithic post-backward
allreduce serializes communication behind the whole backward pass; the
fix is to partition the grad pytree into ~`bucket_bytes` buckets and
launch each bucket's reduction as soon as its grads exist. This module
is the pure-math half of that path: deterministic bucket PARTITIONING
plus flat-segment gather/scatter. The async launch/fence machinery
lives in :mod:`ray_tpu.util.collective.overlap`.

Design constraints the partition honors:

* **Every leaf lands in exactly one bucket** — scalars, zero-size
  leaves, and mixed dtypes included. The reduction wire is f32, so a
  bucket's byte size is ``4 * sum(leaf sizes)`` regardless of the
  leaves' storage dtypes.
* **Reverse-topological order**: leaves are packed starting from the
  END of the flattened pytree. Backward produces last-layer grads
  first, and jax.tree flattening walks layers in forward order, so
  bucket 0 holds the leaves whose grads materialize earliest — launch
  order matches production order.
* **Rank determinism**: the partition is a pure function of the leaf
  shapes and ``bucket_bytes``. Every rank derives the identical bucket
  list from its (structurally identical) grad tree, so per-bucket
  collective tags pair up without any negotiation.
* **EF-safe tags**: each bucket carries a ``signature`` hashed from its
  member leaves' (index, shape, dtype). When a resize/repartition moves
  a leaf between buckets, the signature changes, the collective tag
  changes, and the quantized ring's per-(tag, step) error-feedback
  residuals start fresh instead of being misapplied to different data.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Any, Sequence

import numpy as np

# ~25MB of f32 per bucket: large enough that ring-hop latency amortizes,
# small enough that several buckets are in flight during one backward.
DEFAULT_BUCKET_BYTES = 25 * 1024 * 1024


def leaf_size(leaf: Any) -> int:
    """Element count of a leaf; scalars count 1, zero-size arrays 0."""
    return int(np.prod(np.shape(leaf), dtype=np.int64))


@dataclasses.dataclass(frozen=True)
class Bucket:
    """One bucket of the partition: which leaves, in launch order."""

    index: int
    leaf_ids: tuple[int, ...]  # indices into the flattened leaf list
    nbytes: int                # f32 wire bytes of the whole bucket
    signature: str             # structure hash — part of the wire tag

    @property
    def tag(self) -> str:
        """The per-bucket collective tag. Includes the structure
        signature so a repartition never reuses a stale EF site."""
        return f"__gb{self.index}:{self.signature}"


def _signature(leaves: Sequence[Any], leaf_ids: Sequence[int]) -> str:
    meta = tuple(
        (i, tuple(np.shape(leaves[i])), np.asarray(leaves[i]).dtype.str)
        for i in leaf_ids
    )
    return hashlib.blake2s(repr(meta).encode(), digest_size=4).hexdigest()


def partition_buckets(
    leaves: Sequence[Any], bucket_bytes: int = DEFAULT_BUCKET_BYTES
) -> list[Bucket]:
    """Greedy size-targeted partition of ``leaves`` into buckets.

    Walks the leaf list in REVERSE (last leaves — produced first by
    backward — land in bucket 0) and closes a bucket once it reaches
    ``bucket_bytes`` of f32 payload. Every leaf appears in exactly one
    bucket; a single leaf larger than ``bucket_bytes`` gets a bucket of
    its own rather than being split (the ring chunks it internally).
    """
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes must be positive, got {bucket_bytes}")
    buckets: list[Bucket] = []
    current: list[int] = []
    current_bytes = 0

    def _flush() -> None:
        nonlocal current, current_bytes
        if not current:
            return
        buckets.append(
            Bucket(
                index=len(buckets),
                leaf_ids=tuple(current),
                nbytes=current_bytes,
                signature=_signature(leaves, current),
            )
        )
        current, current_bytes = [], 0

    for i in range(len(leaves) - 1, -1, -1):
        current.append(i)
        current_bytes += leaf_size(leaves[i]) * 4  # f32 wire
        if current_bytes >= bucket_bytes:
            _flush()
    _flush()
    return buckets


def gather_segment(leaves: Sequence[Any], bucket: Bucket) -> np.ndarray:
    """Concatenate a bucket's leaves into one flat f32 wire segment."""
    parts = [
        np.asarray(leaves[i], np.float32).ravel() for i in bucket.leaf_ids
    ]
    if not parts:
        return np.zeros(0, np.float32)
    return np.concatenate(parts)


def scatter_segment(
    segment: np.ndarray, leaves: Sequence[Any], bucket: Bucket
) -> dict[int, np.ndarray]:
    """Split a reduced flat segment back into per-leaf arrays with the
    original shapes/dtypes. Returns {leaf_id: array}."""
    out: dict[int, np.ndarray] = {}
    offset = 0
    for i in bucket.leaf_ids:
        shape = np.shape(leaves[i])
        size = leaf_size(leaves[i])
        out[i] = (
            segment[offset : offset + size]
            .reshape(shape)
            .astype(np.asarray(leaves[i]).dtype)
        )
        offset += size
    if offset != segment.size:
        raise ValueError(
            f"bucket {bucket.index}: segment has {segment.size} elements, "
            f"leaves expect {offset}"
        )
    return out

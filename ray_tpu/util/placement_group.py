"""Placement groups — gang scheduling of resource bundles.

Role-equivalent of python/ray/util/placement_group.py
(:: placement_group, PlacementGroup, remove_placement_group,
placement_group_table). Strategies: STRICT_PACK / PACK / SPREAD /
STRICT_SPREAD, scheduled by the controller's 2-phase bundle commit
(gcs_placement_group_manager.cc [N3]).

TPU addition: ``tpu_slice_bundles("v4-32")`` builds the bundle list for a
whole pod slice (one bundle per host, STRICT_SPREAD across hosts within the
slice's ICI domain) — the pod-slice placement group of the north star.
"""

from __future__ import annotations

import time
from typing import Any

from ray_tpu import exceptions
from ray_tpu._private import worker
from ray_tpu._private.ids import PlacementGroupID

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: str, bundles: list[dict], strategy: str):
        self.id = pg_id
        self.bundles = bundles
        self.strategy = strategy

    def ready(self, timeout: float | None = None):
        """Block until all bundles are committed (reference: pg.ready())."""
        ctx = worker.get_global_context()
        import asyncio

        async def _wait():
            return await ctx.controller.call("pg_ready", {"pg_id": self.id})

        try:
            resp = ctx.io.run(
                asyncio.wait_for(_wait(), timeout) if timeout else _wait(),
                timeout=timeout + 5 if timeout else None,
            )
        except Exception as exc:
            raise exceptions.PlacementGroupUnschedulableError(
                f"placement group {self.id} not ready: {exc}"
            ) from None
        if resp.get("status") != "ok":
            raise exceptions.PlacementGroupUnschedulableError(self.id)
        return self

    @property
    def bundle_count(self) -> int:
        return len(self.bundles)

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundles, self.strategy))


def placement_group(
    bundles: list[dict],
    strategy: str = "PACK",
    name: str = "",
    lifetime: str | None = None,
) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be non-empty resource dicts")
    ctx = worker.get_global_context()
    pg_id = PlacementGroupID.random()
    ctx.io.run(
        ctx.controller.call(
            "create_placement_group",
            {
                "pg_id": pg_id,
                "bundles": [{k: float(v) for k, v in b.items()} for b in bundles],
                "strategy": strategy,
                "name": name,
                "lifetime": lifetime,
                "job_id": ctx.job_id,
                # Idempotency token (see create_actor): pg_id is
                # client-random, so it names this logical create.
                "mutation_token": f"create-pg:{pg_id}",
            },
        )
    )
    return PlacementGroup(pg_id, bundles, strategy)


def remove_placement_group(pg: PlacementGroup) -> None:
    ctx = worker.get_global_context()
    ctx.io.run(
        ctx.controller.call("remove_placement_group", {"pg_id": pg.id})
    )


def placement_group_table() -> list[dict]:
    ctx = worker.get_global_context()
    return ctx.io.run(ctx.controller.call("list_placement_groups", {}))


# ---------------------------------------------------------------------------
# TPU pod-slice vocabulary
# ---------------------------------------------------------------------------
_SLICE_HOSTS = {
    # generation -> chips per host
    "v4": 4, "v5p": 4, "v5e": 8, "v6e": 8,
}


def tpu_slice_bundles(slice_spec: str) -> list[dict]:
    """Bundles for a whole pod slice, one per TPU host.

    e.g. "v4-32" = 16 chips (v4 sizes count TensorCores) over 4 hosts of 4
    chips -> 4 bundles of {"TPU": 4}. Schedule with STRICT_SPREAD so each
    bundle lands on a distinct host of the slice's ICI domain.
    """
    generation, size = slice_spec.split("-")
    size = int(size)
    chips = size // 2 if generation in ("v4", "v5p") else size
    per_host = _SLICE_HOSTS.get(generation, 4)
    num_hosts = max(1, chips // per_host)
    chips_per_host = chips / num_hosts
    return [
        {"TPU": chips_per_host, f"TPU-{slice_spec}": chips_per_host}
        for _ in range(num_hosts)
    ]


def tpu_slice_placement_group(slice_spec: str, name: str = "") -> PlacementGroup:
    return placement_group(
        tpu_slice_bundles(slice_spec), strategy="STRICT_SPREAD", name=name
    )

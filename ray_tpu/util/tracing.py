"""Distributed task tracing — OpenTelemetry-style spans without the SDK.

Role-equivalent of the reference's opt-in OTel integration
(python/ray/util/tracing/tracing_helper.py, SURVEY §5.1): when
``RAY_TPU_tracing_enabled=1``, the whole task lifecycle is wrapped in a
causally-linked span tree whose context (trace_id, span_id) propagates
inside the TaskSpec, actor-call frames, and Serve proxy metadata — a
driver's ``submit`` span becomes the parent of the controller's
``lease_wait``, the agent's ``worker_start`` and the worker's
``fetch_args``/``execute``/``put_result`` spans, across processes.

Span taxonomy (see docs/observability.md for the full table):

  submit <name>      driver   f.remote() / actor.m.remote() client side
  lease_wait         ctrl     time a lease request sat parked for capacity
  worker_start       agent    cold worker spawn forced by a lease
  fetch_args         worker   dependency resolution before user code
  execute <name>     worker   the user function / actor method body
  put_result         worker   serializing + seeding return values
  queue_wait         worker   in-actor time between arrival and execution
  object_pull/push   any      object-store transfers (bytes attribute)
  collective.<op>    worker   allreduce/… (bytes + world_size attributes)
  serve.request      proxy    HTTP request as seen by the Serve proxy
  serve.replica      replica  replica-side handling of one request

The exporter is a per-process JSONL file under
``<session_dir>/tracing/spans-<pid>.jsonl`` (the OTel span JSON shape:
name, trace_id, span_id, parent_id, start/end unix-nanos, status,
attributes). Writes are buffered and flushed in batches (size- and
age-triggered, plus atexit) so tracing is not one open()+write() syscall
pair per span. No opentelemetry dependency: the wire model is small
enough to own, and an environment with the SDK installed can lift these
records into any OTLP pipeline verbatim.
"""

from __future__ import annotations

import atexit
import collections
import contextlib
import contextvars
import glob
import itertools
import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Iterator

from ray_tpu._private.config import global_config

_current: contextvars.ContextVar[tuple[str, str] | None] = contextvars.ContextVar(
    "raytpu_trace_ctx", default=None
)
_lock = threading.Lock()       # guards _buffer / _flusher_started
_io_lock = threading.Lock()    # serializes file appends
_dir: str | None = None

# Buffered exporter: Span OBJECTS accumulate in a deque (append is
# atomic — no lock on the record path) and are serialized + appended in
# one batch by the flusher thread (age tick / atexit), so the hot path
# pays neither json.dumps nor a write() syscall nor a lock round-trip.
# Small per-task costs here are amplified by GIL contention with the io
# loop thread, so the record path must stay at "a few attribute stores
# and a deque append". The size cap is a memory backstop only — at
# steady state the 0.2s tick drains first.
_BUFFER_SPANS = 8192
_FLUSH_AGE_S = 0.2
_buffer: collections.deque = collections.deque()
_flusher_started = False

# Cheap span/trace ids: one urandom() per process (fork-safe via the pid
# key) + a counter, instead of two urandom syscalls per span. Same hex
# shapes as OTel ids: 16 chars for span_id, 32 for trace_id.
# _id_state = (pid, trace_prefix_16chars, span_prefix_8chars).
_id_state: tuple[int, str, str] | None = None
_id_counter = itertools.count(1)


def _id_prefixes() -> tuple[int, str, str]:
    global _id_state, _id_counter
    state = _id_state
    if state is None or state[0] != os.getpid():
        prefix = os.urandom(8).hex()
        state = _id_state = (os.getpid(), prefix, prefix[:8])
        _id_counter = itertools.count(1)
    return state


def _new_span_id() -> str:
    return f"{_id_prefixes()[2]}{next(_id_counter) & 0xFFFFFFFF:08x}"


def _new_trace_id() -> str:
    return f"{_id_prefixes()[1]}{next(_id_counter) & 0xFFFFFFFFFFFFFFFF:016x}"


def enabled() -> bool:
    return bool(getattr(global_config(), "tracing_enabled", False))


def configure(session_dir: str | None) -> None:
    """Set the export directory (driver: from init; workers: from env)."""
    global _dir
    if session_dir:
        # Drain any buffered spans into the PREVIOUS session's files so a
        # reconfigure (new init in the same process) never leaks old spans
        # into the new session dir.
        try:
            flush()
        except Exception:  # rtlint: disable=swallowed-exception - flush into a dead previous session is best-effort
            pass
        _dir = os.path.join(session_dir, "tracing")


def _export_dir() -> str | None:
    # Memoize the env fallback (workers learn the session dir from the
    # environment): _record() runs per span and must not re-do an environ
    # lookup + path join each time.
    global _dir
    if _dir is None and "RAYTPU_SESSION_DIR" in os.environ:
        _dir = os.path.join(os.environ["RAYTPU_SESSION_DIR"], "tracing")
    return _dir


def _export_path() -> str | None:
    base = _export_dir()
    if base is None:
        return None
    os.makedirs(base, exist_ok=True)
    return os.path.join(base, f"spans-{os.getpid()}.jsonl")


@dataclass(slots=True)
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: str | None = None
    start_ns: int = 0
    end_ns: int = 0
    status: str = "ok"
    attributes: dict = field(default_factory=dict)

    def set_error(self, exc: BaseException | str) -> None:
        """Mark the span failed, recording the exception type."""
        self.status = "error"
        if isinstance(exc, BaseException):
            self.attributes["error_type"] = type(exc).__name__
            self.attributes.setdefault("error_message", str(exc)[:200])
        else:
            self.attributes["error_type"] = str(exc)

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_ns": self.start_ns,
            "end_ns": self.end_ns,
            "status": self.status,
            "pid": _id_state[0] if _id_state else os.getpid(),
            "attributes": self.attributes,
        }


def flush() -> None:
    """Serialize + write every buffered span to the per-process file."""
    if not _buffer:
        return
    batch = []
    while True:
        try:
            batch.append(_buffer.popleft())
        except IndexError:
            break
    if not batch:
        return
    path = _export_path()
    if path is None:
        return
    # Hand-rolled JSON line: every field except name/attributes is an int
    # or hex id we generated, so json.dumps only runs on the two fields
    # that need escaping. ~2x faster than dumps(to_json()) per span, and
    # serialization time steals GIL slices from task execution even on
    # the flusher thread.
    pid = _id_state[0] if _id_state else os.getpid()
    dumps = json.dumps
    parts = []
    for rec in batch:
        parent = '"' + rec.parent_id + '"' if rec.parent_id else "null"
        parts.append(
            f'{{"name":{dumps(rec.name)},"trace_id":"{rec.trace_id}",'
            f'"span_id":"{rec.span_id}","parent_id":{parent},'
            f'"start_ns":{rec.start_ns},"end_ns":{rec.end_ns},'
            f'"status":"{rec.status}","pid":{pid},'
            f'"attributes":{dumps(rec.attributes, separators=(",", ":"))}}}\n'
        )
    lines = "".join(parts)
    with _io_lock:
        # rtlint: disable=blocking-in-async - flush normally runs on the background _flush_loop thread; the async-reachable path is the bounded force-flush at span shutdown
        with open(path, "a") as fh:
            fh.write(lines)


def _flush_loop() -> None:
    while True:
        time.sleep(_FLUSH_AGE_S)
        try:
            flush()
        except Exception:
            # Keep the daemon alive; surface persistent write failures
            # when span-level debugging is on.
            logging.getLogger(__name__).debug(
                "trace flush failed", exc_info=True
            )


def _ensure_flusher() -> None:
    global _flusher_started
    with _lock:
        if _flusher_started:
            return
        _flusher_started = True
    threading.Thread(
        target=_flush_loop, name="raytpu-span-flusher", daemon=True
    ).start()
    atexit.register(flush)


def _record(span: Span) -> None:
    if _export_dir() is None:
        return
    _buffer.append(span)  # deque append: atomic, no lock
    if not _flusher_started:
        _ensure_flusher()
    if len(_buffer) >= _BUFFER_SPANS:
        flush()  # memory backstop; the age tick normally drains first


def _parent_ctx(
    parent: tuple[str, str] | dict | None
) -> tuple[str, str] | None:
    if isinstance(parent, dict):
        return (parent["trace_id"], parent["span_id"])
    if parent is not None:
        return parent
    return _current.get()


@contextlib.contextmanager
def span(
    name: str,
    parent: tuple[str, str] | dict | None = None,
    **attributes: Any,
) -> Iterator[Span | None]:
    """Open a span. ``parent`` may be an injected dict from a TaskSpec, an
    explicit (trace_id, span_id) tuple, or None (inherit the contextvar /
    start a new trace). If the body raises, the span still sets ``end_ns``
    and flushes, with ``status: "error"`` + the exception type recorded."""
    if not enabled():
        yield None
        return
    parent_ctx = _parent_ctx(parent)
    trace_id = parent_ctx[0] if parent_ctx else _new_trace_id()
    record = Span(
        name=name,
        trace_id=trace_id,
        span_id=_new_span_id(),
        parent_id=parent_ctx[1] if parent_ctx else None,
        start_ns=time.time_ns(),
        attributes=attributes,
    )
    token = _current.set((trace_id, record.span_id))
    try:
        yield record
    except BaseException as exc:
        record.set_error(exc)
        raise
    finally:
        _current.reset(token)
        record.end_ns = time.time_ns()
        _record(record)


def emit(
    name: str,
    parent: tuple[str, str] | dict | None = None,
    *,
    start_ns: int,
    end_ns: int | None = None,
    status: str = "ok",
    **attributes: Any,
) -> Span | None:
    """Record a pre-timed span (for phases whose start predates the call
    site: controller lease parking, in-actor queue wait). Returns the
    recorded Span so callers can chain children off its span_id."""
    if not enabled():
        return None
    parent_ctx = _parent_ctx(parent)
    record = Span(
        name=name,
        trace_id=parent_ctx[0] if parent_ctx else _new_trace_id(),
        span_id=_new_span_id(),
        parent_id=parent_ctx[1] if parent_ctx else None,
        start_ns=start_ns,
        end_ns=end_ns if end_ns is not None else time.time_ns(),
        status=status,
        attributes=attributes,
    )
    _record(record)
    return record


def begin(
    name: str,
    parent: tuple[str, str] | dict | None = None,
    **attributes: Any,
) -> Span:
    """Hot-path span start: no contextmanager, no contextvar write.

    For per-task call sites (driver submit, worker execute) where the
    `span()` generator + contextvar round-trip is measurable at task
    rates. The caller embeds ``{"trace_id": s.trace_id, "span_id":
    s.span_id}`` wherever the context must ride and MUST call
    ``finish(s)`` on every path. Child spans name the parent explicitly,
    so skipping the contextvar loses nothing. The contextvar is still
    READ for parentage (a task submitted inside a traced actor method
    must chain), just never written. (Parent resolution is inlined:
    this path runs per task and every call costs ~3-8x its raw time in
    GIL handoffs with the io loop thread.)"""
    if type(parent) is dict:
        parent_ctx = (parent["trace_id"], parent["span_id"])
    elif parent is not None:
        parent_ctx = parent
    else:
        parent_ctx = _current.get()
    state = _id_prefixes()
    n = next(_id_counter)  # one draw serves both ids of a root span
    return Span(
        name=name,
        trace_id=(
            parent_ctx[0]
            if parent_ctx
            else f"{state[1]}{n & 0xFFFFFFFFFFFFFFFF:016x}"
        ),
        span_id=f"{state[2]}{n & 0xFFFFFFFF:08x}",
        parent_id=parent_ctx[1] if parent_ctx else None,
        start_ns=time.time_ns(),
        attributes=attributes,
    )


def finish(record: Span) -> None:
    """Close + record a span started with begin()."""
    record.end_ns = time.time_ns()
    _record(record)


def set_current(record: Span):
    """Make a begin()-span the ambient parent (returns a reset token).

    For hot-path spans that wrap USER code (worker execute): nested
    submits must chain off them, so the contextvar write span() does is
    needed — but the contextlib generator machinery is not."""
    return _current.set((record.trace_id, record.span_id))


def reset_current(token) -> None:
    _current.reset(token)


def inject() -> dict | None:
    """Current span context as a TaskSpec-embeddable dict."""
    if not enabled():
        return None
    ctx = _current.get()
    if ctx is None:
        return None
    return {"trace_id": ctx[0], "span_id": ctx[1]}


def context_of(record: Span | None) -> dict | None:
    """A specific span's context as an injectable dict (for hand-built
    parent/child links that bypass the contextvar)."""
    if record is None:
        return None
    return {"trace_id": record.trace_id, "span_id": record.span_id}


# -- compact wire context (ISSUE 19) ----------------------------------------
# The rtdag channel plane moves payloads with no RPC frame to ride, so the
# trace context crosses processes as a fixed 25-byte binary segment:
# 16-byte trace_id + 8-byte span_id + 1 flags byte (bit 0 = sampled).
# Hex round-trips exactly (ids are generated as 32/16 hex chars above).

CTX_WIRE_SIZE = 25
_FLAG_SAMPLED = 0x01


def pack_ctx(ctx: dict | tuple | None) -> bytes:
    """Encode an injected context for a channel frame header. Returns
    b"" for None (the disabled path writes zero extra bytes beyond the
    1-byte length that frames always carry)."""
    if ctx is None:
        return b""
    if isinstance(ctx, dict):
        trace_id, span_id = ctx["trace_id"], ctx["span_id"]
    else:
        trace_id, span_id = ctx
    try:
        return (
            bytes.fromhex(trace_id)
            + bytes.fromhex(span_id)
            + bytes([_FLAG_SAMPLED])
        )
    except ValueError:
        # Foreign-format ids (an OTLP bridge injecting its own): drop
        # rather than corrupt the frame.
        return b""


def unpack_ctx(buf) -> dict | None:
    """Decode a pack_ctx segment back to an injectable dict (None for
    empty/short segments)."""
    if not buf or len(buf) < CTX_WIRE_SIZE:
        return None
    b = bytes(buf[:CTX_WIRE_SIZE])
    return {
        "trace_id": b[:16].hex(),
        "span_id": b[16:24].hex(),
        "sampled": bool(b[24] & _FLAG_SAMPLED),
    }


def read_spans(session_dir: str) -> list[dict]:
    """All spans exported under a session (tests + dashboard route)."""
    flush()  # surface this process's buffered spans first
    out: list[dict] = []
    for path in sorted(
        glob.glob(os.path.join(session_dir, "tracing", "spans-*.jsonl"))
    ):
        try:
            with open(path) as fh:
                for line in fh:
                    line = line.strip()
                    if line:
                        out.append(json.loads(line))
        except OSError:
            continue
    return out

"""Distributed task tracing — OpenTelemetry-style spans without the SDK.

Role-equivalent of the reference's opt-in OTel integration
(python/ray/util/tracing/tracing_helper.py, SURVEY §5.1): when
``RAY_TPU_tracing_enabled=1``, task submission and execution are wrapped
in spans whose context (trace_id, span_id) propagates inside the TaskSpec
— a driver's submit span becomes the parent of the worker's execute span,
across processes.

The exporter is a per-process JSONL file under
``<session_dir>/tracing/spans-<pid>.jsonl`` (the OTel span JSON shape:
name, trace_id, span_id, parent_id, start/end unix-nanos, attributes).
No opentelemetry dependency: the wire model is small enough to own, and
an environment with the SDK installed can lift these records into any
OTLP pipeline verbatim.
"""

from __future__ import annotations

import contextlib
import contextvars
import glob
import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Iterator

from ray_tpu._private.config import global_config

_current: contextvars.ContextVar[tuple[str, str] | None] = contextvars.ContextVar(
    "raytpu_trace_ctx", default=None
)
_lock = threading.Lock()
_dir: str | None = None


def enabled() -> bool:
    return bool(getattr(global_config(), "tracing_enabled", False))


def configure(session_dir: str | None) -> None:
    """Set the export directory (driver: from init; workers: from env)."""
    global _dir
    if session_dir:
        _dir = os.path.join(session_dir, "tracing")


def _export_path() -> str | None:
    base = _dir or (
        os.path.join(os.environ["RAYTPU_SESSION_DIR"], "tracing")
        if "RAYTPU_SESSION_DIR" in os.environ
        else None
    )
    if base is None:
        return None
    os.makedirs(base, exist_ok=True)
    return os.path.join(base, f"spans-{os.getpid()}.jsonl")


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: str | None = None
    start_ns: int = 0
    end_ns: int = 0
    attributes: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_ns": self.start_ns,
            "end_ns": self.end_ns,
            "attributes": self.attributes,
        }


def _record(span: Span) -> None:
    path = _export_path()
    if path is None:
        return
    line = json.dumps(span.to_json())
    with _lock:
        with open(path, "a") as fh:
            fh.write(line + "\n")


@contextlib.contextmanager
def span(
    name: str,
    parent: tuple[str, str] | dict | None = None,
    **attributes: Any,
) -> Iterator[Span | None]:
    """Open a span. ``parent`` may be an injected dict from a TaskSpec, an
    explicit (trace_id, span_id) tuple, or None (inherit the contextvar /
    start a new trace)."""
    if not enabled():
        yield None
        return
    if isinstance(parent, dict):
        parent_ctx = (parent["trace_id"], parent["span_id"])
    elif parent is not None:
        parent_ctx = parent
    else:
        parent_ctx = _current.get()
    trace_id = parent_ctx[0] if parent_ctx else os.urandom(16).hex()
    record = Span(
        name=name,
        trace_id=trace_id,
        span_id=os.urandom(8).hex(),
        parent_id=parent_ctx[1] if parent_ctx else None,
        start_ns=time.time_ns(),
        attributes=dict(attributes),
    )
    token = _current.set((trace_id, record.span_id))
    try:
        yield record
    finally:
        _current.reset(token)
        record.end_ns = time.time_ns()
        _record(record)


def inject() -> dict | None:
    """Current span context as a TaskSpec-embeddable dict."""
    if not enabled():
        return None
    ctx = _current.get()
    if ctx is None:
        return None
    return {"trace_id": ctx[0], "span_id": ctx[1]}


def read_spans(session_dir: str) -> list[dict]:
    """All spans exported under a session (tests + dashboard route)."""
    out: list[dict] = []
    for path in sorted(
        glob.glob(os.path.join(session_dir, "tracing", "spans-*.jsonl"))
    ):
        try:
            with open(path) as fh:
                for line in fh:
                    line = line.strip()
                    if line:
                        out.append(json.loads(line))
        except OSError:
            continue
    return out

"""Chrome/Perfetto trace export — merge spans, task events, and native
engine counters onto per-process tracks.

Role-equivalent of ``ray.timeline()``'s chrome://tracing dump (SURVEY
§5.5), upgraded to the full critical-path span store from ISSUE 4: one
JSON file (the Trace Event Format) that ``ui.perfetto.dev`` or
``chrome://tracing`` loads directly, with

  * one track (pid) per cluster process that recorded spans — driver,
    controller, each node agent, each worker — with "X" complete events
    per span (args carry span attributes + trace/span ids),
  * the controller's task-event log as per-node "X" events (RUNNING →
    terminal window), and
  * a "C" counter snapshot per native-engine / control-plane gauge so
    queue depths sit on the same time axis as the spans they explain.

All timestamps are unix-epoch microseconds (spans record unix nanos,
task events unix seconds — both collapse onto the same axis).
"""

from __future__ import annotations

import time
from typing import Any

from ray_tpu.util import tracing

# Span names that identify a process's role when naming its track.
_ROLE_HINTS = (
    ("lease_wait", "controller"),
    ("worker_start", "node_agent"),
    ("execute", "worker"),
    ("serve.replica", "worker"),
    ("submit", "driver"),
    ("serve.request", "serve_proxy"),
)


def _track_names(spans: list[dict]) -> dict[int, str]:
    """Human track name per recording pid, from the span mix it wrote."""
    by_pid: dict[int, list[dict]] = {}
    for span in spans:
        by_pid.setdefault(span.get("pid") or 0, []).append(span)
    names: dict[int, str] = {}
    for pid, recs in by_pid.items():
        role = None
        for hint, candidate in _ROLE_HINTS:
            if any(r.get("name", "").startswith(hint) for r in recs):
                role = candidate
                break
        worker_ids = {
            (r.get("attributes") or {}).get("worker_id")
            for r in recs
            if (r.get("attributes") or {}).get("worker_id")
        }
        if role in (None, "worker") and len(worker_ids) == 1:
            names[pid] = f"worker {next(iter(worker_ids))}"
        else:
            names[pid] = f"{role or 'process'} (pid {pid})"
    return names


def _span_events(spans: list[dict]) -> list[dict]:
    events: list[dict] = []
    for pid, label in _track_names(spans).items():
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": label},
            }
        )
    for span in spans:
        start_ns = span.get("start_ns") or 0
        end_ns = span.get("end_ns") or start_ns
        attrs = dict(span.get("attributes") or {})
        attrs["trace_id"] = span.get("trace_id")
        attrs["span_id"] = span.get("span_id")
        if span.get("parent_id"):
            attrs["parent_id"] = span["parent_id"]
        if span.get("status") not in (None, "ok"):
            attrs["status"] = span["status"]
        events.append(
            {
                "name": span.get("name", "span"),
                "cat": "span",
                "ph": "X",
                "ts": start_ns / 1e3,
                "dur": max(0.0, (end_ns - start_ns) / 1e3),
                "pid": span.get("pid") or 0,
                "tid": 0,
                "args": attrs,
            }
        )
    return events


def _task_event_events(task_events: list[dict]) -> list[dict]:
    """Terminal task events as "X" windows on per-node tracks (the
    pre-span timeline view, kept so untraced runs still render)."""
    events: list[dict] = []
    nodes: dict[str, int] = {}
    for ev in task_events:
        state = ev.get("state")
        if state not in ("FINISHED", "FAILED", "CANCELLED"):
            continue
        ts = ev.get("ts")
        start = ev.get("start_ts") or ts
        if not ts or not start:
            continue
        node = str(ev.get("node_id") or "?")
        # Synthetic negative pids keep node tracks clear of real processes.
        pid = nodes.setdefault(node, -(len(nodes) + 1))
        events.append(
            {
                "name": ev.get("name") or ev.get("task_id") or "task",
                "cat": "task_event",
                "ph": "X",
                "ts": start * 1e6,
                "dur": max(0.0, (ts - start) * 1e6),
                "pid": pid,
                "tid": 0,
                "args": {"task_id": ev.get("task_id"), "state": state},
            }
        )
    for node, pid in nodes.items():
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": f"node {node} (task events)"},
            }
        )
    return events


def _counter_events(points: list, ts_us: float) -> list[dict]:
    events: list[dict] = []
    for name, tags, value, _kind in points:
        label = name
        if tags:
            label += "[" + ",".join(f"{k}={v}" for k, v in sorted(tags.items())) + "]"
        events.append(
            {
                "name": label,
                "cat": "counter",
                "ph": "C",
                "ts": ts_us,
                "pid": 0,
                "tid": 0,
                "args": {"value": value},
            }
        )
    return events


def build_chrome_trace(
    session_dir: str,
    task_events: list[dict] | None = None,
    include_counters: bool = True,
) -> dict:
    """Assemble the Trace Event Format dict for one session.

    ``task_events``: pass the controller's event log when connected (the
    CLI/dashboard do); None skips that layer. Counter snapshots are
    best-effort — a disconnected export still renders the spans."""
    spans = tracing.read_spans(session_dir)
    events = _span_events(spans)
    if task_events:
        events.extend(_task_event_events(task_events))
    if include_counters:
        now_us = time.time() * 1e6
        try:
            from ray_tpu._private import worker as worker_mod
            from ray_tpu.util import metrics

            points = list(metrics.local_engine_points())
            try:
                ctx = worker_mod.get_global_context()
                points.extend(metrics.control_plane_points(ctx))
            except Exception:  # rtlint: disable=swallowed-exception - control-plane counters are optional off-cluster
                pass
            events.extend(_counter_events(points, now_us))
        except Exception:  # rtlint: disable=swallowed-exception - counter events are optional enrichment
            pass
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def build_sequence_trace(session_dir: str, request_id: str) -> dict:
    """Perfetto view of ONE served sequence (ISSUE 19,
    ``ray_tpu timeline --seq <id>``): every span that shares the
    sequence's trace id — proxy request, replica handling, prefill, KV
    transfer/wire hops, channel push/pop, decode iterations — plus an
    instant event per emitted token, so TTFT and inter-token gaps are
    readable off the ruler.

    Raises KeyError when no terminal timeline record exists for
    ``request_id`` (not served, not sampled, or sampling disabled)."""
    from ray_tpu.serve.llm import observability as seq_obs

    seq_rec = None
    for rec in seq_obs.read_sequences(session_dir):
        if rec.get("kind") == "seq" and rec.get("request_id") == request_id:
            seq_rec = rec  # keep the LAST record (replays re-export)
    if seq_rec is None:
        raise KeyError(
            f"no sequence timeline record for request_id={request_id!r} "
            "(was the sequence sampled? see LLMConfig.seq_trace_sample)"
        )
    trace_id = seq_rec.get("trace_id") or ""
    spans = [
        s for s in tracing.read_spans(session_dir)
        if trace_id and s.get("trace_id") == trace_id
    ]
    events = _span_events(spans)
    # Token instants ride the ingress track (the earliest span's pid,
    # else a synthetic one): ts anchors on the trace's first span so
    # the relative emission offsets land on the same axis.
    starts = [s.get("start_ns") or 0 for s in spans if s.get("start_ns")]
    rels = seq_rec.get("token_rel_s") or []
    if starts:
        anchor_us = min(starts) / 1e3
    elif rels:
        # No spans (tracing off, sampled timeline only): reconstruct
        # the enqueue wall time from the terminal record's timestamp.
        anchor_us = (float(seq_rec.get("ts", 0.0)) - rels[-1]) * 1e6
    else:
        anchor_us = 0.0
    pid = spans[0].get("pid", 0) if spans else 0
    for i, rel_s in enumerate(rels):
        events.append({
            "name": f"token[{i}]",
            "cat": "token",
            "ph": "i",
            "s": "p",
            "ts": anchor_us + rel_s * 1e6,
            "pid": pid,
            "tid": 0,
            "args": {"request_id": request_id, "index": i},
        })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {"sequence": seq_rec},
    }

"""Scheduling strategies for tasks/actors.

Role-equivalent of python/ray/util/scheduling_strategies.py
(:: PlacementGroupSchedulingStrategy, NodeAffinitySchedulingStrategy).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any


@dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: Any
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclass
class NodeAffinitySchedulingStrategy:
    node_id: str
    soft: bool = False


# String strategies "DEFAULT" and "SPREAD" are passed directly as
# scheduling_strategy="SPREAD" (same as the reference).

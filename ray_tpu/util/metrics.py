"""User/library metrics — Counter/Gauge/Histogram.

Role-equivalent of python/ray/util/metrics.py (SURVEY §5.5): metrics
recorded anywhere in the cluster flow to the controller KV (namespace
"metrics", merged per metric+tags) and are exported by the dashboard's
/metrics endpoint in Prometheus text format — the role the per-node
metrics agent + OpenCensus pipeline [N27] plays in the reference.
"""

from __future__ import annotations

import atexit
import json
import logging
import threading
import time
from typing import Mapping, Optional, Sequence

from ray_tpu._private import worker as worker_mod

_FLUSH_INTERVAL_S = 2.0
_local_lock = threading.Lock()
_pending: dict[str, dict] = {}
_flusher_started = False


def _flush_loop() -> None:
    while True:
        time.sleep(_FLUSH_INTERVAL_S)
        try:
            flush()
        except Exception:
            # Keep the daemon alive across controller blips; debug-level
            # so a permanently broken uplink is still discoverable.
            logging.getLogger(__name__).debug(
                "metrics flush failed", exc_info=True
            )


def _ensure_flusher() -> None:
    global _flusher_started
    with _local_lock:
        if not _flusher_started:
            _flusher_started = True
            threading.Thread(target=_flush_loop, daemon=True).start()
            # Final flush at interpreter exit: a short-lived worker or
            # driver whose last points landed under one flush interval
            # ago would otherwise silently drop them (the daemon flusher
            # dies mid-sleep).
            atexit.register(_flush_at_exit)


def _flush_at_exit() -> None:
    try:
        flush()
    except Exception:
        logging.getLogger(__name__).debug(
            "final metrics flush failed", exc_info=True
        )


# Uplink RPCs issued by flush() since process start — observability
# for steady-state RPC accounting (one kv_multi_put per flush interval
# regardless of traffic; serve_llm's `steady_rpc_probe` attributes
# background uplinks by RPC method name when isolating request-path
# controller calls).
flush_rpcs_total = 0


def flush() -> None:
    """Push pending metric points to the controller KV — the whole tick
    rides ONE kv_multi_put RPC, not one kv_put per series."""
    global flush_rpcs_total
    with _local_lock:
        points = dict(_pending)
        _pending.clear()
    if not points:
        return
    try:
        ctx = worker_mod.get_global_context()
    except Exception:  # rtlint: disable=swallowed-exception - no cluster context: nothing to flush to
        return
    entries = [
        {"key": key, "value": json.dumps(point).encode()}
        for key, point in points.items()
    ]
    flush_rpcs_total += 1
    ctx.io.run(
        ctx.controller.call(
            "kv_multi_put",
            {
                "namespace": "metrics",
                "entries": entries,
                "overwrite": True,
            },
        )
    )


def _record(kind: str, name: str, description: str, tags: Mapping[str, str],
            value: float, buckets: Optional[Sequence[float]] = None) -> None:
    tag_str = ",".join(f'{k}="{v}"' for k, v in sorted(tags.items()))
    key = f"{name}{{{tag_str}}}"
    with _local_lock:
        point = _pending.get(key)
        if point is None:
            point = {
                "kind": kind,
                "name": name,
                "description": description,
                "tags": dict(tags),
                "value": 0.0,
                "count": 0,
                "sum": 0.0,
                "buckets": list(buckets) if buckets else None,
                "bucket_counts": [0] * (len(buckets) + 1) if buckets else None,
                "ts": time.time(),
            }
            _pending[key] = point
        if kind == "counter":
            point["value"] += value
        elif kind == "gauge":
            point["value"] = value
        else:  # histogram
            point["count"] += 1
            point["sum"] += value
            for i, bound in enumerate(point["buckets"]):
                if value <= bound:
                    point["bucket_counts"][i] += 1
                    break
            else:
                point["bucket_counts"][-1] += 1
        point["ts"] = time.time()
    _ensure_flusher()


class _Metric:
    kind = ""

    def __init__(
        self,
        name: str,
        description: str = "",
        tag_keys: Sequence[str] = (),
    ):
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys)
        self._default_tags: dict[str, str] = {}

    def set_default_tags(self, tags: Mapping[str, str]) -> "_Metric":
        self._default_tags = dict(tags)
        return self

    def _tags(self, tags: Optional[Mapping[str, str]]) -> dict:
        return {**self._default_tags, **(tags or {})}


class Counter(_Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, tags: Mapping[str, str] | None = None):
        _record("counter", self._name, self._description, self._tags(tags), value)


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, tags: Mapping[str, str] | None = None):
        _record("gauge", self._name, self._description, self._tags(tags), value)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(
        self,
        name: str,
        description: str = "",
        boundaries: Sequence[float] = (0.01, 0.1, 1, 10),
        tag_keys: Sequence[str] = (),
    ):
        super().__init__(name, description, tag_keys)
        self._boundaries = tuple(boundaries)

    def observe(self, value: float, tags: Mapping[str, str] | None = None):
        _record(
            "histogram", self._name, self._description, self._tags(tags),
            value, self._boundaries,
        )


# ---------------------------------------------------------------------------
# Collective-layer series (ISSUE 7): every ring/xla/hierarchical op feeds a
# bytes counter + latency histogram tagged by op and backend, so comm time
# and wire volume are dashboard queries (and summarize_comm() fodder).
# ---------------------------------------------------------------------------

_collective_bytes: Counter | None = None
_collective_latency: Histogram | None = None


def record_collective_op(
    op: str, backend: str, nbytes: int, seconds: float
) -> None:
    """One completed collective op: rt_collective_bytes_total (wire bytes
    where the backend measures them, logical payload otherwise) and
    rt_collective_op_latency_s, both tagged {op, backend}."""
    global _collective_bytes, _collective_latency
    if _collective_bytes is None:
        _collective_bytes = Counter(
            "rt_collective_bytes_total",
            description="Bytes moved by collective ops",
            tag_keys=("op", "backend"),
        )
        _collective_latency = Histogram(
            "rt_collective_op_latency_s",
            description="Collective op latency (seconds)",
            boundaries=(0.001, 0.01, 0.1, 1, 10),
            tag_keys=("op", "backend"),
        )
    tags = {"op": op, "backend": backend}
    _collective_bytes.inc(max(0, int(nbytes)), tags=tags)
    _collective_latency.observe(float(seconds), tags=tags)


# ---------------------------------------------------------------------------
# Comm flight recorder series (ISSUE 14): the per-process watchdog counts
# suspected stalls and exports an in-flight gauge each tick. Gauges are
# snapshots (overwritten, never drained), so a retried metrics flush stays
# idempotent — the PR-5 snapshot-don't-drain rule.
# ---------------------------------------------------------------------------

_comm_stalls: Counter | None = None
_comm_inflight: Gauge | None = None
_comm_inflight_age: Gauge | None = None


def record_comm_stall(group: str, channel: str) -> None:
    """One watchdog-suspected comm stall: rt_comm_stalls_total{group,
    channel} (channel = ``group:kind:tag-skeleton`` flight channel id)."""
    global _comm_stalls
    if _comm_stalls is None:
        _comm_stalls = Counter(
            "rt_comm_stalls_total",
            description="Comm watchdog suspected-stall events",
            tag_keys=("group", "channel"),
        )
    _comm_stalls.inc(1, tags={"group": group, "channel": channel})


def set_comm_inflight(count: int, oldest_age_s: float, identity: str) -> None:
    """Current in-flight comm ops on this process: rt_comm_inflight{worker}
    plus the age of the oldest one (the watchdog's stall candidate)."""
    global _comm_inflight, _comm_inflight_age
    if _comm_inflight is None:
        _comm_inflight = Gauge(
            "rt_comm_inflight",
            description="Comm ops currently in flight",
            tag_keys=("worker",),
        )
        _comm_inflight_age = Gauge(
            "rt_comm_inflight_oldest_age_s",
            description="Age of the oldest in-flight comm op (seconds)",
            tag_keys=("worker",),
        )
    tags = {"worker": identity}
    _comm_inflight.set(float(count), tags=tags)
    _comm_inflight_age.set(float(oldest_age_s), tags=tags)


# ---------------------------------------------------------------------------
# Serve SLO series (ISSUE 8): every proxied request feeds a per-route
# latency histogram + status counter; replicas push occupancy gauges.
# These are the Prometheus half of the flight recorder's serve view (the
# p50/p95/p99 snapshots ride the controller workload store).
# ---------------------------------------------------------------------------

_serve_latency: Histogram | None = None
_serve_requests: Counter | None = None
_serve_gauges: dict[str, Gauge] = {}

# SLO-shaped bounds: sub-5ms cache hits through multi-second tail.
SERVE_LATENCY_BOUNDARIES = (0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 5.0)


def record_serve_request(route: str, seconds: float, status: str) -> None:
    """One completed HTTP/handle request through the serve proxy:
    rt_serve_request_latency_s{route} + rt_serve_requests_total{route,
    status} where status is the HTTP class ("200", "404", "500", ...)."""
    global _serve_latency, _serve_requests
    if _serve_latency is None:
        _serve_latency = Histogram(
            "rt_serve_request_latency_s",
            description="Serve request latency through the proxy (seconds)",
            boundaries=SERVE_LATENCY_BOUNDARIES,
            tag_keys=("route",),
        )
        _serve_requests = Counter(
            "rt_serve_requests_total",
            description="Serve requests by route and status",
            tag_keys=("route", "status"),
        )
    _serve_latency.observe(float(seconds), tags={"route": route})
    _serve_requests.inc(1, tags={"route": route, "status": str(status)})


_serve_reliability_counters: dict[str, Counter] = {}

# Reliability event counters (ISSUE 13): every self-healing action on the
# serve path is countable, so "did the breaker trip / did we shed" is a
# dashboard query. Tag vocabulary is fixed per name below.
_SERVE_RELIABILITY_TAGS = {
    "retries": ("deployment", "reason"),
    "hedges": ("deployment", "outcome"),
    "shed": ("route", "where"),
    "drains": ("deployment", "trigger"),
    "stream_cancel_failures": ("deployment",),
    "proxy_restarts": ("proxy",),
    "deadline_exceeded": ("deployment",),
}


def inc_serve_reliability(name: str, n: int = 1, **tags: str) -> None:
    """Increment rt_serve_<name>_total (retries, hedges, shed, drains,
    stream_cancel_failures, proxy_restarts, deadline_exceeded)."""
    counter = _serve_reliability_counters.get(name)
    if counter is None:
        counter = _serve_reliability_counters[name] = Counter(
            f"rt_serve_{name}_total",
            description=f"Serve reliability events: {name.replace('_', ' ')}",
            tag_keys=_SERVE_RELIABILITY_TAGS.get(name, ()),
        )
    counter.inc(n, tags={k: str(v) for k, v in tags.items()})


def set_serve_breaker_state(
    deployment: str, replica_id: str, state: int
) -> None:
    """rt_serve_breaker_state{deployment,replica}: 0=closed, 1=half-open,
    2=open. A per-replica circuit breaker state transition gauge."""
    set_serve_replica_gauge("breaker_state", deployment, replica_id, state)


def set_serve_replica_gauge(
    name: str, deployment: str, replica_id: str, value: float
) -> None:
    """Replica-side occupancy gauges: rt_serve_<name>{deployment,
    replica}. Used for queue_depth, batch_occupancy, ongoing_requests."""
    gauge = _serve_gauges.get(name)
    if gauge is None:
        gauge = _serve_gauges[name] = Gauge(
            f"rt_serve_{name}",
            description=f"Serve replica {name.replace('_', ' ')}",
            tag_keys=("deployment", "replica"),
        )
    gauge.set(
        float(value), tags={"deployment": deployment, "replica": replica_id}
    )


_serve_token_hists: dict[str, Histogram] = {}
_serve_token_counter: Counter | None = None

# Token-level SLO bounds (ISSUE 19): TTFT spans queue wait + prefill +
# KV transfer + the first decode iteration (request-latency-shaped);
# TPOT is one decode iteration (orders of magnitude tighter).
SERVE_TTFT_BOUNDARIES = SERVE_LATENCY_BOUNDARIES
SERVE_TPOT_BOUNDARIES = (
    0.0005, 0.001, 0.002, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 1.0,
)


def record_serve_token_latency(
    kind: str, seconds: float, deployment: str
) -> None:
    """rt_serve_ttft_s / rt_serve_tpot_s {deployment}: time-to-first-
    token and time-per-output-token of the continuous-batching decode
    path (ISSUE 19 token-level SLO)."""
    hist = _serve_token_hists.get(kind)
    if hist is None:
        hist = _serve_token_hists[kind] = Histogram(
            f"rt_serve_{kind}_s",
            description=(
                "Time to first token (seconds)" if kind == "ttft"
                else "Time per output token (seconds)"
            ),
            boundaries=(
                SERVE_TTFT_BOUNDARIES if kind == "ttft"
                else SERVE_TPOT_BOUNDARIES
            ),
            tag_keys=("deployment",),
        )
    hist.observe(float(seconds), tags={"deployment": deployment})


def inc_serve_tokens(cls: str, n: int, deployment: str) -> None:
    """rt_serve_tokens_total{class,deployment}: the token goodput ledger
    (ISSUE 19) — ``issued`` plus its exact partition into productive /
    shed / evicted / replay_discarded as sequences reach a terminal
    state."""
    global _serve_token_counter
    if n <= 0:
        return
    if _serve_token_counter is None:
        _serve_token_counter = Counter(
            "rt_serve_tokens_total",
            description="Decode tokens by ledger class",
            tag_keys=("class", "deployment"),
        )
    _serve_token_counter.inc(
        n, tags={"class": cls, "deployment": deployment}
    )


def set_serve_kv_blocks(
    deployment: str, replica_id: str, used: int, free: int
) -> None:
    """rt_serve_kv_blocks_used / rt_serve_kv_blocks_free {deployment,
    replica}: the decode replica's paged-KV pool headroom (ISSUE 17
    satellite 2) — the memory signal behind the serve_llm autoscaler's
    kv_headroom_min floor."""
    set_serve_replica_gauge("kv_blocks_used", deployment, replica_id, used)
    set_serve_replica_gauge("kv_blocks_free", deployment, replica_id, free)


# ---------------------------------------------------------------------------
# Native/control-plane observability [N27]: the C++ engine's internal
# counters and the controller's queue depths surface as first-class
# Prometheus series, so "is the control plane draining?" is a dashboard
# query instead of a debugger session.
# ---------------------------------------------------------------------------

_CONTROLLER_GAUGES = (
    "pending_lease_shapes",
    "pending_lease_depth",
    "pending_demands",
    "pub_outbox_depth",
    "subscriber_conns",
    "mutation_cache_size",
    "nodes_alive",
)
_NODE_GAUGES = ("workers", "idle_workers", "leases", "bundles",
                "resource_waiters")


def local_engine_points() -> list:
    """(name, tags, value, kind) for every live native engine in THIS
    process (driver side; node agents report theirs via heartbeat)."""
    points: list = []
    try:
        from ray_tpu._private.rpc import _NativeEngine

        with _NativeEngine._lock:
            engines = sorted(_NativeEngine._by_loop.items())
    except Exception:
        return points
    for idx, (_loop_id, engine) in enumerate(engines):
        try:
            stats = engine.stats()
        except Exception:  # rtlint: disable=swallowed-exception - engine died mid-scrape; skip it
            continue
        for field, value in stats.items():
            points.append(
                (f"native_engine_{field}", {"engine": str(idx)},
                 float(value), "gauge")
            )
    return points


def control_plane_points(ctx) -> list:
    """(name, tags, value, kind) from the controller's live internals:
    its own counters/queue depths plus the per-node agent stats (worker
    pools + native engine counters) piggybacked on heartbeats."""
    points: list = []
    try:
        stats = ctx.io.run(
            ctx.controller.call("controller_stats", {}, timeout=5.0)
        )
    except Exception:
        return points
    for name, value in sorted((stats.get("counters") or {}).items()):
        points.append((f"controller_{name}", {}, float(value), "counter"))
    for field in _CONTROLLER_GAUGES:
        if field in stats:
            points.append(
                (f"controller_{field}", {}, float(stats[field]), "gauge")
            )
    for field, value in sorted((stats.get("snapshot") or {}).items()):
        points.append(
            (f"controller_snapshot_{field}", {}, float(value), "gauge")
        )
    for node_id, nstats in sorted((stats.get("node_stats") or {}).items()):
        for field in _NODE_GAUGES:
            if field in nstats:
                points.append(
                    (f"node_{field}", {"node": node_id},
                     float(nstats[field]), "gauge")
                )
        for field, value in sorted((nstats.get("engine") or {}).items()):
            points.append(
                (f"native_engine_{field}", {"node": node_id},
                 float(value), "gauge")
            )
    return points


# Node-sample fields exported 1:1 as per-node gauges (ISSUE 5). The
# full history stays in the controller's time-series store; /metrics
# exposes the CURRENT sample set the way Prometheus expects (it builds
# its own history by scraping).
_TELEMETRY_GAUGES = (
    "cpu_percent",
    "mem_used",
    "mem_total",
    "num_workers",
    "workers_rss_total",
    "workers_rss_max",
    "object_store_bytes",
    "object_store_capacity",
    "hbm_used",
    "hbm_total",
)


def telemetry_points(ctx) -> list:
    """(name, tags, value, kind) from each node's latest telemetry
    sample, plus per-worker RSS gauges and the oom_risk counter."""
    points: list = []
    try:
        summary = ctx.io.run(
            ctx.controller.call("resource_summary", {}, timeout=5.0)
        )
    except Exception:
        return points
    for node_id, entry in sorted((summary.get("nodes") or {}).items()):
        latest = entry.get("latest") or {}
        tags = {"node": node_id}
        for field in _TELEMETRY_GAUGES:
            if field in latest:
                points.append(
                    (f"node_{field}", tags, float(latest[field]), "gauge")
                )
        for worker_id, rss in sorted(
            (latest.get("worker_rss") or {}).items()
        ):
            points.append(
                ("worker_rss_bytes",
                 {"node": node_id, "worker": worker_id},
                 float(rss), "gauge")
            )
    points.append(
        ("oom_risk_events", {},
         float(summary.get("oom_risk_events") or 0), "counter")
    )
    return points


def _render_points(points, lines: list, seen_headers: set) -> None:
    for name, tags, value, kind in points:
        full = "ray_tpu_" + name
        if full not in seen_headers:
            seen_headers.add(full)
            lines.append(f"# HELP {full} internal {kind}")
            lines.append(f"# TYPE {full} {kind}")
        tag_str = ",".join(f'{k}="{v}"' for k, v in sorted(tags.items()))
        label = f"{{{tag_str}}}" if tag_str else ""
        lines.append(f"{full}{label} {value}")


def collect_prometheus_text() -> str:
    """Render every recorded metric in Prometheus exposition format."""
    try:
        ctx = worker_mod.get_global_context()
    except Exception:  # rtlint: disable=swallowed-exception - no cluster context: empty exposition
        return ""
    keys = ctx.io.run(
        ctx.controller.call("kv_keys", {"namespace": "metrics", "prefix": ""})
    )
    lines: list[str] = []
    seen_headers: set[str] = set()
    for key in sorted(keys):
        resp = ctx.io.run(
            ctx.controller.call("kv_get", {"namespace": "metrics", "key": key})
        )
        if resp.get("status") != "ok":
            continue
        point = json.loads(resp["value"])
        name = "ray_tpu_" + point["name"]
        if name not in seen_headers:
            seen_headers.add(name)
            lines.append(f"# HELP {name} {point['description']}")
            lines.append(f"# TYPE {name} {point['kind']}")
        tag_str = ",".join(
            f'{k}="{v}"' for k, v in sorted(point["tags"].items())
        )
        label = f"{{{tag_str}}}" if tag_str else ""
        if point["kind"] == "histogram":
            cum = 0
            for bound, count in zip(
                point["buckets"], point["bucket_counts"]
            ):
                cum += count
                sep = "," if tag_str else ""
                lines.append(
                    f'{name}_bucket{{{tag_str}{sep}le="{bound}"}} {cum}'
                )
            cum += point["bucket_counts"][-1]
            sep = "," if tag_str else ""
            lines.append(f'{name}_bucket{{{tag_str}{sep}le="+Inf"}} {cum}')
            lines.append(f"{name}_count{label} {point['count']}")
            lines.append(f"{name}_sum{label} {point['sum']}")
        else:
            lines.append(f"{name}{label} {point['value']}")
    _render_points(local_engine_points(), lines, seen_headers)
    _render_points(control_plane_points(ctx), lines, seen_headers)
    _render_points(telemetry_points(ctx), lines, seen_headers)
    return "\n".join(lines) + ("\n" if lines else "")

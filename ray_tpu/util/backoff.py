"""Full-jitter exponential backoff (AWS-style), shared by every retry loop.

One policy object, two consumers with different sleep substrates: the RPC
clients (`_private/rpc.py`) await it on the event loop, the trainer's
gang-recovery loop (`train/jax_trainer.py`) blocks a thread. Both need the
same *shape* — sleep U(0, ceiling) then double the ceiling — because the
failure they recover from is correlated: a controller crash or gang death
orphans every client at the same instant, and deterministic schedules turn
the reconnect into a synchronized thundering herd.

Stdlib-only on purpose: rpc.py sits below every other module, so this
helper must not import anything from ray_tpu.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field


@dataclass
class Backoff:
    """Iterative full-jitter backoff state.

    Each `next_delay()` samples U(0, ceiling) and doubles the ceiling up to
    `max_backoff_s`. `attempts` counts delays handed out; `reset()` rearms
    after a success.
    """

    initial_backoff_s: float = 0.1
    max_backoff_s: float = 10.0
    _ceiling: float = field(init=False, default=0.0)
    attempts: int = field(init=False, default=0)

    def __post_init__(self) -> None:
        self._ceiling = self.initial_backoff_s

    def next_delay(self, cap: float | None = None) -> float:
        """Sample the next delay. ``cap`` bounds the sample from above —
        serve retry loops pass the request's remaining deadline so a
        backoff never sleeps past the budget it is trying to spend."""
        delay = random.uniform(0, self._ceiling)
        self._ceiling = min(self._ceiling * 2, self.max_backoff_s)
        self.attempts += 1
        if cap is not None:
            delay = min(delay, max(0.0, cap))
        return delay

    def sleep(self) -> float:
        """Blocking variant (trainer-side). Returns the delay slept."""
        delay = self.next_delay()
        time.sleep(delay)
        return delay

    async def async_sleep(self) -> float:
        """Event-loop variant (RPC clients). Returns the delay awaited."""
        import asyncio

        delay = self.next_delay()
        await asyncio.sleep(delay)
        return delay

    def reset(self) -> None:
        self._ceiling = self.initial_backoff_s
        self.attempts = 0

"""Public fault-injection API — ``ray_tpu.util.chaos``.

The deterministic decision engine lives in ``ray_tpu._private.chaos``
(where the transport can import it without cycles); this module is the
user-facing face plus the pieces that need the CLUSTER, not just one
process:

  * :class:`FaultSchedule` / :func:`install` / :func:`get_injector` /
    :func:`reset` — re-exported from the core.
  * :class:`ChaosMonkey` — a driver-side thread that executes the
    schedule's *process-level* faults (SIGKILL workers / agents / the
    controller at scheduled offsets, optional restarts) against a
    ``ray_tpu.cluster_utils.Cluster``.
  * :func:`read_event_log` — collect every process's JSONL chaos events
    (sorted deterministically) so tests can assert that two runs of the
    same seed produced the identical fault sequence.

Quick start::

    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.chaos import FaultSchedule

    schedule = FaultSchedule(
        seed=7,
        drop_request=0.05, drop_reply=0.05, dup_reply=0.2,
        partitions=[{"src": "node:*", "dst": "controller",
                     "start_s": 5, "duration_s": 10}],
        kills=[{"at_s": 3, "target": "worker", "index": 0}],
    )
    cluster = Cluster(initialize_head=True)
    monkey = cluster.start_chaos(schedule, log_dir="/tmp/chaos")
    ...
    monkey.stop()

Environment form (equivalent, inherited by every cluster process)::

    RAY_TPU_chaos='{"seed": 7, "drop_request": 0.05, ...}'
"""

from __future__ import annotations

import json
import os
import threading
import time

from ray_tpu._private.chaos import (  # noqa: F401  (public re-exports)
    ChaosFault,
    ChaosInjector,
    FaultSchedule,
    failpoint,
    get_injector,
    install,
    reset,
    set_identity,
)

__all__ = [
    "ChaosFault",
    "ChaosInjector",
    "ChaosMonkey",
    "FaultSchedule",
    "failpoint",
    "get_injector",
    "install",
    "read_event_log",
    "reset",
    "set_identity",
]


class ChaosMonkey:
    """Executes a FaultSchedule's ``kills`` against a live Cluster.

    Each kill entry::

        {"at_s": 3.0,                 # offset from monkey start
         "target": "controller"       # or "agent:<idx>", "worker", "actor"
         "index": 0,                  # worker kills: deterministic victim
         "agent": 0,                  # worker kills: which agent to ask
         "prefer": "actor",           # worker kills: prefer actor workers
         "name": "SERVE_PROXY::8000", # actor kills: the named actor
         "restart_after_s": 2.0}      # controller only: restart delay

    Worker kills go through the agent's ``chaos_kill_worker`` RPC (the
    agent picks the victim deterministically and reports the death as a
    crash, not an intended exit). Runs on a daemon thread; every executed
    kill is appended to ``self.events``.
    """

    def __init__(self, cluster, schedule: FaultSchedule):
        self.cluster = cluster
        self.schedule = schedule
        self.events: list[dict] = []
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    def start(self) -> "ChaosMonkey":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="chaos-monkey", daemon=True
            )
            self._thread.start()
        return self

    def join(self, timeout: float | None = None) -> None:
        """Block until every scheduled kill has executed (or timeout)."""
        if self._thread is not None:
            self._thread.join(timeout)

    def stop(self) -> None:
        self._stop.set()
        self.join(timeout=5)

    # -- internals ---------------------------------------------------------
    def _run(self) -> None:
        start = time.monotonic()
        pending = sorted(
            self.schedule.kills, key=lambda k: float(k.get("at_s", 0.0))
        )
        for kill in pending:
            delay = float(kill.get("at_s", 0.0)) - (time.monotonic() - start)
            if delay > 0 and self._stop.wait(delay):
                return
            if self._stop.is_set():
                return
            try:
                self._execute(kill)
            except Exception as exc:  # a failed kill must not end the run
                self.events.append(
                    {"kill": kill, "status": "error", "error": str(exc)}
                )

    def _execute(self, kill: dict) -> None:
        target = kill.get("target", "worker")
        if target == "controller":
            self.cluster.kill_controller()
            self.events.append({"kill": kill, "status": "ok"})
            restart_after = kill.get("restart_after_s")
            if restart_after is not None:
                if self._stop.wait(float(restart_after)):
                    return
                self.cluster.restart_controller()
                self.events.append(
                    {"kill": kill, "status": "restarted"}
                )
            return
        if target.startswith("agent"):
            _, _, raw_index = target.partition(":")
            self.cluster.kill_agent(int(raw_index or 0))
            self.events.append({"kill": kill, "status": "ok"})
            return
        if target == "actor":
            # Named-actor kill (ISSUE 13): takes down serve proxies /
            # replicas / any detached actor by registry name, exercising
            # the controller's restart + client-failover paths.
            import ray_tpu

            name = kill["name"]
            ray_tpu.kill(ray_tpu.get_actor(name))
            self.events.append(
                {"kill": kill, "status": "ok", "actor_name": name}
            )
            return
        # Worker kill: ask the agent over a blocking wire-v1 client (this
        # thread has no asyncio loop).
        from ray_tpu._private.snapshot_store import _SyncWireClient

        agent_index = int(kill.get("agent", 0))
        host, port = self.cluster.agent_addrs[agent_index]
        client = _SyncWireClient(host, int(port), timeout=30.0)
        try:
            reply = client.call(
                "chaos_kill_worker",
                {
                    "index": int(kill.get("index", 0)),
                    "prefer": kill.get("prefer", "actor"),
                },
            )
        finally:
            try:
                if client._sock is not None:
                    client._sock.close()
            except Exception:  # rtlint: disable=swallowed-exception - socket already closed
                pass
        self.events.append(
            {"kill": kill, "status": reply.get("status"),
             "worker_id": reply.get("worker_id"),
             "actor_id": reply.get("actor_id")}
        )


def read_event_log(log_dir: str) -> list[dict]:
    """Every chaos event from every process, in a deterministic order.

    Events are sorted by (identity, point, method, n) — NOT wall-clock —
    because per-process decision counters are the reproducible coordinate
    system; timestamps differ between runs even when the fault sequence
    is identical. Two runs of the same seed and workload must yield equal
    lists (minus the "t" timestamps, which this strips).
    """
    events: list[dict] = []
    if not os.path.isdir(log_dir):
        return events
    for name in sorted(os.listdir(log_dir)):
        if not name.endswith(".jsonl"):
            continue
        with open(os.path.join(log_dir, name)) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    event = json.loads(line)
                except ValueError:
                    continue
                event.pop("t", None)
                events.append(event)
    events.sort(
        key=lambda e: (
            e.get("id", ""), e.get("point", ""), e.get("method", ""),
            e.get("n", 0),
        )
    )
    return events

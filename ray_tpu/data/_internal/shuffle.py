"""All-to-all stages: repartition, random_shuffle, sort, groupby-aggregate.

Role-equivalent of the reference's shuffle ops (SURVEY §2.7 "shuffle via
map/reduce task stages"): a map wave partitions each input block into N
parts, a reduce wave concatenates each partition's parts — all parts move
through the object store, so the shuffle is fully distributed.
"""

from __future__ import annotations

import zlib
from typing import Any, Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

import ray_tpu
from ray_tpu.data.block import BlockAccessor


def _stable_hash(v) -> int:
    """Deterministic across processes. The builtin hash() is per-process
    salted for str/bytes, and _split_block runs in different workers —
    the same groupby key would land in different partitions, silently
    producing duplicate keys with partial aggregates."""
    if isinstance(v, bytes):
        data = v
    elif isinstance(v, str):
        data = v.encode()
    else:
        data = repr(v).encode()
    return zlib.crc32(data)


@ray_tpu.remote
def _split_block(block, num_parts: int, mode: str, key, seed) -> list:
    """Map side. mode: 'slice' (repartition), 'random', 'range' (sort,
    key+bounds), 'hash' (groupby)."""
    table = BlockAccessor.for_block(block).block
    n = table.num_rows
    if mode == "slice":
        # Even contiguous split; reducer i gets rows [i*n/N, (i+1)*n/N).
        cuts = [round(i * n / num_parts) for i in range(num_parts + 1)]
        return [table.slice(cuts[i], cuts[i + 1] - cuts[i]) for i in range(num_parts)]
    if mode == "random":
        rng = np.random.default_rng(seed)
        assignment = rng.integers(0, num_parts, size=n)
    elif mode == "range":
        bounds = key["bounds"]
        col = table.column(key["key"]).to_numpy(zero_copy_only=False)
        assignment = np.searchsorted(bounds, col, side="right")
        if key.get("descending"):
            assignment = (num_parts - 1) - assignment
    elif mode == "hash":
        col = table.column(key).to_pandas()
        assignment = col.map(lambda v: _stable_hash(v) % num_parts).to_numpy()
    else:
        raise ValueError(mode)
    parts = []
    for i in range(num_parts):
        idx = np.nonzero(assignment == i)[0]
        parts.append(table.take(pa.array(idx)))
    return parts


@ray_tpu.remote
def _merge_parts(mode: str, key, seed, *parts):
    """Reduce side: concat my parts (+ per-mode post-processing)."""
    table = BlockAccessor.concat(list(parts))
    if mode == "random" and table.num_rows:
        rng = np.random.default_rng(seed)
        table = table.take(pa.array(rng.permutation(table.num_rows)))
    elif mode == "range" and table.num_rows:
        order = "descending" if key.get("descending") else "ascending"
        table = table.sort_by([(key["key"], order)])
    return table


def shuffle_blocks(
    block_refs: list,
    num_out: int,
    mode: str,
    key: Any = None,
    seed: Optional[int] = None,
) -> list:
    """Run the 2-wave shuffle; returns num_out output block refs."""
    if not block_refs:
        return []
    part_lists = [
        _split_block.options(num_returns=num_out).remote(
            ref, num_out, mode, key, None if seed is None else seed + i
        )
        for i, ref in enumerate(block_refs)
    ]
    if num_out == 1:
        part_lists = [[p] for p in part_lists]
    out = []
    for j in range(num_out):
        parts_j = [parts[j] for parts in part_lists]
        out.append(
            _merge_parts.remote(
                mode, key, None if seed is None else seed + 7919 * (j + 1), *parts_j
            )
        )
    return out


def sample_sort_bounds(block_refs: list, sort_key: str, num_out: int) -> np.ndarray:
    """Range-partition boundaries from a uniform sample (reference: sort's
    boundary sampling)."""

    @ray_tpu.remote
    def _sample(block, k):
        table = BlockAccessor.for_block(block).block
        if not table.num_rows:
            return np.array([])
        rng = np.random.default_rng(0)
        idx = rng.choice(table.num_rows, size=min(k, table.num_rows), replace=False)
        return table.take(pa.array(np.sort(idx))).column(sort_key).to_numpy(
            zero_copy_only=False
        )

    samples = ray_tpu.get([_sample.remote(ref, 64) for ref in block_refs])
    merged = np.sort(np.concatenate([s for s in samples if len(s)] or [np.array([])]))
    if not len(merged):
        return np.array([])
    quantiles = [
        merged[min(len(merged) - 1, int(len(merged) * q / num_out))]
        for q in range(1, num_out)
    ]
    return np.asarray(quantiles)


# ---- groupby aggregation ----

class AggregateFn:
    """name/init/accumulate(pa.Table column chunk)/merge/finalize."""

    def __init__(self, name: str, on: Optional[str]):
        self.name = name
        self.on = on

    def accumulate(self, table: pa.Table):
        raise NotImplementedError


class Count(AggregateFn):
    def __init__(self):
        super().__init__("count()", None)

    def accumulate(self, table: pa.Table):
        return table.num_rows


class _ColumnAgg(AggregateFn):
    _pc_fn: str = ""

    def __init__(self, on: str):
        super().__init__(f"{self._pc_fn}({on})", on)

    def accumulate(self, table: pa.Table):
        value = getattr(pc, self._pc_fn)(table.column(self.on))
        return value.as_py()


class Sum(_ColumnAgg):
    _pc_fn = "sum"


class Min(_ColumnAgg):
    _pc_fn = "min"


class Max(_ColumnAgg):
    _pc_fn = "max"


class Mean(_ColumnAgg):
    _pc_fn = "mean"


class Std(_ColumnAgg):
    _pc_fn = "stddev"


@ray_tpu.remote
def _agg_partition(key: Optional[str], aggs: list, *parts):
    table = BlockAccessor.concat(list(parts))
    if table.num_rows == 0:
        return table
    if key is None:
        row = {a.name: a.accumulate(table) for a in aggs}
        return BlockAccessor.for_block([row]).block
    out_rows = []
    # Partition is hash-complete per key: group locally.
    keys = table.column(key).to_pandas()
    for value in keys.drop_duplicates():
        mask = pc.equal(table.column(key), pa.scalar(value))
        group = table.filter(mask)
        row = {key: value}
        for agg in aggs:
            row[agg.name] = agg.accumulate(group)
        out_rows.append(row)
    out_rows.sort(key=lambda r: (r[key] is None, r[key]))
    return BlockAccessor.for_block(out_rows).block


def groupby_aggregate(
    block_refs: list, key: Optional[str], aggs: list, num_out: int
) -> list:
    if not block_refs:
        return []
    if key is None:
        # Global aggregate: single reduce over per-block partials would need
        # mergeable partials; simplest correct path: one reduce task.
        return [_agg_partition.remote(None, aggs, *block_refs)]
    num_out = min(num_out, len(block_refs)) or 1
    part_lists = [
        _split_block.options(num_returns=num_out).remote(ref, num_out, "hash", key, None)
        for ref in block_refs
    ]
    if num_out == 1:
        part_lists = [[p] for p in part_lists]
    return [
        _agg_partition.remote(key, aggs, *[parts[j] for parts in part_lists])
        for j in range(num_out)
    ]

"""Streaming executor — drives the physical stage pipeline.

Role-equivalent of python/ray/data/_internal/execution/streaming_executor.py
(SURVEY §2.7, §3.6): blocks stream through fused map stages with a bounded
in-flight task window (backpressure — the ReservationOpResourceAllocator's
budget role), materializing only at all-to-all barriers. Map stages run as
ray_tpu tasks (stateless UDFs) or an autoscaling actor pool (stateful/class
UDFs), mirroring TaskPoolMapOperator / ActorPoolMapOperator.
"""

from __future__ import annotations

import time
from typing import Any, Iterator

import ray_tpu
from ray_tpu.data.block import BlockAccessor, DataContext
from ray_tpu.data._internal.map_fn import instantiate_udfs, make_fused_fn
from ray_tpu.data._internal.plan import (
    Aggregate,
    AllToAllStage,
    InputData,
    Limit,
    MapStage,
    RandomShuffle,
    Read,
    Repartition,
    Sort,
    SourceStage,
    Union,
    Zip,
)
from ray_tpu.data._internal.shuffle import (
    groupby_aggregate,
    sample_sort_bounds,
    shuffle_blocks,
)


@ray_tpu.remote(num_returns=2)
def _run_read_task(read_task) -> Any:
    cpu0 = time.thread_time()
    blocks = list(read_task())
    out = BlockAccessor.concat(blocks)
    accessor = BlockAccessor.for_block(out)
    meta = {
        "rows": accessor.num_rows(),
        "bytes": accessor.size_bytes(),
        "cpu_s": time.thread_time() - cpu0,
    }
    return out, meta


@ray_tpu.remote(num_returns=2)
def _map_task(ops: list, block) -> Any:
    cpu0 = time.thread_time()
    out = make_fused_fn(ops)(block)
    accessor = BlockAccessor.for_block(out)
    meta = {
        "rows": accessor.num_rows(),
        "bytes": accessor.size_bytes(),
        "cpu_s": time.thread_time() - cpu0,
    }
    return out, meta


@ray_tpu.remote
def _num_rows(block) -> int:
    return BlockAccessor.for_block(block).num_rows()


@ray_tpu.remote
def _slice_block(block, start: int, end: int):
    return BlockAccessor.for_block(block).slice(start, end)


@ray_tpu.remote
class _MapActor:
    """Actor-pool worker: constructs stateful UDFs once, maps blocks.
    Accumulates its own execution stats (collected once at stage end —
    zero per-block overhead, unlike the task path's per-task metadata)."""

    def __init__(self, ops: list):
        self._ops = ops
        self._fused = make_fused_fn(ops, instantiate_udfs(ops))
        self._rows = 0
        self._bytes = 0
        self._cpu_s = 0.0
        self._tasks = 0

    def map(self, block) -> Any:
        cpu0 = time.thread_time()
        out = self._fused(block)
        accessor = BlockAccessor.for_block(out)
        self._rows += accessor.num_rows()
        self._bytes += accessor.size_bytes()
        self._cpu_s += time.thread_time() - cpu0
        self._tasks += 1
        return out

    def get_exec_stats(self) -> dict:
        return {
            "rows": self._rows, "bytes": self._bytes,
            "cpu_s": self._cpu_s, "tasks": self._tasks,
        }


def _collect_metas(stats: "_StageStats", meta_refs: list) -> None:
    """Fold completed per-task metadata into stage stats; one bounded wait
    total — tasks whose meta is not ready (early-stopped stream) are
    skipped, not waited for."""
    if not meta_refs:
        return
    try:
        ready, _ = ray_tpu.wait(
            meta_refs, num_returns=len(meta_refs), timeout=1.0
        )
        for ref in ready:
            stats.add_meta(ray_tpu.get(ref))
    except Exception:  # rtlint: disable=swallowed-exception - stats are advisory; never fail the run for them
        pass


class _StageStats:
    def __init__(self, name: str):
        self.name = name
        self.wall_s = 0.0
        self.blocks_out = 0
        self.rows_out = 0
        self.bytes_out = 0
        self.cpu_s = 0.0
        self.tasks = 0

    def add_meta(self, meta: dict) -> None:
        self.rows_out += meta.get("rows", 0)
        self.bytes_out += meta.get("bytes", 0)
        self.cpu_s += meta.get("cpu_s", 0.0)
        self.tasks += meta.get("tasks", 1)


class StreamingExecutor:
    def __init__(self, stages: list, ctx: DataContext | None = None):
        self.stages = stages
        self.ctx = ctx or DataContext.get_current()
        self.stage_stats: list[_StageStats] = []
        self._throttled = 0  # byte-budget admission rejections (stats)
        self._budget_checked_at = 0.0
        self._budget_over = False

    # -- public --

    def execute(self) -> Iterator:
        """Yield output block refs as they become available."""
        stream: Iterator = iter(())
        for stage in self.stages:
            stats = _StageStats(stage.describe())
            self.stage_stats.append(stats)
            if isinstance(stage, SourceStage):
                stream = self._run_source(stage, stats)
            elif isinstance(stage, MapStage):
                stream = self._run_map(stage, stream, stats)
            elif isinstance(stage, AllToAllStage):
                stream = self._run_all_to_all(stage, stream, stats)
            else:
                raise TypeError(stage)
        return stream

    def execute_to_refs(self) -> list:
        return list(self.execute())

    # -- backpressure ----------------------------------------------------
    def _admit(self, n_pending: int, window: int) -> bool:
        """Admission control = task window AND object-store byte budget
        (reference ReservationOpResourceAllocator role): beyond the first
        in-flight task, launching stops while the local arena sits above
        ``streaming_store_budget_fraction`` of capacity — a task-count
        window alone lets large-block pipelines overrun the store."""
        if n_pending >= window:
            return False
        if n_pending == 0:
            return True  # progress guarantee
        frac = getattr(self.ctx, "streaming_store_budget_fraction", 1.0)
        if frac >= 1.0:
            return True
        now = time.monotonic()
        if now - self._budget_checked_at > 0.05:
            # short-cached: one stats RPC per ~50ms, never one per launch
            self._budget_checked_at = now
            try:
                import ray_tpu._private.worker as worker_mod

                stats = worker_mod.get_global_context().store.stats()
                self._budget_over = (
                    stats["used"] > frac * stats["capacity"]
                )
            except Exception:
                self._budget_over = False  # no store visibility: window only
        if self._budget_over:
            self._throttled += 1
        return not self._budget_over

    # -- stages --

    def _run_source(self, stage: SourceStage, stats: _StageStats) -> Iterator:
        op = stage.op
        start = time.perf_counter()
        if isinstance(op, InputData):
            for block in op.blocks:
                stats.blocks_out += 1
                yield block if _is_ref(block) else ray_tpu.put(
                    BlockAccessor.for_block(block).block
                )
            stats.wall_s += time.perf_counter() - start
            return
        assert isinstance(op, Read)
        window = self.ctx.streaming_max_inflight_tasks
        pending: list = []
        meta_refs: list = []
        tasks = list(op.read_tasks)
        idx = 0
        try:
            while idx < len(tasks) or pending:
                while idx < len(tasks) and self._admit(len(pending), window):
                    block_ref, meta_ref = _run_read_task.remote(tasks[idx])
                    meta_refs.append(meta_ref)
                    pending.append(block_ref)
                    idx += 1
                ready, pending_rest = ray_tpu.wait(pending, num_returns=1)
                pending = list(pending_rest)
                for ref in ready:
                    stats.blocks_out += 1
                    stats.wall_s += time.perf_counter() - start
                    yield ref
                    start = time.perf_counter()
        finally:
            # Batched: one get at stream end, never a blocking RPC in the
            # per-block hot loop.
            _collect_metas(stats, meta_refs)

    def _run_map(
        self, stage: MapStage, stream: Iterator, stats: _StageStats
    ) -> Iterator:
        if stage.compute == "actors":
            yield from self._run_map_actors(stage, stream, stats)
            return
        window = self.ctx.streaming_max_inflight_tasks
        pending: list = []
        meta_refs: list = []
        start = time.perf_counter()
        exhausted = False
        try:
            while not exhausted or pending:
                while not exhausted and self._admit(len(pending), window):
                    try:
                        block_ref = next(stream)
                    except StopIteration:
                        exhausted = True
                        break
                    out_ref, meta_ref = _map_task.remote(stage.ops, block_ref)
                    meta_refs.append(meta_ref)
                    pending.append(out_ref)
                if not pending:
                    break
                ready, pending_rest = ray_tpu.wait(pending, num_returns=1)
                pending = list(pending_rest)
                for ref in ready:
                    stats.blocks_out += 1
                    stats.wall_s += time.perf_counter() - start
                    yield ref
                    start = time.perf_counter()
        finally:
            _collect_metas(stats, meta_refs)

    def _run_map_actors(
        self, stage: MapStage, stream: Iterator, stats: _StageStats
    ) -> Iterator:
        pool_size = self.ctx.actor_pool_min_size
        actors = [_MapActor.remote(stage.ops) for _ in range(pool_size)]
        per_actor_inflight = 2
        pending: dict[Any, int] = {}  # ref -> actor idx
        load = [0] * len(actors)
        start = time.perf_counter()
        exhausted = False
        completed = False
        try:
            while not exhausted or pending:
                while (
                    not exhausted
                    and min(load) < per_actor_inflight
                    and self._admit(len(pending), len(actors)
                                    * per_actor_inflight)
                ):
                    # autoscale up to max while all actors are busy
                    if (
                        all(l > 0 for l in load)
                        and len(actors) < self.ctx.actor_pool_max_size
                    ):
                        actors.append(_MapActor.remote(stage.ops))
                        load.append(0)
                    try:
                        block_ref = next(stream)
                    except StopIteration:
                        exhausted = True
                        break
                    target = load.index(min(load))
                    ref = actors[target].map.remote(block_ref)
                    pending[ref] = target
                    load[target] += 1
                if not pending:
                    break
                ready, _ = ray_tpu.wait(list(pending), num_returns=1)
                for ref in ready:
                    load[pending.pop(ref)] -= 1
                    stats.blocks_out += 1
                    stats.wall_s += time.perf_counter() - start
                    yield ref
                    start = time.perf_counter()
            completed = True
        finally:
            for actor in actors:
                if completed:
                    # Only on normal exhaustion: an early-stopped stream
                    # (e.g. a downstream limit) must not block teardown
                    # behind busy actors just to collect stats.
                    try:
                        stats.add_meta(
                            ray_tpu.get(
                                actor.get_exec_stats.remote(), timeout=10
                            )
                        )
                    except Exception:  # rtlint: disable=swallowed-exception - stats fetch from a busy actor at teardown
                        pass
                try:
                    ray_tpu.kill(actor)
                except Exception:  # rtlint: disable=swallowed-exception - actor already dead
                    pass

    def _run_all_to_all(
        self, stage: AllToAllStage, stream: Iterator, stats: _StageStats
    ) -> Iterator:
        op = stage.op
        start = time.perf_counter()

        if isinstance(op, Limit):
            taken = 0
            for ref in stream:
                if taken >= op.limit:
                    break
                rows = ray_tpu.get(_num_rows.remote(ref))
                if taken + rows <= op.limit:
                    taken += rows
                    stats.blocks_out += 1
                    yield ref
                else:
                    keep = op.limit - taken
                    taken = op.limit
                    stats.blocks_out += 1
                    yield _slice_block.remote(ref, 0, keep)
            stats.wall_s += time.perf_counter() - start
            return

        if isinstance(op, Union):
            for ref in stream:
                stats.blocks_out += 1
                yield ref
            for other_refs in op.others:
                for ref in other_refs:
                    stats.blocks_out += 1
                    yield ref
            stats.wall_s += time.perf_counter() - start
            return

        refs = list(stream)

        if isinstance(op, Repartition):
            out = shuffle_blocks(refs, op.num_blocks, "slice")
        elif isinstance(op, RandomShuffle):
            out = shuffle_blocks(
                refs, max(1, len(refs)), "random",
                seed=op.seed if op.seed is not None else int(time.time()),
            )
        elif isinstance(op, Sort):
            bounds = sample_sort_bounds(refs, op.key, max(1, len(refs)))
            out = shuffle_blocks(
                refs,
                max(1, len(refs)),
                "range",
                key={"key": op.key, "bounds": bounds, "descending": op.descending},
            )
            if op.descending:
                out = list(out)
        elif isinstance(op, Aggregate):
            out = groupby_aggregate(refs, op.key, op.aggs, max(1, len(refs)))
        elif isinstance(op, Zip):
            out = self._zip(refs, list(op.other))
        else:
            raise TypeError(op)
        for ref in out:
            stats.blocks_out += 1
            yield ref
        stats.wall_s += time.perf_counter() - start

    @staticmethod
    def _zip(left_refs: list, right_refs: list) -> list:
        @ray_tpu.remote
        def _concat_all(*blocks):
            return BlockAccessor.concat(list(blocks))

        @ray_tpu.remote
        def _zip_tables(left, right):
            import pyarrow as pa

            lt = BlockAccessor.for_block(left).block
            rt = BlockAccessor.for_block(right).block
            if lt.num_rows != rt.num_rows:
                raise ValueError(
                    f"zip row-count mismatch: {lt.num_rows} vs {rt.num_rows}"
                )
            cols = {name: lt.column(name) for name in lt.column_names}
            for name in rt.column_names:
                out_name = name
                while out_name in cols:
                    out_name = out_name + "_1"
                cols[out_name] = rt.column(name)
            return pa.table(cols)

        left = _concat_all.remote(*left_refs) if len(left_refs) != 1 else left_refs[0]
        right = (
            _concat_all.remote(*right_refs) if len(right_refs) != 1 else right_refs[0]
        )
        return [_zip_tables.remote(left, right)]


def _is_ref(obj: Any) -> bool:
    from ray_tpu import ObjectRef

    return isinstance(obj, ObjectRef)

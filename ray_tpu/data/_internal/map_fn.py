"""Fused map-chain application — the body of every map task.

Role-equivalent of the transform functions the reference's planner emits
(python/ray/data/_internal/planner/plan_udf_map_op.py): one function that
applies a fused run of map-like logical ops to one block, including batch
slicing + format conversion for map_batches UDFs.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator

import pyarrow as pa

from ray_tpu.data.block import BlockAccessor, BlockBuilder, _normalize
from ray_tpu.data._internal.plan import Filter, FlatMap, MapBatches, MapRows


def format_batch(table: pa.Table, batch_format: str) -> Any:
    if batch_format in ("numpy", "default", None):
        return BlockAccessor.for_block(table).to_numpy()
    if batch_format == "pandas":
        return table.to_pandas()
    if batch_format in ("pyarrow", "arrow"):
        return table
    raise ValueError(f"unknown batch_format {batch_format!r}")


def batch_blocks(
    table: pa.Table, batch_size: int | None
) -> Iterator[pa.Table]:
    if batch_size is None or table.num_rows <= batch_size:
        if table.num_rows:
            yield table
        return
    for start in range(0, table.num_rows, batch_size):
        yield table.slice(start, min(batch_size, table.num_rows - start))


def _apply_map_batches(op: MapBatches, fn: Callable, table: pa.Table) -> pa.Table:
    builder = BlockBuilder()
    for batch in batch_blocks(table, op.batch_size):
        formatted = format_batch(batch, op.batch_format)
        out = fn(formatted, *op.fn_args, **op.fn_kwargs)
        if out is None:
            continue
        # UDFs may yield multiple output batches (generator UDF).
        outs = out if isinstance(out, Iterator) else [out]
        for item in outs:
            builder.add_block(_normalize(item))
    return builder.build()


def _apply_rowwise(op, table: pa.Table) -> pa.Table:
    rows = table.to_pylist()
    if isinstance(op, MapRows):
        new_rows = [op.fn(row) for row in rows]
    elif isinstance(op, FlatMap):
        new_rows = [out for row in rows for out in op.fn(row)]
    elif isinstance(op, Filter):
        new_rows = [row for row in rows if op.fn(row)]
    else:
        raise TypeError(op)
    if not new_rows:
        return table.slice(0, 0)
    builder = BlockBuilder()
    for row in new_rows:
        builder.add_row(row)
    return builder.build()


def make_fused_fn(ops: list, udf_instances: dict[int, Callable] | None = None):
    """Build block → block applying the fused chain. `udf_instances` maps
    op index → constructed callable for actor-compute MapBatches classes."""

    def fused(block) -> pa.Table:
        table = _normalize(block)
        for idx, op in enumerate(ops):
            if isinstance(op, MapBatches):
                fn = (udf_instances or {}).get(idx)
                if fn is None:
                    fn = op.fn
                    if isinstance(fn, type):
                        fn = fn(*op.fn_constructor_args)
                table = _apply_map_batches(op, fn, table)
            else:
                table = _apply_rowwise(op, table)
        return table

    return fused


def instantiate_udfs(ops: list) -> dict[int, Callable]:
    """Construct stateful UDF classes once (actor-pool compute)."""
    instances: dict[int, Callable] = {}
    for idx, op in enumerate(ops):
        if isinstance(op, MapBatches) and isinstance(op.fn, type):
            instances[idx] = op.fn(*op.fn_constructor_args)
    return instances

"""TFRecord + tf.Example codec, dependency-free.

Role-equivalent of python/ray/data/read_api.py :: read_tfrecords /
Dataset.write_tfrecords — without TensorFlow. Both layers are simple,
stable wire formats implemented directly:

  * TFRecord framing: per record
        [u64 length][u32 masked_crc32c(length)][data][u32 masked_crc32c(data)]
    CRCs are written correctly (crc32c when google-crc32c/ crc32c is
    importable, else zlib.crc32 — flagged in the header as non-standard is
    NOT possible, so when no crc32c implementation exists we still write
    zlib values; our reader does not verify CRCs, matching common readers'
    default) — see _masked_crc.
  * tf.Example protobuf: Example{features: Features{feature:
    map<string, Feature{oneof bytes_list|float_list|int64_list}>}} —
    a ~hundred-line protobuf wire codec covers exactly this schema.
"""

from __future__ import annotations

import struct
from typing import Any, Iterator

_U64 = struct.Struct("<Q")
_U32 = struct.Struct("<I")

try:  # real crc32c if any implementation is available
    import crc32c as _crc32c_mod

    def _crc32c(data: bytes) -> int:
        return _crc32c_mod.crc32c(data)
except Exception:  # pragma: no cover - environment-dependent
    try:
        from google_crc32c import value as _gcrc

        def _crc32c(data: bytes) -> int:
            return _gcrc(data)
    except Exception:
        import zlib

        def _crc32c(data: bytes) -> int:
            # Fallback: wrong polynomial, but self-consistent — files we
            # write are readable by us; readers (incl. ours) don't verify.
            return zlib.crc32(data) & 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# record framing
# ---------------------------------------------------------------------------
def read_records(path: str) -> Iterator[bytes]:
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if len(header) < 12:
                return
            (length,) = _U64.unpack_from(header, 0)
            data = f.read(length)
            if len(data) < length:
                raise ValueError(f"{path}: truncated tfrecord")
            f.read(4)  # data crc (not verified)
            yield data


def write_records(path: str, records: Iterator[bytes]) -> int:
    n = 0
    # rtlint: disable=non-atomic-write - streaming record file of unbounded size; readers detect truncation via per-record CRC framing
    with open(path, "wb") as f:
        for data in records:
            header = _U64.pack(len(data))
            f.write(header)
            f.write(_U32.pack(_masked_crc(header)))
            f.write(data)
            f.write(_U32.pack(_masked_crc(data)))
            n += 1
    return n


# ---------------------------------------------------------------------------
# protobuf wire primitives (just what tf.Example needs)
# ---------------------------------------------------------------------------
def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _write_varint(out: bytearray, value: int) -> None:
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return


def _iter_fields(buf: bytes) -> Iterator[tuple[int, int, Any]]:
    """Yields (field_number, wire_type, value) over a message buffer."""
    pos = 0
    end = len(buf)
    while pos < end:
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:  # varint
            value, pos = _read_varint(buf, pos)
        elif wire == 1:  # 64-bit
            value = buf[pos : pos + 8]
            pos += 8
        elif wire == 2:  # length-delimited
            length, pos = _read_varint(buf, pos)
            value = buf[pos : pos + length]
            pos += length
        elif wire == 5:  # 32-bit
            value = buf[pos : pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wire}")
        yield field, wire, value


def _field(out: bytearray, number: int, wire: int) -> None:
    _write_varint(out, (number << 3) | wire)


def _bytes_field(out: bytearray, number: int, data: bytes) -> None:
    _field(out, number, 2)
    _write_varint(out, len(data))
    out += data


# ---------------------------------------------------------------------------
# tf.Example decode/encode
# ---------------------------------------------------------------------------
def decode_example(data: bytes) -> dict:
    """tf.Example bytes -> {name: list|scalar}. Single-element lists are
    unwrapped to scalars (the reference's read_tfrecords behavior)."""
    features: dict[str, Any] = {}
    for field, _w, value in _iter_fields(data):
        if field != 1:  # Example.features
            continue
        for f2, _w2, feature_map_entry in _iter_fields(value):
            if f2 != 1:  # Features.feature (map entry)
                continue
            name, feature = None, None
            for f3, _w3, v3 in _iter_fields(feature_map_entry):
                if f3 == 1:
                    name = v3.decode()
                elif f3 == 2:
                    feature = v3
            if name is None or feature is None:
                continue
            features[name] = _decode_feature(feature)
    return features


def _decode_feature(buf: bytes):
    for field, _w, value in _iter_fields(buf):
        if field == 1:  # BytesList
            out = [v for f, _ww, v in _iter_fields(value) if f == 1]
        elif field == 2:  # FloatList (packed or unpacked float32)
            out = []
            for f, wire, v in _iter_fields(value):
                if f != 1:
                    continue
                if wire == 2:  # packed
                    out += [
                        struct.unpack_from("<f", v, i)[0]
                        for i in range(0, len(v), 4)
                    ]
                else:
                    out.append(struct.unpack("<f", v)[0])
        elif field == 3:  # Int64List (packed or unpacked varint)
            out = []
            for f, wire, v in _iter_fields(value):
                if f != 1:
                    continue
                if wire == 2:  # packed
                    pos = 0
                    while pos < len(v):
                        item, pos = _read_varint(v, pos)
                        out.append(_to_signed(item))
                else:
                    out.append(_to_signed(v))
        else:
            continue
        return out[0] if len(out) == 1 else out
    return None


def _to_signed(value: int) -> int:
    return value - (1 << 64) if value >= (1 << 63) else value


def encode_example(row: dict) -> bytes:
    """{name: scalar|list of int/float/str/bytes} -> tf.Example bytes.
    None values are omitted (tf.Example's missing-feature convention);
    numeric lists mixing ints and floats are promoted to FloatList."""
    features = bytearray()
    for name, value in row.items():
        if value is None:
            continue
        values = value if isinstance(value, (list, tuple)) else [value]
        values = [v for v in values if v is not None]
        if not values:
            continue
        if any(isinstance(v, float) for v in values):
            values = [float(v) for v in values]
        feature = bytearray()
        if values and isinstance(values[0], (bytes, str)):
            blist = bytearray()
            for v in values:
                _bytes_field(blist, 1, v.encode() if isinstance(v, str) else v)
            _bytes_field(feature, 1, bytes(blist))
        elif values and isinstance(values[0], float):
            packed = b"".join(struct.pack("<f", float(v)) for v in values)
            flist = bytearray()
            _bytes_field(flist, 1, packed)
            _bytes_field(feature, 2, bytes(flist))
        else:  # ints (incl. bools, numpy ints)
            packed = bytearray()
            for v in values:
                _write_varint(packed, int(v) & 0xFFFFFFFFFFFFFFFF)
            ilist = bytearray()
            _bytes_field(ilist, 1, bytes(packed))
            _bytes_field(feature, 3, bytes(ilist))
        entry = bytearray()
        _bytes_field(entry, 1, name.encode())
        _bytes_field(entry, 2, bytes(feature))
        features_entry = bytearray()
        _bytes_field(features_entry, 1, bytes(entry))
        features += features_entry
    example = bytearray()
    _bytes_field(example, 1, bytes(features))
    return bytes(example)

"""Logical plan: declarative ops + fusion into physical stages.

Role-equivalent of python/ray/data/_internal/logical/ + _internal/planner/
(SURVEY §2.7): Dataset methods append LogicalOps; the planner fuses maximal
runs of row/batch-wise ops into single task functions (operator fusion —
one ray task applies the whole fused chain per block), and all-to-all ops
(shuffle/sort/groupby/repartition) become barrier stages.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional


@dataclass
class LogicalOp:
    name: str = "op"


@dataclass
class Read(LogicalOp):
    """Leaf: a datasource's read tasks (each returns an iterator of blocks)."""

    read_tasks: list = field(default_factory=list)  # list[Callable[[], Iterator[Block]]]
    name: str = "Read"


@dataclass
class InputData(LogicalOp):
    """Leaf: pre-materialized blocks (from_items / from_numpy / from_arrow)."""

    blocks: list = field(default_factory=list)  # list[ObjectRef | Block]
    name: str = "InputData"


@dataclass
class MapBatches(LogicalOp):
    fn: Any = None  # callable or actor class
    batch_size: Optional[int] = None
    batch_format: str = "numpy"
    compute: str = "tasks"  # "tasks" | "actors"
    fn_args: tuple = ()
    fn_kwargs: dict = field(default_factory=dict)
    fn_constructor_args: tuple = ()
    num_cpus: float = 1.0
    name: str = "MapBatches"


@dataclass
class MapRows(LogicalOp):
    fn: Callable = None
    name: str = "Map"


@dataclass
class FlatMap(LogicalOp):
    fn: Callable = None
    name: str = "FlatMap"


@dataclass
class Filter(LogicalOp):
    fn: Callable = None
    name: str = "Filter"


@dataclass
class Limit(LogicalOp):
    limit: int = 0
    name: str = "Limit"


@dataclass
class Repartition(LogicalOp):
    num_blocks: int = 1
    name: str = "Repartition"


@dataclass
class RandomShuffle(LogicalOp):
    seed: Optional[int] = None
    name: str = "RandomShuffle"


@dataclass
class Sort(LogicalOp):
    key: str = ""
    descending: bool = False
    name: str = "Sort"


@dataclass
class Aggregate(LogicalOp):
    key: Optional[str] = None
    aggs: list = field(default_factory=list)  # list[AggregateFn]
    name: str = "Aggregate"


@dataclass
class Zip(LogicalOp):
    other: "LogicalPlan" = None
    name: str = "Zip"


@dataclass
class Union(LogicalOp):
    others: list = field(default_factory=list)  # list[LogicalPlan]
    name: str = "Union"


class LogicalPlan:
    def __init__(self, ops: list[LogicalOp]):
        self.ops = ops

    def with_op(self, op: LogicalOp) -> "LogicalPlan":
        return LogicalPlan(self.ops + [op])

    def describe(self) -> str:
        return " -> ".join(op.name for op in self.ops)


# ---- planner: fuse map-like runs into stages ----

_MAPLIKE = (MapBatches, MapRows, FlatMap, Filter)


@dataclass
class MapStage:
    """A fused run of map-like ops executed as ONE task per input block."""

    ops: list[LogicalOp]
    compute: str = "tasks"
    fn_actor_cls: Any = None  # set when any MapBatches uses actor compute
    name: str = "MapStage"

    def describe(self) -> str:
        return "+".join(op.name for op in self.ops)


@dataclass
class AllToAllStage:
    op: LogicalOp
    name: str = "AllToAll"

    def describe(self) -> str:
        return self.op.name


@dataclass
class SourceStage:
    op: LogicalOp  # Read | InputData

    def describe(self) -> str:
        return self.op.name


def plan_stages(plan: LogicalPlan) -> list:
    """Linear planner: source stage, then alternating fused-map / barrier
    stages in op order."""
    if not plan.ops:
        return []
    stages: list = [SourceStage(plan.ops[0])]
    run: list[LogicalOp] = []

    def flush():
        nonlocal run
        if run:
            compute = "tasks"
            for op in run:
                if isinstance(op, MapBatches) and op.compute == "actors":
                    compute = "actors"
            stages.append(MapStage(run, compute=compute))
            run = []

    for op in plan.ops[1:]:
        if isinstance(op, _MAPLIKE):
            run.append(op)
        else:
            flush()
            stages.append(AllToAllStage(op))
    flush()
    return stages

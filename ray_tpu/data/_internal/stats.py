"""DatasetStats — per-operator execution stats.

Role-equivalent of python/ray/data/_internal/stats.py :: DatasetStats:
wall time, block and row counts per stage, rendered by Dataset.stats().
"""

from __future__ import annotations


class DatasetStats:
    def __init__(self):
        self.stages: list[dict] = []
        self.total_wall_s: float = 0.0

    def record_stage(self, name: str, wall_s: float, blocks: int, rows: int) -> None:
        self.stages.append(
            {"stage": name, "wall_s": wall_s, "blocks": blocks, "rows": rows}
        )
        self.total_wall_s += wall_s

    def summary_string(self) -> str:
        lines = ["Dataset execution stats:"]
        for s in self.stages:
            lines.append(
                f"  {s['stage']}: {s['wall_s'] * 1000:.1f}ms, "
                f"{s['blocks']} blocks, {s['rows']} rows"
            )
        lines.append(f"  total: {self.total_wall_s * 1000:.1f}ms")
        return "\n".join(lines)

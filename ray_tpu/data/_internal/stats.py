"""DatasetStats — per-operator execution stats.

Role-equivalent of python/ray/data/_internal/stats.py :: DatasetStats:
per-operator wall time, task-side CPU time, task counts, output
rows/bytes, plus consumption-side iterator wait time — rendered as the
table behind Dataset.stats(), so "where did my ingest time go" has an
answer (wall vs cpu separates scheduling overhead from UDF cost; iterator
wait separates producer-bound from consumer-bound pipelines).
"""

from __future__ import annotations


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024
    return f"{n:.1f}GiB"


class DatasetStats:
    def __init__(self):
        self.stages: list[dict] = []
        self.total_wall_s: float = 0.0
        # Consumption side (recorded by DataIterator): time the consumer
        # spent blocked waiting for the next block, vs time in user code.
        self.iter_wait_s: float = 0.0
        self.iter_user_s: float = 0.0
        self.iter_local_s: float = 0.0
        self.iter_batches: int = 0

    def record_stage(
        self,
        name: str,
        wall_s: float,
        blocks: int,
        rows: int,
        *,
        bytes_out: int = 0,
        cpu_s: float = 0.0,
        tasks: int = 0,
    ) -> None:
        self.stages.append(
            {
                "stage": name,
                "wall_s": wall_s,
                "blocks": blocks,
                "rows": rows,
                "bytes": bytes_out,
                "cpu_s": cpu_s,
                "tasks": tasks,
            }
        )
        self.total_wall_s += wall_s

    def record_iter(self, wait_s: float, user_s: float, batches: int,
                    local_s: float = 0.0) -> None:
        self.iter_wait_s += wait_s
        self.iter_user_s += user_s
        self.iter_local_s += local_s
        self.iter_batches += batches

    def replace_stages(self, stage_stats: list) -> None:
        """Install the per-operator records of ONE execution (streaming
        epochs re-execute the plan; stats reflect the latest run, while
        iterator counters keep accumulating)."""
        self.stages = []
        self.total_wall_s = 0.0
        for s in stage_stats:
            self.record_stage(
                s.name, s.wall_s, s.blocks_out, s.rows_out,
                bytes_out=s.bytes_out, cpu_s=s.cpu_s, tasks=s.tasks,
            )

    def summary_string(self) -> str:
        header = (
            f"  {'operator':<28} {'wall':>9} {'cpu':>9} {'tasks':>6} "
            f"{'blocks':>7} {'rows':>10} {'bytes':>10}"
        )
        lines = ["Dataset execution stats:", header]
        for s in self.stages:
            lines.append(
                f"  {s['stage']:<28} {s['wall_s'] * 1e3:>7.1f}ms "
                f"{s['cpu_s'] * 1e3:>7.1f}ms {s['tasks']:>6} "
                f"{s['blocks']:>7} {s['rows']:>10} "
                f"{_fmt_bytes(s['bytes']):>10}"
            )
        lines.append(f"  total wall: {self.total_wall_s * 1e3:.1f}ms")
        if self.iter_batches:
            lines.append(
                f"  iterator: {self.iter_batches} batches, "
                f"wait {self.iter_wait_s * 1e3:.1f}ms "
                f"(blocked on producers), "
                f"local {self.iter_local_s * 1e3:.1f}ms "
                f"(batching/format), "
                f"user {self.iter_user_s * 1e3:.1f}ms"
            )
        return "\n".join(lines)

"""Dataset — lazy distributed data transformations.

Role-equivalent of python/ray/data/dataset.py :: Dataset (SURVEY §2.7):
methods append logical ops; execution happens on consumption (iter_*,
take, count, write_*, materialize) through the streaming executor. Blocks
are Arrow tables in the object store.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Iterator, Optional

import ray_tpu
from ray_tpu.data.block import BlockAccessor, DataContext
from ray_tpu.data.iterator import DataIterator, streaming_split
from ray_tpu.data._internal import shuffle as shuffle_mod
from ray_tpu.data._internal.plan import (
    Aggregate,
    Filter,
    FlatMap,
    Limit,
    LogicalPlan,
    MapBatches,
    MapRows,
    RandomShuffle,
    Repartition,
    Sort,
    Union,
    Zip,
)
from ray_tpu.data._internal.stats import DatasetStats
from ray_tpu.data._internal.streaming_executor import StreamingExecutor, _num_rows
from ray_tpu.data._internal.plan import plan_stages


class Dataset:
    def __init__(self, plan: LogicalPlan):
        from ray_tpu._private import usage

        usage.record_feature("data")
        self._plan = plan
        self._materialized_refs: Optional[list] = None
        self._stats = DatasetStats()

    # ---- transformations (lazy) ----

    def _with_op(self, op) -> "Dataset":
        return Dataset(self._plan.with_op(op))

    def map(self, fn: Callable[[dict], dict]) -> "Dataset":
        return self._with_op(MapRows(fn=fn))

    def map_batches(
        self,
        fn: Any,
        *,
        batch_size: Optional[int] = None,
        batch_format: str = "numpy",
        compute: Optional[str] = None,
        fn_args: tuple = (),
        fn_kwargs: dict | None = None,
        fn_constructor_args: tuple = (),
        num_cpus: float = 1.0,
        concurrency: Optional[int] = None,
    ) -> "Dataset":
        if compute is None:
            compute = "actors" if isinstance(fn, type) else "tasks"
        if concurrency is not None:
            ctx = DataContext.get_current()
            ctx.actor_pool_max_size = max(ctx.actor_pool_max_size, concurrency)
        return self._with_op(
            MapBatches(
                fn=fn,
                batch_size=batch_size,
                batch_format=batch_format,
                compute=compute,
                fn_args=fn_args,
                fn_kwargs=fn_kwargs or {},
                fn_constructor_args=fn_constructor_args,
                num_cpus=num_cpus,
            )
        )

    def flat_map(self, fn: Callable[[dict], list]) -> "Dataset":
        return self._with_op(FlatMap(fn=fn))

    def filter(self, fn: Callable[[dict], bool]) -> "Dataset":
        return self._with_op(Filter(fn=fn))

    def limit(self, n: int) -> "Dataset":
        return self._with_op(Limit(limit=n))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._with_op(Repartition(num_blocks=num_blocks))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return self._with_op(RandomShuffle(seed=seed))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return self._with_op(Sort(key=key, descending=descending))

    def groupby(self, key: Optional[str]) -> "GroupedData":
        return GroupedData(self, key)

    def zip(self, other: "Dataset") -> "Dataset":
        return self._with_op(Zip(other=other._refs()))

    def union(self, *others: "Dataset") -> "Dataset":
        return self._with_op(Union(others=[o._refs() for o in others]))

    def select_columns(self, cols: list[str]) -> "Dataset":
        return self.map_batches(
            lambda b: b.select(cols), batch_format="pyarrow"
        )

    def drop_columns(self, cols: list[str]) -> "Dataset":
        def drop(table):
            keep = [c for c in table.column_names if c not in cols]
            return table.select(keep)

        return self.map_batches(drop, batch_format="pyarrow")

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        def add(table):
            return table.append_column(name, fn(table))

        return self.map_batches(add, batch_format="pyarrow")

    def random_sample(self, fraction: float, *, seed: Optional[int] = None) -> "Dataset":
        import numpy as np

        def sample(table):
            rng = np.random.default_rng(seed)
            mask = rng.random(table.num_rows) < fraction
            import pyarrow as pa

            return table.filter(pa.array(mask))

        return self.map_batches(sample, batch_format="pyarrow")

    # ---- execution ----

    def _refs(self) -> list:
        if self._materialized_refs is None:
            executor = StreamingExecutor(plan_stages(self._plan))
            self._materialized_refs = executor.execute_to_refs()
            self._stats.replace_stages(executor.stage_stats)
        return self._materialized_refs

    def _streaming_refs(self) -> Iterator:
        if self._materialized_refs is not None:
            return iter(self._materialized_refs)
        executor = StreamingExecutor(plan_stages(self._plan))

        def run() -> Iterator:
            try:
                yield from executor.execute()
            finally:
                # The consumed run's operator stats feed ds.stats() — a
                # streamed dataset must not re-execute just to report.
                self._stats.replace_stages(executor.stage_stats)

        return run()

    def materialize(self) -> "Dataset":
        self._refs()
        return self

    def iterator(self) -> DataIterator:
        return DataIterator(self._streaming_refs, stats=self._stats)

    def iter_batches(self, **kwargs) -> Iterator:
        return self.iterator().iter_batches(**kwargs)

    def iter_torch_batches(self, **kwargs) -> Iterator:
        return self.iterator().iter_torch_batches(**kwargs)

    def iter_rows(self) -> Iterator[dict]:
        return self.iterator().iter_rows()

    def streaming_split(
        self,
        n: int,
        *,
        equal: bool = True,
        resume_from: dict | None = None,
    ) -> list[DataIterator]:
        return streaming_split(self._refs(), n, resume_from=resume_from)

    def split(self, n: int) -> list["Dataset"]:
        refs = self._refs()
        shards = [refs[i::n] for i in range(n)]
        return [from_block_refs(shard) for shard in shards]

    # ---- consumption ----

    def take(self, n: int = 20) -> list[dict]:
        rows: list[dict] = []
        for row in self.iter_rows():
            rows.append(row)
            if len(rows) >= n:
                break
        return rows

    def take_all(self) -> list[dict]:
        return list(self.iter_rows())

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def count(self) -> int:
        return sum(ray_tpu.get([_num_rows.remote(r) for r in self._refs()]))

    def num_blocks(self) -> int:
        return len(self._refs())

    def schema(self):
        refs = self._refs()
        if not refs:
            return None
        return BlockAccessor.for_block(ray_tpu.get(refs[0])).schema()

    def columns(self) -> list[str]:
        schema = self.schema()
        return list(schema.names) if schema is not None else []

    def to_pandas(self):
        import pandas as pd

        tables = [
            BlockAccessor.for_block(b).to_pandas()
            for b in ray_tpu.get(self._refs())
        ]
        tables = [t for t in tables if len(t)]
        if not tables:
            return pd.DataFrame()
        return pd.concat(tables, ignore_index=True)

    def to_arrow(self):
        return BlockAccessor.concat(ray_tpu.get(self._refs()))

    def stats(self) -> str:
        # Execute only if nothing has run yet — a consumed streaming run
        # already recorded its operator stats.
        if not self._stats.stages and self._materialized_refs is None:
            self._refs()
        return self._stats.summary_string()

    # aggregates
    def sum(self, on: str):
        return self._global_agg(shuffle_mod.Sum(on))

    def min(self, on: str):
        return self._global_agg(shuffle_mod.Min(on))

    def max(self, on: str):
        return self._global_agg(shuffle_mod.Max(on))

    def mean(self, on: str):
        return self._global_agg(shuffle_mod.Mean(on))

    def std(self, on: str):
        return self._global_agg(shuffle_mod.Std(on))

    def _global_agg(self, agg):
        out = self._with_op(Aggregate(key=None, aggs=[agg]))
        rows = out.take_all()
        return rows[0][agg.name] if rows else None

    def aggregate(self, *aggs):
        out = self._with_op(Aggregate(key=None, aggs=list(aggs)))
        rows = out.take_all()
        return rows[0] if rows else {}

    # ---- writes ----

    def write_parquet(self, path: str) -> None:
        self._write(path, "parquet")

    def write_csv(self, path: str) -> None:
        self._write(path, "csv")

    def write_json(self, path: str) -> None:
        self._write(path, "json")

    def _write(self, path: str, fmt: str) -> None:
        import os

        os.makedirs(path, exist_ok=True)

        @ray_tpu.remote
        def _write_block(block, out_path: str, fmt: str) -> str:
            table = BlockAccessor.for_block(block).block
            if fmt == "parquet":
                import pyarrow.parquet as pq

                pq.write_table(table, out_path)
            elif fmt == "csv":
                import pyarrow.csv as pacsv

                pacsv.write_csv(table, out_path)
            elif fmt == "json":
                table.to_pandas().to_json(out_path, orient="records", lines=True)
            return out_path

        ext = {"parquet": "parquet", "csv": "csv", "json": "json"}[fmt]
        refs = [
            _write_block.remote(
                block_ref, f"{path}/part-{i:05d}.{ext}", fmt
            )
            for i, block_ref in enumerate(self._refs())
        ]
        ray_tpu.get(refs)

    def write_tfrecords(self, path: str) -> None:
        """One TFRecord file of tf.Example protos per block (in-tree codec,
        no TensorFlow)."""
        import os

        os.makedirs(path, exist_ok=True)

        @ray_tpu.remote
        def _write_block(block, out_path: str) -> str:
            from ray_tpu.data._internal.tfrecord import (
                encode_example, write_records,
            )

            accessor = BlockAccessor.for_block(block)
            write_records(
                out_path,
                (encode_example(row) for row in accessor.iter_rows()),
            )
            return out_path

        refs = [
            _write_block.remote(block_ref, f"{path}/part-{i:05d}.tfrecord")
            for i, block_ref in enumerate(self._refs())
        ]
        ray_tpu.get(refs)

    def write_datasink(self, datasink) -> None:
        """Write through a custom Datasink plugin (reference:
        Dataset.write_datasink + datasink.py lifecycle)."""
        datasink.on_write_start()
        try:
            @ray_tpu.remote
            def _write_task(sink, task_index: int, *blocks):
                tables = [BlockAccessor.for_block(b).block for b in blocks]
                return sink.write(tables, {"task_index": task_index})

            refs = [
                _write_task.remote(datasink, i, block_ref)
                for i, block_ref in enumerate(self._refs())
            ]
            results = ray_tpu.get(refs)
        except Exception as exc:
            datasink.on_write_failed(exc)
            raise
        datasink.on_write_complete(results)

    def __repr__(self):
        return f"Dataset(plan={self._plan.describe()})"


class GroupedData:
    """Dataset.groupby(key) result — reference: grouped_data.py."""

    def __init__(self, ds: Dataset, key: Optional[str]):
        self._ds = ds
        self._key = key

    def _agg(self, *aggs) -> Dataset:
        return self._ds._with_op(Aggregate(key=self._key, aggs=list(aggs)))

    def aggregate(self, *aggs) -> Dataset:
        return self._agg(*aggs)

    def count(self) -> Dataset:
        return self._agg(shuffle_mod.Count())

    def sum(self, on: str) -> Dataset:
        return self._agg(shuffle_mod.Sum(on))

    def min(self, on: str) -> Dataset:
        return self._agg(shuffle_mod.Min(on))

    def max(self, on: str) -> Dataset:
        return self._agg(shuffle_mod.Max(on))

    def mean(self, on: str) -> Dataset:
        return self._agg(shuffle_mod.Mean(on))

    def std(self, on: str) -> Dataset:
        return self._agg(shuffle_mod.Std(on))

    def map_groups(self, fn: Callable) -> Dataset:
        """Apply fn to each whole group (hash-partitioned by key)."""
        key = self._key

        def apply_groups(table):
            import pyarrow.compute as pc
            import pyarrow as pa

            out = []
            values = table.column(key).to_pandas().drop_duplicates()
            for value in values:
                group = table.filter(pc.equal(table.column(key), pa.scalar(value)))
                result = fn(BlockAccessor.for_block(group).to_numpy())
                out.append(BlockAccessor.for_block(result).block)
            return BlockAccessor.concat(out) if out else table.slice(0, 0)

        shuffled = self._ds._with_op(
            Repartition(num_blocks=max(1, self._ds.num_blocks()))
        )
        # Hash-partition so each group lands wholly in one block.
        refs = shuffle_mod.shuffle_blocks(
            shuffled._refs(), max(1, len(shuffled._refs())), "hash", key
        )
        return from_block_refs(refs).map_batches(
            apply_groups, batch_format="pyarrow", batch_size=None
        )


def from_block_refs(refs: list) -> Dataset:
    from ray_tpu.data._internal.plan import InputData

    ds = Dataset(LogicalPlan([InputData(blocks=list(refs))]))
    ds._materialized_refs = list(refs)
    return ds

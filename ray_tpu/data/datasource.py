"""Datasource / Datasink plugin protocol.

Role-equivalent of python/ray/data/datasource/datasource.py :: Datasource
(get_read_tasks/estimate_inmemory_data_size) and datasink.py :: Datasink
(on_write_start/write/on_write_complete/on_write_failed) — SURVEY §2.7.
Custom connectors implement these and plug into read_datasource /
Dataset.write_datasink; every built-in format rides the same machinery.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional


class ReadTask:
    """One unit of parallel read work: a callable yielding blocks, plus
    optional metadata used for scheduling/row estimates."""

    def __init__(self, read_fn: Callable[[], Iterable], *,
                 num_rows: Optional[int] = None,
                 size_bytes: Optional[int] = None,
                 input_files: Optional[list] = None):
        self._read_fn = read_fn
        self.num_rows = num_rows
        self.size_bytes = size_bytes
        self.input_files = input_files or []

    def __call__(self) -> Iterable:
        return self._read_fn()


class Datasource:
    """Implement get_read_tasks (and optionally the size estimate)."""

    def get_name(self) -> str:
        return type(self).__name__.replace("Datasource", "") or "Custom"

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        raise NotImplementedError

    # Legacy single-method form: subclasses may implement read_all()
    # returning an iterable of blocks; the default get_read_tasks wraps it.


class Datasink:
    """Implement write(); lifecycle hooks are optional."""

    def on_write_start(self) -> None:
        pass

    def write(self, blocks: Iterable, ctx: dict) -> Any:
        """Called once per write task with an iterable of blocks (pyarrow
        tables). Returns an opaque per-task result passed to
        on_write_complete."""
        raise NotImplementedError

    def on_write_complete(self, write_results: list) -> None:
        pass

    def on_write_failed(self, error: Exception) -> None:
        pass

    @property
    def num_rows_per_write(self) -> Optional[int]:
        return None

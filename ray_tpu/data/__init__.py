"""ray_tpu.data — streaming distributed datasets (Ray Data-equivalent).

Lazy logical plans over Arrow blocks in the object store, a streaming
executor with bounded in-flight backpressure, task/actor-pool map
operators, map/reduce shuffles, and ML-ingest iterators (streaming_split
into train gangs). SURVEY §2.7.
"""

from ray_tpu.data.block import BlockAccessor, BlockMetadata, DataContext
from ray_tpu.data.dataset import Dataset, GroupedData, from_block_refs
from ray_tpu.data.datasource import Datasink, Datasource, ReadTask
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data.read_api import (
    from_arrow,
    from_huggingface,
    from_items,
    from_numpy,
    from_pandas,
    from_torch,
    range,
    range_tensor,
    read_csv,
    read_datasource,
    read_images,
    read_json,
    read_numpy,
    read_parquet,
    read_text,
    read_tfrecords,
)
from ray_tpu.data._internal.shuffle import Count, Max, Mean, Min, Std, Sum

__all__ = [
    "Dataset",
    "GroupedData",
    "DataIterator",
    "DataContext",
    "BlockAccessor",
    "BlockMetadata",
    "from_block_refs",
    "from_items",
    "from_numpy",
    "from_arrow",
    "from_pandas",
    "from_torch",
    "from_huggingface",
    "range",
    "range_tensor",
    "read_parquet",
    "read_csv",
    "read_json",
    "read_numpy",
    "read_images",
    "read_text",
    "read_tfrecords",
    "read_datasource",
    "Datasource",
    "Datasink",
    "ReadTask",
    "Count",
    "Sum",
    "Min",
    "Max",
    "Mean",
    "Std",
]

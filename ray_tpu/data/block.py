"""Blocks — the unit of data movement.

Role-equivalent of python/ray/data/block.py :: Block / BlockAccessor /
BlockMetadata (SURVEY §2.7). A Block is an Arrow table (canonical), a
pandas DataFrame, or a dict of numpy columns; BlockAccessor normalizes
access. Blocks live in the object store between operators — Arrow's
columnar buffers serialize as out-of-band pickle-5 buffers, so hand-off is
zero-copy on the read side (the same economics as the reference's plasma
blocks).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Iterator, Optional

import numpy as np
import pyarrow as pa


@dataclass
class BlockMetadata:
    num_rows: int
    size_bytes: int
    schema: Optional[Any] = None
    input_files: list[str] = field(default_factory=list)
    exec_stats: Optional[dict] = None


@dataclass
class DataContext:
    """Global knobs — reference: python/ray/data/context.py :: DataContext.
    target_max_block_size mirrors the ~128 MiB default."""

    target_max_block_size: int = 128 * 1024 * 1024
    target_min_block_size: int = 1 * 1024 * 1024
    read_op_min_num_blocks: int = 8
    actor_pool_min_size: int = 1
    actor_pool_max_size: int = 4
    streaming_max_inflight_tasks: int = 8
    # Object-store BYTE budget for streaming admission (the reference's
    # ReservationOpResourceAllocator role): no new task launches while
    # store usage exceeds this fraction of arena capacity — a task-count
    # window alone lets a large-block pipeline overrun the arena.
    # Progress is always guaranteed (>=1 task stays admitted). Counts
    # TOTAL usage including results the consumer retains: a caller
    # holding more than the budget deliberately degrades the pipeline
    # toward serial (spill-pressure beats arena overrun).
    streaming_store_budget_fraction: float = 0.75
    eager_free: bool = True

    _current: "DataContext | None" = None

    @classmethod
    def get_current(cls) -> "DataContext":
        if cls._current is None:
            cls._current = cls()
        return cls._current


class BlockAccessor:
    """Normalized view over any block representation."""

    def __init__(self, block: Any):
        self._block = block

    @staticmethod
    def for_block(block: Any) -> "BlockAccessor":
        return BlockAccessor(_normalize(block))

    @property
    def block(self) -> pa.Table:
        return self._block

    def num_rows(self) -> int:
        return self._block.num_rows

    def size_bytes(self) -> int:
        return self._block.nbytes

    def schema(self):
        return self._block.schema

    def metadata(self, input_files: list[str] | None = None) -> BlockMetadata:
        return BlockMetadata(
            num_rows=self.num_rows(),
            size_bytes=self.size_bytes(),
            schema=self.schema(),
            input_files=input_files or [],
        )

    def to_arrow(self) -> pa.Table:
        return self._block

    def to_pandas(self):
        return self._block.to_pandas()

    def to_numpy(self, columns: list[str] | None = None) -> dict[str, np.ndarray]:
        table = self._block
        names = columns or table.column_names
        out = {}
        for name in names:
            col = table.column(name)
            try:
                out[name] = _chunked_to_numpy(col)
            except (pa.ArrowInvalid, ValueError):
                out[name] = np.asarray(col.to_pylist(), dtype=object)
        return out

    def iter_rows(self) -> Iterator[dict]:
        yield from self._block.to_pylist()

    def slice(self, start: int, end: int) -> pa.Table:
        return self._block.slice(start, end - start)

    def take(self, indices) -> pa.Table:
        return self._block.take(pa.array(indices))

    def select(self, columns: list[str]) -> pa.Table:
        return self._block.select(columns)

    def sample(self, n: int, rng: np.random.Generator) -> pa.Table:
        n = min(n, self.num_rows())
        idx = rng.choice(self.num_rows(), size=n, replace=False)
        return self.take(np.sort(idx))

    @staticmethod
    def concat(blocks: list[Any]) -> pa.Table:
        tables = [_normalize(b) for b in blocks if _normalize(b).num_rows > 0]
        if not tables:
            return pa.table({})
        return pa.concat_tables(tables, promote_options="permissive")

    @staticmethod
    def builder() -> "BlockBuilder":
        return BlockBuilder()


class BlockBuilder:
    """Accumulate rows/batches, emit blocks at a target size."""

    def __init__(self):
        self._tables: list[pa.Table] = []
        self._rows: list[dict] = []
        self._size = 0

    def add_row(self, row: dict) -> None:
        self._rows.append(row)
        self._size += sum(_rough_size(v) for v in row.values())

    def add_block(self, block: Any) -> None:
        table = _normalize(block)
        if table.num_rows:
            self._tables.append(table)
            self._size += table.nbytes

    def size_bytes(self) -> int:
        return self._size

    def num_rows(self) -> int:
        return sum(t.num_rows for t in self._tables) + len(self._rows)

    def build(self) -> pa.Table:
        if self._rows:
            self._tables.append(_rows_to_table(self._rows))
            self._rows = []
        if not self._tables:
            return pa.table({})
        out = pa.concat_tables(self._tables, promote_options="permissive")
        self._tables = [out]
        return out


def _chunked_to_numpy(col: pa.ChunkedArray) -> np.ndarray:
    if col.num_chunks == 1:
        chunk = col.chunk(0)
        if isinstance(chunk, (pa.FixedSizeListArray, pa.ListArray)):
            return _list_array_to_numpy(chunk)
        return chunk.to_numpy(zero_copy_only=False)
    if col.num_chunks and isinstance(
        col.chunk(0), (pa.FixedSizeListArray, pa.ListArray)
    ):
        return np.concatenate([_list_array_to_numpy(c) for c in col.chunks])
    return col.to_numpy()


def _list_array_to_numpy(arr) -> np.ndarray:
    """Tensor columns stored as nested fixed-size list arrays → stacked
    ndarray with the original trailing shape restored."""
    if isinstance(arr, pa.FixedSizeListArray):
        shape = []
        atype = arr.type
        values = arr
        while pa.types.is_fixed_size_list(atype):
            shape.append(atype.list_size)
            values = values.values
            atype = atype.value_type
        flat = values.to_numpy(zero_copy_only=False)
        return flat.reshape((len(arr), *shape))
    return np.asarray(arr.to_pylist(), dtype=object)


def _rows_to_table(rows: list[dict]) -> pa.Table:
    if not rows:
        return pa.table({})
    columns: dict[str, list] = {k: [] for k in rows[0]}
    for row in rows:
        for key in columns:
            columns[key].append(row.get(key))
    return _normalize(columns)


def _normalize(block: Any) -> pa.Table:
    """Canonicalize to Arrow. ndarray values become tensor (list) columns."""
    if isinstance(block, pa.Table):
        return block
    if isinstance(block, dict):
        arrays = {}
        for name, values in block.items():
            arrays[name] = _column_to_arrow(values)
        return pa.table(arrays)
    if isinstance(block, list):
        return _rows_to_table(block)
    try:
        import pandas as pd

        if isinstance(block, pd.DataFrame):
            return pa.Table.from_pandas(block, preserve_index=False)
    except ImportError:
        pass
    raise TypeError(f"cannot treat {type(block).__name__} as a block")


def _column_to_arrow(values: Any) -> pa.Array:
    if isinstance(values, pa.Array):
        return values
    arr = np.asarray(values)
    if arr.ndim > 1:
        out = pa.array(arr.reshape(-1))
        for dim in reversed(arr.shape[1:]):
            out = pa.FixedSizeListArray.from_arrays(out, dim)
        return out
    if arr.dtype == object:
        return pa.array(list(values))
    return pa.array(arr)


def _rough_size(value: Any) -> int:
    if isinstance(value, np.ndarray):
        return value.nbytes
    if isinstance(value, (bytes, str)):
        return len(value)
    return 8


@dataclass
class ExecStats:
    """Per-task execution stats feeding DatasetStats (SURVEY §2.7)."""

    wall_s: float = 0.0
    rows: int = 0
    blocks: int = 0

    @staticmethod
    def timer():
        return time.perf_counter()

"""DataIterator — consumption-side streaming with prefetch.

Role-equivalent of python/ray/data/iterator.py :: DataIterator.iter_batches
(threaded block prefetch, format conversion) and streaming_split's
per-consumer iterators (SURVEY §2.7 "ML ingest"). Batches come out as
numpy dicts (default), pandas, arrow, or torch CPU tensors.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterator, Optional

import ray_tpu
from ray_tpu.data.block import BlockAccessor
from ray_tpu.data._internal.map_fn import batch_blocks, format_batch


class DataIterator:
    def __init__(self, ref_iter_factory, owner_name: str = "dataset",
                 stats=None):
        """ref_iter_factory: () -> iterator of block refs (fresh each epoch)."""
        self._factory = ref_iter_factory
        self._owner_name = owner_name
        self._stats = stats
        self._fetch_wait_s = 0.0

    def _block_iter(self, prefetch_blocks: int) -> Iterator:
        """Fetch blocks with a prefetch thread (depth = prefetch_blocks+1)."""
        refs = self._factory()
        q: queue.Queue = queue.Queue(maxsize=max(1, prefetch_blocks + 1))
        _DONE = object()

        def producer():
            try:
                for ref in refs:
                    q.put(ray_tpu.get(ref))
            except BaseException as exc:
                q.put(exc)
                return
            q.put(_DONE)

        thread = threading.Thread(target=producer, daemon=True)
        thread.start()
        import time as _time

        while True:
            t0 = _time.perf_counter()
            item = q.get()
            # Time truly blocked on producers (vs local batching/format).
            self._fetch_wait_s += _time.perf_counter() - t0
            if item is _DONE:
                return
            if isinstance(item, BaseException):
                raise item
            yield item

    def iter_batches(self, **kwargs) -> Iterator[Any]:
        """Yields formatted batches; when the owning Dataset tracks stats,
        records wait-on-producer vs in-user-code time (the "is my input
        pipeline the bottleneck" split of ds.stats())."""
        inner = self._iter_batches_impl(**kwargs)
        if self._stats is None:
            yield from inner
            return
        import time as _time

        produce_s = user_s = 0.0
        batches = 0
        last_yield_end = None
        self._fetch_wait_s = 0.0
        try:
            while True:
                resume = _time.perf_counter()
                if last_yield_end is not None:
                    user_s += resume - last_yield_end
                try:
                    batch = next(inner)
                except StopIteration:
                    break
                produce_s += _time.perf_counter() - resume
                batches += 1
                yield batch
                last_yield_end = _time.perf_counter()
        finally:
            # Split production time into blocked-on-producers (block fetch
            # wait, measured in _block_iter) vs local batching/formatting.
            wait_s = min(self._fetch_wait_s, produce_s)
            self._stats.record_iter(
                wait_s, user_s, batches, local_s=produce_s - wait_s
            )

    def _iter_batches_impl(
        self,
        *,
        batch_size: Optional[int] = 256,
        batch_format: str = "numpy",
        prefetch_blocks: int = 2,
        drop_last: bool = False,
        local_shuffle_buffer_size: Optional[int] = None,
        local_shuffle_seed: Optional[int] = None,
    ) -> Iterator[Any]:
        import numpy as np

        carry = None
        shuffle_rng = (
            np.random.default_rng(local_shuffle_seed)
            if local_shuffle_buffer_size
            else None
        )
        buffer = []
        buffered_rows = 0

        def emit(table):
            nonlocal carry
            for batch in batch_blocks(table, batch_size):
                if batch_size and batch.num_rows < batch_size:
                    carry = batch
                    return
                yield format_batch(batch, batch_format)

        for block in self._block_iter(prefetch_blocks):
            table = BlockAccessor.for_block(block).block
            if carry is not None:
                table = BlockAccessor.concat([carry, table])
                carry = None
            if shuffle_rng is not None:
                buffer.append(table)
                buffered_rows += table.num_rows
                if buffered_rows < local_shuffle_buffer_size:
                    continue
                merged = BlockAccessor.concat(buffer)
                buffer, buffered_rows = [], 0
                import pyarrow as pa

                table = merged.take(
                    pa.array(shuffle_rng.permutation(merged.num_rows))
                )
            yield from emit(table)
        if buffer:
            merged = BlockAccessor.concat(buffer)
            import pyarrow as pa

            table = merged.take(pa.array(shuffle_rng.permutation(merged.num_rows)))
            if carry is not None:
                table = BlockAccessor.concat([carry, table])
                carry = None
            yield from emit(table)
        if carry is not None and (not drop_last or batch_size is None):
            yield format_batch(carry, batch_format)

    def iter_rows(self) -> Iterator[dict]:
        for batch in self.iter_batches(batch_size=None, batch_format="pyarrow"):
            yield from batch.to_pylist()

    def iter_torch_batches(
        self, *, batch_size: Optional[int] = 256, dtypes=None, **kwargs
    ) -> Iterator[dict]:
        import torch

        for batch in self.iter_batches(
            batch_size=batch_size, batch_format="numpy", **kwargs
        ):
            out = {}
            for key, value in batch.items():
                tensor = torch.as_tensor(value)
                if dtypes is not None:
                    want = dtypes.get(key) if isinstance(dtypes, dict) else dtypes
                    if want is not None:
                        tensor = tensor.to(want)
                out[key] = tensor
            yield out

    def materialize_refs(self) -> list:
        return list(self._factory())


@ray_tpu.remote
class _SplitCoordinator:
    """Round-robin block assignment to n consumers (locality-blind twin of
    the reference's streaming_split OutputSplitter; equalize=True keeps
    per-consumer row counts within one block)."""

    def __init__(self, block_refs: list, n: int):
        self._queues: list[list] = [[] for _ in range(n)]
        for i, ref in enumerate(block_refs):
            self._queues[i % n].append(ref)

    def get_blocks(self, rank: int) -> list:
        return self._queues[rank]


def streaming_split(block_refs: list, n: int) -> list[DataIterator]:
    """n independent DataIterators over a disjoint partition of blocks."""
    coordinator = _SplitCoordinator.remote(list(block_refs), n)
    iterators = []
    for rank in range(n):
        shard_refs = ray_tpu.get(coordinator.get_blocks.remote(rank))

        def factory(refs=shard_refs):
            return iter(refs)

        iterators.append(DataIterator(factory, owner_name=f"split[{rank}]"))
    return iterators

"""DataIterator — consumption-side streaming with prefetch + resumable state.

Role-equivalent of python/ray/data/iterator.py :: DataIterator.iter_batches
(threaded block prefetch, format conversion) and streaming_split's
per-consumer iterators (SURVEY §2.7 "ML ingest"). Batches come out as
numpy dicts (default), pandas, arrow, or torch CPU tensors.

Resume-exact ingest (ISSUE 6): split iterators are *span-based* — a shard
is an ordered list of ``[block_idx, start, stop]`` spans over the global
block list — and expose ``state_dict()`` / ``load_state_dict()`` carrying
(epoch, spans, rows-consumed-this-epoch). ``streaming_split(...,
resume_from=...)`` rebuilds shards from a set of per-rank states captured
at a checkpoint, subtracting consumed rows and re-partitioning the
*remaining* sample space across the new world size — so a restart at any
world size replays no committed sample and drops none.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterator, Optional

import ray_tpu
from ray_tpu.data.block import BlockAccessor
from ray_tpu.data._internal.map_fn import batch_blocks, format_batch


def _span_slice(block, start: int, stop: Optional[int]):
    """Slice rows [start, stop) out of a block (stop=None → to the end)."""
    table = BlockAccessor.for_block(block).block
    if start == 0 and (stop is None or stop >= table.num_rows):
        return table
    end = table.num_rows if stop is None else min(stop, table.num_rows)
    return table.slice(start, end - start)


class DataIterator:
    def __init__(self, ref_iter_factory=None, owner_name: str = "dataset",
                 stats=None, *, block_refs: list | None = None,
                 spans: list | None = None):
        """Two construction modes:

        * ``ref_iter_factory``: () -> iterator of block refs (fresh each
          epoch). Streaming pipelines; position is not resumable.
        * ``block_refs`` + ``spans``: a materialized global block list plus
          this consumer's ordered [block_idx, start, stop] spans — the
          split-shard mode, which supports state_dict/load_state_dict.
        """
        if (ref_iter_factory is None) == (block_refs is None):
            raise ValueError(
                "exactly one of ref_iter_factory or block_refs is required"
            )
        self._factory = ref_iter_factory
        self._block_refs = block_refs
        self._base_spans = [list(s) for s in spans] if spans is not None else None
        self._owner_name = owner_name
        self._stats = stats
        self._fetch_wait_s = 0.0
        # Resume position: epoch counter, spans for the *current* pass
        # (differs from _base_spans only on the first pass after a resume),
        # rows to skip at the head of the current pass, and rows delivered
        # so far in the in-flight pass (counted at batch-yield time).
        self._epoch = 0
        self._resume_spans: list | None = None
        self._resume_skip = 0
        self._pass_rows = 0
        self._pass_active = False

    @property
    def fetch_wait_s(self) -> float:
        """Cumulative seconds the consumer spent blocked on producers —
        the flight recorder's data-wait clock (ISSUE 8): each
        ``train.report()`` interval attributes the delta to the step's
        ``data_wait_s`` phase."""
        return self._fetch_wait_s

    # -- resumable-ingest state ----------------------------------------
    @property
    def supports_state(self) -> bool:
        return self._base_spans is not None

    def state_dict(self) -> dict:
        """Position snapshot: {"epoch", "rows", "spans"}.

        ``rows`` counts rows *delivered to the caller* in the current epoch
        (a partially-assembled carry batch is not counted — those rows were
        never seen by user code and will be re-read on resume). ``spans``
        are the spans of the in-flight pass, so a state taken mid-resume
        composes: resuming a resumed run subtracts from the right base.
        """
        if self._resume_spans is not None:
            spans = self._resume_spans
            rows = self._pass_rows if self._pass_active else self._resume_skip
        else:
            spans = self._base_spans
            rows = self._pass_rows
        return {
            "epoch": self._epoch,
            "rows": rows,
            "spans": [list(s) for s in spans] if spans is not None else None,
        }

    def load_state_dict(self, state: dict) -> None:
        """Resume this iterator at a position captured by ``state_dict()``
        (same world size — for cross-size resumes go through
        ``streaming_split(..., resume_from=...)``)."""
        if not self.supports_state:
            raise ValueError(
                f"{self._owner_name}: streaming (factory-based) iterators "
                "cannot load ingest state; materialize + split instead"
            )
        if state.get("spans") is None:
            raise ValueError("state has no spans; not a split-shard state")
        self._epoch = int(state.get("epoch", 0))
        self._resume_spans = [list(s) for s in state["spans"]]
        self._resume_skip = int(state.get("rows", 0))
        self._pass_rows = 0
        self._pass_active = False

    def _block_iter(self, prefetch_blocks: int) -> Iterator:
        """Fetch blocks with a prefetch thread (depth = prefetch_blocks+1)."""
        if self._factory is not None:
            refs = self._factory()
            spans = None
        else:
            # The resume overlay (cleared by _end_pass when the in-flight
            # epoch completes) wins over the steady-state base spans.
            if self._resume_spans is not None:
                spans = self._resume_spans
                skip = self._resume_skip
            else:
                spans = self._base_spans
                skip = 0
            refs = None
        q: queue.Queue = queue.Queue(maxsize=max(1, prefetch_blocks + 1))
        _DONE = object()

        def producer():
            try:
                if spans is None:
                    for ref in refs:
                        q.put(ray_tpu.get(ref))
                else:
                    remaining_skip = skip
                    for block_idx, start, stop in spans:
                        table = _span_slice(
                            ray_tpu.get(self._block_refs[block_idx]),
                            start, stop,
                        )
                        if remaining_skip:
                            if table.num_rows <= remaining_skip:
                                remaining_skip -= table.num_rows
                                continue
                            table = table.slice(remaining_skip)
                            remaining_skip = 0
                        if table.num_rows:
                            q.put(table)
            except BaseException as exc:
                q.put(exc)
                return
            q.put(_DONE)

        thread = threading.Thread(target=producer, daemon=True)
        thread.start()
        import time as _time

        while True:
            t0 = _time.perf_counter()
            item = q.get()
            # Time truly blocked on producers (vs local batching/format).
            self._fetch_wait_s += _time.perf_counter() - t0
            if item is _DONE:
                return
            if isinstance(item, BaseException):
                raise item
            yield item

    def iter_batches(self, **kwargs) -> Iterator[Any]:
        """Yields formatted batches; when the owning Dataset tracks stats,
        records wait-on-producer vs in-user-code time (the "is my input
        pipeline the bottleneck" split of ds.stats())."""
        inner = self._iter_batches_impl(**kwargs)
        if self._stats is None:
            yield from inner
            return
        import time as _time

        produce_s = user_s = 0.0
        batches = 0
        last_yield_end = None
        self._fetch_wait_s = 0.0
        try:
            while True:
                resume = _time.perf_counter()
                if last_yield_end is not None:
                    user_s += resume - last_yield_end
                try:
                    batch = next(inner)
                except StopIteration:
                    break
                produce_s += _time.perf_counter() - resume
                batches += 1
                yield batch
                last_yield_end = _time.perf_counter()
        finally:
            # Split production time into blocked-on-producers (block fetch
            # wait, measured in _block_iter) vs local batching/formatting.
            wait_s = min(self._fetch_wait_s, produce_s)
            self._stats.record_iter(
                wait_s, user_s, batches, local_s=produce_s - wait_s
            )

    def _begin_pass(self) -> None:
        self._pass_active = True
        # Skipped rows count as already delivered this epoch so that a
        # state taken mid-resume records the absolute epoch position.
        self._pass_rows = (
            self._resume_skip if self._resume_spans is not None else 0
        )

    def _end_pass(self) -> None:
        """A pass ran to exhaustion: advance the epoch and drop any resume
        overlay — the next pass re-reads this shard's full base spans."""
        self._pass_active = False
        self._epoch += 1
        self._resume_spans = None
        self._resume_skip = 0
        self._pass_rows = 0

    def _iter_batches_impl(
        self,
        *,
        batch_size: Optional[int] = 256,
        batch_format: str = "numpy",
        prefetch_blocks: int = 2,
        drop_last: bool = False,
        local_shuffle_buffer_size: Optional[int] = None,
        local_shuffle_seed: Optional[int] = None,
    ) -> Iterator[Any]:
        import numpy as np

        self._begin_pass()
        carry = None
        shuffle_rng = (
            np.random.default_rng(local_shuffle_seed)
            if local_shuffle_buffer_size
            else None
        )
        buffer = []
        buffered_rows = 0

        def emit(table):
            nonlocal carry
            for batch in batch_blocks(table, batch_size):
                if batch_size and batch.num_rows < batch_size:
                    carry = batch
                    return
                formatted = format_batch(batch, batch_format)
                self._pass_rows += batch.num_rows
                yield formatted

        for block in self._block_iter(prefetch_blocks):
            table = BlockAccessor.for_block(block).block
            if carry is not None:
                table = BlockAccessor.concat([carry, table])
                carry = None
            if shuffle_rng is not None:
                buffer.append(table)
                buffered_rows += table.num_rows
                if buffered_rows < local_shuffle_buffer_size:
                    continue
                merged = BlockAccessor.concat(buffer)
                buffer, buffered_rows = [], 0
                import pyarrow as pa

                table = merged.take(
                    pa.array(shuffle_rng.permutation(merged.num_rows))
                )
            yield from emit(table)
        if buffer:
            merged = BlockAccessor.concat(buffer)
            import pyarrow as pa

            table = merged.take(pa.array(shuffle_rng.permutation(merged.num_rows)))
            if carry is not None:
                table = BlockAccessor.concat([carry, table])
                carry = None
            yield from emit(table)
        if carry is not None and (not drop_last or batch_size is None):
            formatted = format_batch(carry, batch_format)
            self._pass_rows += carry.num_rows
            yield formatted
        self._end_pass()

    def iter_rows(self) -> Iterator[dict]:
        for batch in self.iter_batches(batch_size=None, batch_format="pyarrow"):
            yield from batch.to_pylist()

    def iter_torch_batches(
        self, *, batch_size: Optional[int] = 256, dtypes=None, **kwargs
    ) -> Iterator[dict]:
        import torch

        for batch in self.iter_batches(
            batch_size=batch_size, batch_format="numpy", **kwargs
        ):
            out = {}
            for key, value in batch.items():
                tensor = torch.as_tensor(value)
                if dtypes is not None:
                    want = dtypes.get(key) if isinstance(dtypes, dict) else dtypes
                    if want is not None:
                        tensor = tensor.to(want)
                out[key] = tensor
            yield out

    def materialize_refs(self) -> list:
        if self._factory is not None:
            return list(self._factory())
        # Span mode: materialize each span as its own (sliced) block ref.
        out = []
        for block_idx, start, stop in self._base_spans:
            ref = self._block_refs[block_idx]
            if start == 0 and stop is None:
                out.append(ref)
            else:
                out.append(ray_tpu.put(_span_slice(ray_tpu.get(ref), start, stop)))
        return out


@ray_tpu.remote
class _SplitCoordinator:
    """Round-robin block assignment to n consumers (locality-blind twin of
    the reference's streaming_split OutputSplitter; equalize=True keeps
    per-consumer row counts within one block)."""

    def __init__(self, block_refs: list, n: int):
        self._queues: list[list] = [[] for _ in range(n)]
        for i, ref in enumerate(block_refs):
            self._queues[i % n].append(ref)

    def get_blocks(self, rank: int) -> list:
        return self._queues[rank]


def _block_num_rows(block_refs: list, needed: set) -> dict[int, int]:
    """Row counts for the given block indices (one remote round trip)."""
    from ray_tpu.data._internal.streaming_executor import _num_rows

    idxs = sorted(needed)
    counts = ray_tpu.get([_num_rows.remote(block_refs[i]) for i in idxs])
    return dict(zip(idxs, counts))


def _remaining_spans(state: dict, nrows: dict[int, int]) -> list:
    """Subtract a rank's consumed-row count from its spans, returning the
    fragments it had not yet delivered."""
    rows = int(state.get("rows", 0))
    out = []
    for block_idx, start, stop in state["spans"]:
        end = nrows[block_idx] if stop is None else min(stop, nrows[block_idx])
        span_len = max(0, end - start)
        if rows >= span_len:
            rows -= span_len
            continue
        out.append([block_idx, start + rows, end])
        rows = 0
    return out


def streaming_split(
    block_refs: list, n: int, *, resume_from: dict | None = None
) -> list[DataIterator]:
    """n independent DataIterators over a disjoint partition of blocks.

    ``resume_from`` = ``{"world_size": W, "per_rank": [state, ...]}`` (the
    per-rank ``state_dict()`` snapshots stamped into a committed
    checkpoint) resumes mid-epoch at *any* new world size n: every rank's
    un-consumed span fragments are pooled, re-partitioned across the n new
    ranks for the in-flight epoch, and subsequent epochs use the fresh
    n-way split. Rows a rank consumed after the snapshot are re-delivered
    (duplication bounded to the last uncommitted round); nothing is
    dropped.
    """
    block_refs = list(block_refs)
    iterators = []
    base = [
        [[i, 0, None] for i in range(rank, len(block_refs), n)]
        for rank in range(n)
    ]
    resume_per_rank: list | None = None
    epoch0 = 0
    if resume_from and resume_from.get("per_rank"):
        states = [
            s for s in resume_from["per_rank"]
            if s and s.get("spans") is not None
        ]
        if states:
            epoch0 = min(int(s.get("epoch", 0)) for s in states)
            needed = {
                span[0] for s in states for span in s["spans"]
            }
            nrows = _block_num_rows(block_refs, needed) if needed else {}
            fragments: list = []
            for s in states:
                fragments.extend(_remaining_spans(s, nrows))
            fragments.sort(key=lambda f: (f[0], f[1]))
            resume_per_rank = [fragments[rank::n] for rank in range(n)]
    for rank in range(n):
        it = DataIterator(
            owner_name=f"split[{rank}]",
            block_refs=block_refs,
            spans=base[rank],
        )
        if resume_per_rank is not None:
            it._epoch = epoch0
            it._resume_spans = resume_per_rank[rank]
            it._resume_skip = 0
        iterators.append(it)
    return iterators

"""Read API — parallel datasource reads.

Role-equivalent of python/ray/data/read_api.py :: read_parquet/read_csv/
read_json/read_images/range/from_items/... (SURVEY §2.7). Each read_*
builds a Read logical op whose read tasks run as ray_tpu tasks; file lists
are split across `parallelism` tasks (metadata-pruned parallel reads).
"""

from __future__ import annotations

import glob as globmod
import os
from typing import Any, Iterable, Optional

from ray_tpu.data.block import BlockAccessor, DataContext
from ray_tpu.data.dataset import Dataset
from ray_tpu.data._internal.plan import InputData, LogicalPlan, Read


def _resolve_paths(paths) -> list[str]:
    if isinstance(paths, str):
        paths = [paths]
    files: list[str] = []
    for path in paths:
        if os.path.isdir(path):
            for root, _, names in os.walk(path):
                files += [
                    os.path.join(root, n) for n in names if not n.startswith(".")
                ]
        elif any(ch in path for ch in "*?["):
            files += globmod.glob(path)
        else:
            files.append(path)
    return sorted(files)


def _split_files(files: list[str], parallelism: int) -> list[list[str]]:
    import builtins

    parallelism = min(parallelism, len(files)) or 1
    return [files[i::parallelism] for i in builtins.range(parallelism)]


def _file_dataset(paths, parallelism: int, reader, name: str) -> Dataset:
    files = _resolve_paths(paths)
    if not files:
        raise FileNotFoundError(f"no files matched {paths!r}")
    if parallelism <= 0:
        parallelism = min(
            DataContext.get_current().read_op_min_num_blocks, len(files)
        )
    tasks = []
    for chunk in _split_files(files, parallelism):
        def task(chunk=chunk, reader=reader):
            for path in chunk:
                yield reader(path)

        tasks.append(task)
    return Dataset(LogicalPlan([Read(read_tasks=tasks, name=name)]))


def read_parquet(paths, *, parallelism: int = -1, columns=None) -> Dataset:
    def reader(path):
        import pyarrow.parquet as pq

        return pq.read_table(path, columns=columns)

    return _file_dataset(paths, parallelism, reader, "ReadParquet")


def read_csv(paths, *, parallelism: int = -1) -> Dataset:
    def reader(path):
        import pyarrow.csv as pacsv

        return pacsv.read_csv(path)

    return _file_dataset(paths, parallelism, reader, "ReadCSV")


def read_json(paths, *, parallelism: int = -1, lines: bool = True) -> Dataset:
    def reader(path):
        import pandas as pd
        import pyarrow as pa

        df = pd.read_json(path, lines=lines)
        return pa.Table.from_pandas(df, preserve_index=False)

    return _file_dataset(paths, parallelism, reader, "ReadJSON")


def read_numpy(paths, *, parallelism: int = -1, column: str = "data") -> Dataset:
    def reader(path):
        import numpy as np

        return BlockAccessor.for_block({column: np.load(path)}).block

    return _file_dataset(paths, parallelism, reader, "ReadNumpy")


def read_images(
    paths, *, parallelism: int = -1, size: Optional[tuple] = None, mode: str = "RGB"
) -> Dataset:
    def reader(path):
        import numpy as np
        from PIL import Image

        img = Image.open(path).convert(mode)
        if size is not None:
            img = img.resize(size)
        arr = np.asarray(img)[None]  # [1, H, W, C]
        return BlockAccessor.for_block(
            {"image": arr, "path": np.array([path], dtype=object)}
        ).block

    return _file_dataset(paths, parallelism, reader, "ReadImages")


def read_text(paths, *, parallelism: int = -1) -> Dataset:
    def reader(path):
        with open(path) as f:
            lines = [line.rstrip("\n") for line in f]
        return BlockAccessor.for_block({"text": lines}).block

    return _file_dataset(paths, parallelism, reader, "ReadText")


def read_tfrecords(paths, *, parallelism: int = -1) -> Dataset:
    """Read TFRecord files of tf.Example protos — no TensorFlow needed:
    both wire formats are decoded by the in-tree codec
    (_internal/tfrecord.py). Reference: read_api.py::read_tfrecords."""
    import pyarrow as pa

    from ray_tpu.data._internal.tfrecord import decode_example, read_records

    def reader(path: str):
        rows = [decode_example(rec) for rec in read_records(path)]
        if not rows:
            return pa.table({})
        # Union of feature names across all records (sparse/optional
        # features are normal in tf.Example data); missing -> null.
        names: list[str] = []
        for row in rows:
            for n in row:
                if n not in names:
                    names.append(n)
        columns = {}
        for n in names:
            values = [r.get(n) for r in rows]
            # A column mixing unwrapped scalars and multi-element lists
            # must be normalized to lists for a consistent Arrow type.
            if any(isinstance(v, list) for v in values):
                values = [
                    v if isinstance(v, list) or v is None else [v]
                    for v in values
                ]
            columns[n] = values
        return pa.table(columns)

    return _file_dataset(paths, parallelism, reader, "ReadTFRecords")


def read_datasource(
    datasource, *, parallelism: int = -1, **_unused
) -> Dataset:
    """Read from a custom Datasource plugin (reference:
    read_api.py::read_datasource + datasource.py protocol)."""
    if parallelism <= 0:
        parallelism = DataContext.get_current().read_op_min_num_blocks
    read_tasks = datasource.get_read_tasks(parallelism)
    if not read_tasks:
        return from_items([])
    return Dataset(
        LogicalPlan(
            [Read(read_tasks=list(read_tasks),
                  name=f"Read{datasource.get_name()}")]
        )
    )


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    import numpy as np

    if parallelism <= 0:
        parallelism = min(DataContext.get_current().read_op_min_num_blocks, max(n, 1))
    import builtins

    tasks = []
    edges = [round(i * n / parallelism) for i in builtins.range(parallelism + 1)]
    for i in builtins.range(parallelism):
        lo, hi = edges[i], edges[i + 1]

        def task(lo=lo, hi=hi):
            yield BlockAccessor.for_block({"id": np.arange(lo, hi)}).block

        tasks.append(task)
    return Dataset(LogicalPlan([Read(read_tasks=tasks, name="ReadRange")]))


def range_tensor(n: int, *, shape: tuple = (1,), parallelism: int = -1) -> Dataset:
    import numpy as np

    def to_tensor(batch):
        ids = batch["id"]
        data = np.broadcast_to(
            ids.reshape((-1,) + (1,) * len(shape)), (len(ids),) + shape
        ).copy()
        return {"data": data}

    return range(n, parallelism=parallelism).map_batches(to_tensor)


def from_items(items: list, *, parallelism: int = -1) -> Dataset:
    rows = [
        item if isinstance(item, dict) else {"item": item} for item in items
    ]
    if parallelism <= 0:
        parallelism = min(DataContext.get_current().read_op_min_num_blocks, max(len(rows), 1))
    import builtins

    chunks = [rows[i::parallelism] for i in builtins.range(parallelism)]
    blocks = [
        BlockAccessor.for_block(chunk).block for chunk in chunks if chunk
    ]
    return Dataset(LogicalPlan([InputData(blocks=blocks)]))


def from_numpy(array, *, column: str = "data") -> Dataset:
    return Dataset(
        LogicalPlan([InputData(blocks=[BlockAccessor.for_block({column: array}).block])])
    )


def from_arrow(table) -> Dataset:
    return Dataset(LogicalPlan([InputData(blocks=[table])]))


def from_pandas(df) -> Dataset:
    return Dataset(
        LogicalPlan([InputData(blocks=[BlockAccessor.for_block(df).block])])
    )


def from_torch(torch_dataset) -> Dataset:
    rows = []
    for item in torch_dataset:
        rows.append({"item": item})
    return from_items(rows)


def from_huggingface(hf_dataset) -> Dataset:
    return from_arrow(hf_dataset.data.table)

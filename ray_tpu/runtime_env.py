"""Public runtime-env spec type.

Role-equivalent of the reference's
python/ray/runtime_env/runtime_env.py :: RuntimeEnv — a validated dict
describing the environment a job/task/actor runs under. Materialization
happens per node in the agent's RuntimeEnvManager
(ray_tpu/_private/runtime_env.py).

Supported fields:

- ``env_vars``: dict of environment variables for the worker process.
- ``working_dir``: directory the worker starts in; a ``.zip`` path is
  extracted into the per-node cache, a plain directory is used in place.
- ``pip``: list of pip requirements (or a local package path); installed
  into an isolated, cached, per-env ``--target`` directory prepended to
  the worker's ``PYTHONPATH``.
- ``py_modules``: list of local module directories / zips staged into the
  cache and put on ``PYTHONPATH``.
- ``config``: reserved for per-env options (timeouts), passed through.
"""

from __future__ import annotations

from ray_tpu._private.runtime_env import validate_runtime_env


class RuntimeEnv(dict):
    """Validated runtime environment spec (a plain dict underneath)."""

    def __init__(
        self,
        *,
        env_vars: dict | None = None,
        working_dir: str | None = None,
        pip: list | str | dict | None = None,
        py_modules: list | None = None,
        config: dict | None = None,
    ):
        spec: dict = {}
        if env_vars is not None:
            spec["env_vars"] = dict(env_vars)
        if working_dir is not None:
            spec["working_dir"] = str(working_dir)
        if pip is not None:
            spec["pip"] = pip
        if py_modules is not None:
            spec["py_modules"] = list(py_modules)
        if config is not None:
            spec["config"] = dict(config)
        super().__init__(validate_runtime_env(spec))

"""EnvRunnerGroup — manages the fleet of rollout actors.

Role-equivalent of rllib/env/env_runner_group.py :: EnvRunnerGroup
(SURVEY §2.8): spawns N SingleAgentEnvRunner actors, fans out sample()
(sync for PPO, async queue-style for IMPALA via sample_async/collect),
broadcasts weights, aggregates runner metrics.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner
from ray_tpu.rllib.policy.sample_batch import MultiAgentBatch, SampleBatch


class EnvRunnerGroup:
    def __init__(
        self,
        env_creator: Any,
        module_spec,
        *,
        num_env_runners: int = 2,
        num_envs_per_runner: int = 1,
        rollout_fragment_length: int = 200,
        seed: Optional[int] = None,
        env_to_module: Any = None,
        module_to_env: Any = None,
        runner_class: Any = None,
        runner_kwargs: dict | None = None,
    ):
        runner_cls = ray_tpu.remote(runner_class or SingleAgentEnvRunner)
        self.num_env_runners = max(1, num_env_runners)
        self.runners = [
            runner_cls.options(num_cpus=1).remote(
                env_creator,
                module_spec,
                num_envs=num_envs_per_runner,
                rollout_fragment_length=rollout_fragment_length,
                worker_index=i,
                seed=seed,
                env_to_module=env_to_module,
                module_to_env=module_to_env,
                **(runner_kwargs or {}),
            )
            for i in range(self.num_env_runners)
        ]
        ray_tpu.get([r.ping.remote() for r in self.runners], timeout=180)
        self._inflight: dict = {}

    def sync_weights(self, params) -> None:
        ref = ray_tpu.put(params)
        ray_tpu.get(
            [r.set_weights.remote(ref) for r in self.runners], timeout=120
        )

    def sample(self) -> SampleBatch:
        """Synchronous fan-out (PPO path)."""
        batches = ray_tpu.get(
            [r.sample.remote() for r in self.runners], timeout=600
        )
        if batches and isinstance(batches[0], MultiAgentBatch):
            return MultiAgentBatch.concat_samples(batches)
        return SampleBatch.concat_samples(batches)

    # -- async pipeline (IMPALA path) -----------------------------------
    def sample_async(self) -> None:
        for i, runner in enumerate(self.runners):
            if i not in self._inflight:
                self._inflight[i] = runner.sample.remote()

    def collect_ready(self, timeout: float = 0.05) -> list[SampleBatch]:
        """Harvest finished rollouts; immediately resubmit those runners."""
        if not self._inflight:
            self.sample_async()
        ref_to_idx = {ref: i for i, ref in self._inflight.items()}
        ready, _ = ray_tpu.wait(
            list(ref_to_idx), num_returns=len(ref_to_idx), timeout=timeout
        )
        out = []
        for ref in ready:
            idx = ref_to_idx[ref]
            try:
                out.append(ray_tpu.get(ref))
            finally:
                self._inflight[idx] = self.runners[idx].sample.remote()
        return out

    def get_connector_state(self) -> dict:
        """Running env→module connector state from runner 0 (the
        reference syncs connector state the same one-of-many way)."""
        try:
            return ray_tpu.get(
                self.runners[0].get_connector_state.remote(), timeout=60
            )
        except Exception as exc:
            import logging

            logging.getLogger(__name__).warning(
                "connector-state fetch from runner 0 failed (%s); "
                "evaluation will run with FRESH normalizer statistics",
                exc,
            )
            return {}

    def get_metrics(self) -> dict:
        metrics = ray_tpu.get(
            [r.get_metrics.remote() for r in self.runners], timeout=120
        )
        returns = [
            m["episode_return_mean"]
            for m in metrics
            if not np.isnan(m.get("episode_return_mean", np.nan))
        ]
        lens = [
            m["episode_len_mean"]
            for m in metrics
            if not np.isnan(m.get("episode_len_mean", np.nan))
        ]
        return {
            "episode_return_mean": float(np.mean(returns)) if returns else np.nan,
            "episode_len_mean": float(np.mean(lens)) if lens else np.nan,
            "num_episodes": int(sum(m["num_episodes"] for m in metrics)),
        }

    def stop(self) -> None:
        for runner in self.runners:
            try:
                ray_tpu.kill(runner)
            except Exception:  # rtlint: disable=swallowed-exception - runner already dead
                pass

"""MultiAgentEnvRunner — rollout actor for MultiAgentEnv.

Role-equivalent of rllib/env/multi_agent_env_runner.py ::
MultiAgentEnvRunner (SURVEY §2.8 multi-agent row): steps one
MultiAgentEnv, routes each agent's observation through
``policy_mapping_fn`` to its module, batches per-module forward passes,
and returns a MultiAgentBatch of per-module SampleBatches. Episode
metrics follow the reference convention: an episode's return is the sum
of ALL agents' rewards.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import numpy as np

from ray_tpu.rllib.policy.sample_batch import (
    ACTION_LOGP, ACTIONS, AGENT_ID, EPS_ID, MultiAgentBatch, NEXT_OBS, OBS,
    REWARDS, SampleBatch, TERMINATEDS, TRUNCATEDS, VF_PREDS,
)


class MultiAgentEnvRunner:
    def __init__(
        self,
        env_creator: Callable[[], Any],
        module_spec,  # MultiRLModuleSpec
        *,
        policy_mapping_fn: Callable[[str], str],
        num_envs: int = 1,
        rollout_fragment_length: int = 200,
        worker_index: int = 0,
        explore: bool = True,
        seed: Optional[int] = None,
        env_to_module: Callable[[], Any] | None = None,
        module_to_env: Callable[[], Any] | None = None,
    ):
        from ray_tpu.rllib.connectors import (
            default_env_to_module, default_module_to_env,
        )

        self.env = env_creator()
        self.rollout_fragment_length = rollout_fragment_length
        self.explore = explore
        self.policy_mapping_fn = policy_mapping_fn
        self.worker_index = worker_index

        # Module spaces: the spaces of the first agent mapping to each id.
        obs_spaces: dict[str, Any] = {}
        act_spaces: dict[str, Any] = {}
        for agent in self.env.possible_agents:
            mid = policy_mapping_fn(agent)
            obs_spaces.setdefault(mid, self.env.get_observation_space(agent))
            act_spaces.setdefault(mid, self.env.get_action_space(agent))
        self.module = module_spec.build(obs_spaces, act_spaces)
        for mid, module in self.module.items():
            if getattr(module, "is_stateful", False):
                raise ValueError(
                    "MultiAgentEnvRunner does not support stateful "
                    f"(use_lstm) modules yet; module {mid!r} is recurrent"
                )
        self._act_spaces = act_spaces
        self._params: Optional[dict] = None
        self._fwd = {
            mid: jax.jit(module.forward_exploration)
            for mid, module in self.module.items()
        }
        self._fwd_greedy = {
            mid: jax.jit(module.forward_inference)
            for mid, module in self.module.items()
        }
        # One connector pipeline per module. Stateful pipelines are not
        # supported here: the multi-agent path must also transform
        # NEXT_OBS each step (agents join/leave between steps, so the
        # "obs of t+1" trick the single-agent runner uses doesn't apply),
        # which would double-advance per-stream connector state.
        self._env_to_module = {
            mid: (env_to_module() if env_to_module else default_env_to_module())
            for mid in self.module.keys()
        }
        for mid, pipe in self._env_to_module.items():
            if getattr(pipe, "stateful", False):
                raise ValueError(
                    "MultiAgentEnvRunner does not support stateful "
                    "env_to_module connectors (framestack/normalizers); "
                    f"module {mid!r} got one"
                )
        self._module_to_env = {
            mid: (module_to_env() if module_to_env else default_module_to_env())
            for mid in self.module.keys()
        }
        self._rng = jax.random.PRNGKey(
            seed if seed is not None else worker_index * 1000 + 29
        )
        self._seed = seed
        self._obs, _ = self.env.reset(
            seed=None if seed is None else seed + worker_index
        )
        # per-agent episode ids (advance on every env-episode reset)
        base = worker_index * 10_000_000
        self._eps_ids = {
            agent: base + i for i, agent in enumerate(self.env.possible_agents)
        }
        self._next_eps = base + len(self.env.possible_agents)
        self._episode_return = 0.0
        self._episode_len = 0
        self._completed: list[tuple[float, int]] = []

    def get_connector_state(self) -> dict:
        # Stateful env→module connectors are rejected in __init__, so
        # there is never running state to sync.
        return {}

    # -- weights ---------------------------------------------------------
    def set_weights(self, params: dict) -> str:
        self._params = jax.device_put(params)
        return "ok"

    def get_weights(self):
        return self._params

    # -- rollout ---------------------------------------------------------
    def sample(self, num_steps: int | None = None) -> MultiAgentBatch:
        assert self._params is not None, "set_weights before sample"
        steps = num_steps or self.rollout_fragment_length
        cols: dict[str, dict[str, list]] = {
            mid: {
                OBS: [], ACTIONS: [], REWARDS: [], TERMINATEDS: [],
                TRUNCATEDS: [], NEXT_OBS: [], ACTION_LOGP: [], VF_PREDS: [],
                EPS_ID: [], AGENT_ID: [],
            }
            for mid in self.module.keys()
        }
        actual_steps = 0
        for _ in range(steps):
            active = sorted(self._obs.keys())
            if not active:
                self._reset_episode()
                continue
            actual_steps += 1
            # group agents by module
            by_module: dict[str, list[str]] = {}
            for agent in active:
                by_module.setdefault(self.policy_mapping_fn(agent), []).append(
                    agent
                )
            action_dict: dict[str, Any] = {}
            step_record: dict[str, dict] = {}
            for mid, agents in by_module.items():
                obs_batch = self._env_to_module[mid](
                    np.stack([np.asarray(self._obs[a]) for a in agents])
                )
                self._rng, key = jax.random.split(self._rng)
                if self.explore:
                    actions, logp, extra = self._fwd[mid](
                        self._params[mid], obs_batch, key
                    )
                    vf = np.asarray(extra["vf_preds"])
                else:
                    actions = self._fwd_greedy[mid](self._params[mid], obs_batch)
                    logp = np.zeros(len(agents))
                    vf = np.zeros(len(agents))
                actions_np = np.asarray(actions)
                env_actions = self._module_to_env[mid](
                    actions_np, action_space=self._act_spaces[mid]
                )
                for i, agent in enumerate(agents):
                    action_dict[agent] = env_actions[i]
                    step_record[agent] = {
                        "mid": mid,
                        "obs": obs_batch[i],
                        "action": actions_np[i],
                        "logp": float(np.asarray(logp)[i]),
                        "vf": float(vf[i]),
                    }
            next_obs, rewards, terms, truncs, _ = self.env.step(action_dict)
            done_all = terms.get("__all__", False) or truncs.get(
                "__all__", False
            )
            for agent, rec in step_record.items():
                mid = rec["mid"]
                col = cols[mid]
                col[OBS].append(rec["obs"])
                col[ACTIONS].append(rec["action"])
                col[REWARDS].append(np.float32(rewards.get(agent, 0.0)))
                col[TERMINATEDS].append(bool(terms.get(agent, False)))
                col[TRUNCATEDS].append(bool(truncs.get(agent, False)))
                nxt = next_obs.get(agent)
                if nxt is None:
                    # Agent produced no next obs (already done): repeat its
                    # (transformed) current obs — terminal rows don't
                    # bootstrap, so the value is inert.
                    col[NEXT_OBS].append(rec["obs"])
                else:
                    # Same stateless pipeline as OBS, so both columns live
                    # in the module's input space.
                    col[NEXT_OBS].append(
                        self._env_to_module[mid](np.asarray(nxt)[None])[0]
                    )
                col[ACTION_LOGP].append(np.float32(rec["logp"]))
                col[VF_PREDS].append(np.float32(rec["vf"]))
                col[EPS_ID].append(np.int64(self._eps_ids[agent]))
                col[AGENT_ID].append(agent)
                self._episode_return += rewards.get(agent, 0.0)
            self._episode_len += 1
            # keep only live agents' observations for the next step
            self._obs = {
                a: o
                for a, o in next_obs.items()
                if not (terms.get(a, False) or truncs.get(a, False))
            }
            if done_all:
                self._reset_episode()

        batches = {}
        for mid, col in cols.items():
            if not col[OBS]:
                continue
            agent_ids = col.pop(AGENT_ID)
            data = {k: np.stack(v) for k, v in col.items() if v}
            # When one module serves several agents, rows interleave
            # (agent_0, agent_1, agent_0, ...) with distinct eps_ids.
            # GAE segments on contiguous eps_id runs, so stable-sort by
            # eps_id to make each agent's episode contiguous; the sort is
            # stable, so time order within an episode is preserved.
            order = np.argsort(data[EPS_ID], kind="stable")
            if not np.array_equal(order, np.arange(len(order))):
                data = {k: v[order] for k, v in data.items()}
                agent_ids = [agent_ids[i] for i in order]
            batch = SampleBatch(data)
            batch[AGENT_ID] = np.array(agent_ids)
            batches[mid] = batch
        return MultiAgentBatch(batches, env_steps=actual_steps)

    def _reset_episode(self) -> None:
        self._completed.append((float(self._episode_return), self._episode_len))
        self._episode_return = 0.0
        self._episode_len = 0
        self._obs, _ = self.env.reset()
        for agent in self.env.possible_agents:
            self._eps_ids[agent] = self._next_eps
            self._next_eps += 1

    def sample_episodes(self, num_episodes: int) -> MultiAgentBatch:
        batches = []
        before = len(self._completed)
        while len(self._completed) - before < num_episodes:
            batches.append(self.sample(self.rollout_fragment_length))
        return MultiAgentBatch.concat_samples(batches)

    # -- metrics ---------------------------------------------------------
    def get_metrics(self) -> dict:
        episodes = self._completed[-100:]
        return {
            "num_episodes": len(self._completed),
            "episode_return_mean": (
                float(np.mean([r for r, _ in episodes])) if episodes else np.nan
            ),
            "episode_len_mean": (
                float(np.mean([l for _, l in episodes])) if episodes else np.nan
            ),
        }

    def ping(self) -> str:
        return "ok"

    def stop(self) -> str:
        self.env.close()
        return "ok"

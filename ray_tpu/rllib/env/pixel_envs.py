"""Pixel test/benchmark environments for the vision (conv) stack.

Role-equivalent of the reference's Atari benchmark harness
(rllib/tuned_examples/ppo/atari_ppo.py + the ALE envs it wraps): ALE ROMs
do not exist in this image, so the same two roles are covered by
in-process envs with the exact Atari observation contract
(uint8 [84, 84, 4] frame-stacked images, Discrete(6)):

  * ``raytpu/RandomImage-v0`` — throughput: pre-generated random frames,
    zero game logic, so a benchmark measures the rollout/learner
    machinery and the conv net, not a Python game loop.
  * ``raytpu/MovingDot-v0`` — learning: a bright dot sits in the left or
    right half of the frame; matching action earns +1. A conv policy
    must actually read pixels to beat the 0.5-per-step chance baseline,
    and can reach ~1/step quickly (the --as-test threshold role).

Importing this module registers both ids with gymnasium.
"""

from __future__ import annotations

import gymnasium as gym
import numpy as np


class RandomImageEnv(gym.Env):
    """Atari-shaped observations with no game logic (throughput bench)."""

    metadata: dict = {"render_modes": []}

    def __init__(
        self,
        height: int = 84,
        width: int = 84,
        channels: int = 4,
        num_actions: int = 6,
        episode_len: int = 128,
        frame_bank: int = 32,
    ):
        self.observation_space = gym.spaces.Box(
            0, 255, shape=(height, width, channels), dtype=np.uint8
        )
        self.action_space = gym.spaces.Discrete(num_actions)
        self.episode_len = episode_len
        # Pre-generated frames: per-step obs is an index into this bank,
        # so stepping costs no RNG fill of a 28 KiB array.
        rng = np.random.default_rng(0)
        self._bank = rng.integers(
            0, 256, size=(frame_bank, height, width, channels), dtype=np.uint8
        )
        self._t = 0
        self._i = 0

    def reset(self, *, seed=None, options=None):
        super().reset(seed=seed)
        self._t = 0
        self._i = (self._i + 1) % len(self._bank)
        return self._bank[self._i], {}

    def step(self, action):
        self._t += 1
        self._i = (self._i + 1) % len(self._bank)
        terminated = self._t >= self.episode_len
        return self._bank[self._i], 1.0, terminated, False, {}


class MovingDotEnv(gym.Env):
    """Trivially learnable pixel task: act toward the bright half."""

    metadata: dict = {"render_modes": []}

    def __init__(
        self, size: int = 32, channels: int = 1, episode_len: int = 32
    ):
        self.size = size
        self.episode_len = episode_len
        self.observation_space = gym.spaces.Box(
            0, 255, shape=(size, size, channels), dtype=np.uint8
        )
        self.action_space = gym.spaces.Discrete(2)
        self._t = 0
        self._side = 0

    def _obs(self) -> np.ndarray:
        obs = np.zeros(self.observation_space.shape, dtype=np.uint8)
        half = self.size // 2
        # a filled bright square in the chosen half (easy conv feature)
        r = self.np_random.integers(4, self.size - 8)
        c_base = 4 if self._side == 0 else half + 4
        c = c_base + int(self.np_random.integers(0, half - 12)) if half > 12 \
            else c_base
        obs[r : r + 6, c : c + 6, :] = 255
        return obs

    def reset(self, *, seed=None, options=None):
        super().reset(seed=seed)
        self._t = 0
        self._side = int(self.np_random.integers(0, 2))
        return self._obs(), {}

    def step(self, action):
        reward = 1.0 if int(action) == self._side else 0.0
        self._t += 1
        self._side = int(self.np_random.integers(0, 2))
        terminated = self._t >= self.episode_len
        return self._obs(), reward, terminated, False, {}


def _register() -> None:
    for env_id, entry in (
        ("raytpu/RandomImage-v0", RandomImageEnv),
        ("raytpu/MovingDot-v0", MovingDotEnv),
    ):
        if env_id not in gym.registry:
            gym.register(id=env_id, entry_point=entry, disable_env_checker=True)


_register()

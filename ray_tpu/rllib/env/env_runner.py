"""EnvRunner — CPU rollout actors.

Role-equivalent of rllib/env/single_agent_env_runner.py ::
SingleAgentEnvRunner (SURVEY §2.8, §3.5): gymnasium vector envs stepped in
a hot loop, actions from RLModule.forward_exploration on CPU, fixed-length
rollout fragments returned as SampleBatch (the connector pipeline here is
the obs/action flatten + logp/vf bookkeeping inline). Stays on CPU in the
TPU build — learners own the accelerator.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.policy.sample_batch import (
    ACTION_LOGP, ACTIONS, EPS_ID, NEXT_OBS, OBS, REWARDS, SampleBatch,
    TERMINATEDS, TRUNCATEDS, VF_PREDS,
)


class SingleAgentEnvRunner:
    """One actor per runner; `sample()` returns a rollout fragment."""

    def __init__(
        self,
        env_creator: Callable[[], Any] | str,
        module_spec,
        *,
        num_envs: int = 1,
        rollout_fragment_length: int = 200,
        worker_index: int = 0,
        explore: bool = True,
        seed: Optional[int] = None,
        env_to_module: Callable[[], Any] | None = None,
        module_to_env: Callable[[], Any] | None = None,
    ):
        import gymnasium as gym

        from ray_tpu.rllib.connectors import (
            default_env_to_module, default_module_to_env,
        )

        if isinstance(env_creator, str):
            env_id = env_creator
            self.env = gym.make_vec(env_id, num_envs=num_envs)
        else:
            self.env = env_creator(num_envs)
        self.num_envs = num_envs
        self.rollout_fragment_length = rollout_fragment_length
        self.explore = explore
        # Connector pipelines (ConnectorV2 role, SURVEY §2.8): factories so
        # each runner actor owns its (possibly stateful) pipeline instance.
        self._env_to_module = (
            env_to_module() if env_to_module else default_env_to_module()
        )
        self._module_to_env = (
            module_to_env() if module_to_env else default_module_to_env()
        )
        seed_val = None if seed is None else seed + worker_index
        raw_obs, _ = self.env.reset(seed=seed_val)
        self._obs = self._env_to_module(raw_obs)
        # The module sees the CONNECTOR's output, not the env's raw space —
        # a shape-changing pipeline (framestack, …) implies a wider input.
        obs_space = self.env.single_observation_space
        if tuple(self._obs.shape[1:]) != tuple(obs_space.shape or ()):
            obs_space = gym.spaces.Box(
                -np.inf, np.inf, shape=self._obs.shape[1:], dtype=np.float32
            )
        self.module = module_spec.build(
            obs_space, self.env.single_action_space
        )
        self._params = None
        self._rng = jax.random.PRNGKey(
            seed if seed is not None else worker_index * 1000 + 17
        )
        self._fwd = jax.jit(self.module.forward_exploration)
        self._fwd_greedy = jax.jit(self.module.forward_inference)
        # Recurrent modules (use_lstm): the runner owns one (h, c) per
        # env, threads it through forward_* and zeroes finished envs'
        # rows at episode boundaries.
        self._stateful = bool(getattr(self.module, "is_stateful", False))
        self._state = (
            self.module.initial_state(num_envs) if self._stateful else None
        )
        # Epsilon-greedy override (DQN-style): when set, actions are greedy
        # w.r.t. the module with prob 1-ε and uniform-random with prob ε —
        # applied BEFORE stepping the env so replay data stays consistent.
        self._epsilon: Optional[float] = None
        self._np_rng = np.random.default_rng(
            (seed if seed is not None else 0) * 7919 + worker_index
        )
        self._eps_ids = np.arange(num_envs, dtype=np.int64) + worker_index * 10_000_000
        self._next_eps = self._eps_ids.max() + 1
        self._episode_returns = np.zeros(num_envs)
        self._episode_lens = np.zeros(num_envs, dtype=np.int64)
        self._completed: list[tuple[float, int]] = []

    # -- weights sync ----------------------------------------------------
    def set_weights(self, params) -> str:
        self._params = jax.device_put(params)
        return "ok"

    def set_epsilon(self, epsilon: Optional[float]) -> str:
        self._epsilon = epsilon
        return "ok"

    def get_weights(self):
        return self._params

    def get_connector_state(self) -> dict:
        """Cross-episode env→module state (running normalizers) so
        evaluation pipelines can start from the training distribution."""
        return self._env_to_module.get_state()

    # -- rollout ---------------------------------------------------------
    def sample(self, num_steps: int | None = None) -> SampleBatch:
        assert self._params is not None, "set_weights before sample"
        steps = num_steps or self.rollout_fragment_length
        if self._stateful:
            # Truncated BPTT aligned with fragments: zero the recurrent
            # state at every fragment start so the TRAINING scan (which
            # zero-inits its windows) replays the exact state trajectory
            # the rollout used — otherwise importance ratios are computed
            # against logps from different hidden states and PPO's clipped
            # updates drift (observed: returns plateau then decline).
            # Set model_config max_seq_len == rollout_fragment_length for
            # exact window alignment.
            self._state = self.module.initial_state(self.num_envs)
        cols: dict[str, list] = {
            OBS: [], ACTIONS: [], REWARDS: [], TERMINATEDS: [],
            TRUNCATEDS: [], NEXT_OBS: [], ACTION_LOGP: [], VF_PREDS: [],
            EPS_ID: [],
        }
        for _ in range(steps):
            self._rng, key = jax.random.split(self._rng)
            if self._epsilon is not None:
                if self._stateful:
                    actions, self._state = self._fwd_greedy(
                        self._params, self._obs, self._state
                    )
                    actions = np.asarray(actions)
                else:
                    actions = np.asarray(
                        self._fwd_greedy(self._params, self._obs)
                    )
                mask = self._np_rng.random(self.num_envs) < self._epsilon
                if mask.any():
                    actions = np.where(
                        mask,
                        self._np_rng.integers(
                            0, self.env.single_action_space.n, self.num_envs
                        ),
                        actions,
                    )
                logp = np.zeros(self.num_envs)
                vf = np.zeros(self.num_envs)
            elif self.explore:
                if self._stateful:
                    actions, logp, extra, self._state = self._fwd(
                        self._params, self._obs, key, self._state
                    )
                else:
                    actions, logp, extra = self._fwd(
                        self._params, self._obs, key
                    )
                vf = extra["vf_preds"]
            else:
                if self._stateful:
                    actions, self._state = self._fwd_greedy(
                        self._params, self._obs, self._state
                    )
                else:
                    actions = self._fwd_greedy(self._params, self._obs)
                logp = np.zeros(self.num_envs)
                vf = np.zeros(self.num_envs)
            actions_np = np.asarray(actions)
            env_actions = self._module_to_env(
                actions_np, action_space=self.env.single_action_space
            )
            raw_next, rewards, terms, truncs, _ = self.env.step(env_actions)
            # Transform once per step: NEXT_OBS of step t is OBS of t+1,
            # so stateful connectors (framestack, normalizers) see each
            # observation exactly once. ``dones`` lets per-stream state
            # (framestacks) reset at episode boundaries.
            next_obs = self._env_to_module(
                raw_next, dones=np.logical_or(terms, truncs)
            )
            cols[OBS].append(self._obs)
            cols[ACTIONS].append(actions_np)
            cols[REWARDS].append(np.asarray(rewards, dtype=np.float32))
            cols[TERMINATEDS].append(terms)
            cols[TRUNCATEDS].append(truncs)
            cols[NEXT_OBS].append(next_obs)
            cols[ACTION_LOGP].append(np.asarray(logp))
            cols[VF_PREDS].append(np.asarray(vf))
            cols[EPS_ID].append(self._eps_ids.copy())

            self._episode_returns += rewards
            self._episode_lens += 1
            done = np.logical_or(terms, truncs)
            if self._stateful and done.any():
                # reset finished envs' recurrent state rows
                keep = jnp.asarray(1.0 - done.astype(np.float32))[:, None]
                self._state = jax.tree_util.tree_map(
                    lambda s: s * keep, self._state
                )
            for i in np.nonzero(done)[0]:
                self._completed.append(
                    (float(self._episode_returns[i]), int(self._episode_lens[i]))
                )
                self._episode_returns[i] = 0.0
                self._episode_lens[i] = 0
                self._eps_ids[i] = self._next_eps
                self._next_eps += 1
            self._obs = next_obs

        # [T, B, ...] → flatten env-major so each env's steps stay contiguous
        # (episode boundaries remain detectable via EPS_ID).
        def flat(stacked: list) -> np.ndarray:
            arr = np.stack(stacked)  # [T, B, ...]
            return np.swapaxes(arr, 0, 1).reshape(
                (arr.shape[0] * arr.shape[1],) + arr.shape[2:]
            )

        return SampleBatch({k: flat(v) for k, v in cols.items()})

    def sample_episodes(self, num_episodes: int) -> SampleBatch:
        batches = []
        completed_before = len(self._completed)
        while len(self._completed) - completed_before < num_episodes:
            batches.append(self.sample(self.rollout_fragment_length))
        return SampleBatch.concat_samples(batches)

    # -- metrics ---------------------------------------------------------
    def get_metrics(self) -> dict:
        episodes = self._completed[-100:]
        out = {
            "num_episodes": len(self._completed),
            "episode_return_mean": (
                float(np.mean([r for r, _ in episodes])) if episodes else np.nan
            ),
            "episode_len_mean": (
                float(np.mean([l for _, l in episodes])) if episodes else np.nan
            ),
        }
        return out

    def ping(self) -> str:
        return "ok"

    def stop(self) -> str:
        self.env.close()
        return "ok"

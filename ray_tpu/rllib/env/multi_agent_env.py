"""MultiAgentEnv — the dict-keyed multi-agent environment protocol.

Role-equivalent of rllib/env/multi_agent_env.py :: MultiAgentEnv and the
MultiAgentCartPole test env (rllib/examples/envs/classes): observations,
rewards, terminateds and truncateds are dicts keyed by agent id; the
``terminateds``/``truncateds`` dicts carry the special ``"__all__"`` key
ending the episode for everyone. Agents may have different spaces; the
runner groups them by module via ``policy_mapping_fn``.
"""

from __future__ import annotations

from typing import Any

import gymnasium as gym


class MultiAgentEnv:
    """Subclass surface: ``possible_agents``, per-agent spaces, reset/step."""

    # All agent ids that can ever appear.
    possible_agents: list = []
    # Either dicts keyed by agent id, or single spaces shared by all.
    observation_spaces: Any = None
    action_spaces: Any = None

    def get_observation_space(self, agent_id) -> gym.Space:
        if isinstance(self.observation_spaces, dict):
            return self.observation_spaces[agent_id]
        return self.observation_spaces

    def get_action_space(self, agent_id) -> gym.Space:
        if isinstance(self.action_spaces, dict):
            return self.action_spaces[agent_id]
        return self.action_spaces

    def reset(self, *, seed: int | None = None, options: dict | None = None):
        """→ (obs_dict, info_dict)"""
        raise NotImplementedError

    def step(self, action_dict: dict):
        """→ (obs, rewards, terminateds, truncateds, infos) dicts; the
        terminateds/truncateds dicts include "__all__"."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class MultiAgentCartPole(MultiAgentEnv):
    """N independent CartPole-v1 copies, one per agent — the canonical
    multi-agent smoke-test env. Agents terminate independently; the
    episode ends when every agent is done."""

    def __init__(self, config: dict | None = None):
        config = config or {}
        self.num_agents = int(config.get("num_agents", 2))
        self.possible_agents = [f"agent_{i}" for i in range(self.num_agents)]
        self._envs = {
            agent: gym.make("CartPole-v1") for agent in self.possible_agents
        }
        first = self._envs[self.possible_agents[0]]
        self.observation_spaces = {
            a: self._envs[a].observation_space for a in self.possible_agents
        }
        self.action_spaces = {
            a: self._envs[a].action_space for a in self.possible_agents
        }
        del first
        self._done: dict[str, bool] = {}

    def reset(self, *, seed=None, options=None):
        obs, infos = {}, {}
        for i, (agent, env) in enumerate(self._envs.items()):
            agent_seed = None if seed is None else seed + i
            obs[agent], infos[agent] = env.reset(seed=agent_seed)
            self._done[agent] = False
        return obs, infos

    def step(self, action_dict: dict):
        obs, rewards, terms, truncs, infos = {}, {}, {}, {}, {}
        for agent, action in action_dict.items():
            if self._done.get(agent, True):
                continue
            o, r, te, tr, info = self._envs[agent].step(action)
            obs[agent] = o
            rewards[agent] = float(r)
            terms[agent] = bool(te)
            truncs[agent] = bool(tr)
            infos[agent] = info
            if te or tr:
                self._done[agent] = True
        terms["__all__"] = all(self._done.values())
        truncs["__all__"] = False
        return obs, rewards, terms, truncs, infos

    def close(self) -> None:
        for env in self._envs.values():
            env.close()

"""BC — behavior cloning (offline RL).

Role-equivalent of rllib/algorithms/bc/ (SURVEY §2.8 offline-RL row):
supervised imitation of a dataset policy — maximize log-likelihood of the
dataset's actions under the module's action distribution; no environment
interaction during training (the env is only probed for spaces and used
by evaluate()). The jitted-learner discipline is identical to PPO's.
"""

from __future__ import annotations

import gymnasium as gym
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner, LearnerGroup
from ray_tpu.rllib.core.rl_module import RLModuleSpec
from ray_tpu.rllib.offline.offline_data import OfflineData
from ray_tpu.rllib.policy.sample_batch import ACTIONS, OBS, SampleBatch


class BCConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or BC)
        self.lr = 1e-3
        self.train_batch_size = 256
        self.updates_per_iteration: int = 100
        # dataset / path / SampleBatch — see OfflineData
        self.input_: object = None
        self.num_env_runners = 0

    def offline_data(self, *, input_=None):
        if input_ is not None:
            self.input_ = input_
        return self

    def validate(self) -> None:
        super().validate()
        if self.input_ is None:
            raise ValueError("BC needs config.offline_data(input_=...)")


class BCLearner(Learner):
    def compute_loss(self, params, batch: dict):
        logp, entropy, _vf = self.module.action_logp(
            params, batch[OBS], batch[ACTIONS]
        )
        loss = -jnp.mean(logp)
        return loss, {"bc_logp": jnp.mean(logp), "entropy": jnp.mean(entropy)}


class _NullRunnerGroup:
    """Offline algorithms have no rollout fleet; keep train()'s surface."""

    runners: list = []

    def sync_weights(self, params) -> None:
        pass

    def get_metrics(self) -> dict:
        return {"episode_return_mean": np.nan, "episode_len_mean": np.nan,
                "num_episodes": 0}

    def get_connector_state(self) -> dict:
        return {}

    def stop(self) -> None:
        pass


class BC(Algorithm):
    learner_class = BCLearner

    def __init__(self, config: BCConfig):
        # No Algorithm.__init__: offline training needs spaces + learner
        # but no env-runner fleet.
        import time as _time

        self.config = config
        self.iteration = 0
        self._total_env_steps = 0
        self._start = _time.time()
        spec = config.rl_module_spec or RLModuleSpec(
            model_config=dict(config.model)
        )
        probe_env = gym.make(config.env, **config.env_config) if isinstance(
            config.env, str
        ) else config.env(config.env_config)
        self.observation_space = probe_env.observation_space
        self.action_space = probe_env.action_space
        self.module_observation_space = self.observation_space
        probe_env.close()
        self.learner_group = LearnerGroup(
            self.learner_class, spec, self.observation_space,
            self.action_space, self._learner_config(), num_learners=0,
        )
        self.env_runner_group = _NullRunnerGroup()
        self.offline_data = OfflineData(config.input_)
        missing = {OBS, ACTIONS} - set(self.offline_data.columns)
        if missing:
            raise ValueError(f"offline dataset lacks columns: {missing}")

    def training_step(self) -> dict:
        learner = self.learner_group.local_learner
        metrics: dict = {}
        for _ in range(self.config.updates_per_iteration):
            batch = self.offline_data.sample(self.config.train_batch_size)
            metrics = learner.update(batch)
        metrics["num_samples_trained"] = (
            self.config.updates_per_iteration * self.config.train_batch_size
        )
        return metrics

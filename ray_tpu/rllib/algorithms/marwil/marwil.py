"""MARWIL — monotonic advantage re-weighted imitation learning (offline).

Role-equivalent of rllib/algorithms/marwil/ (SURVEY §2.8 offline-RL row):
behavior cloning whose log-likelihood term is weighted by
``exp(beta * advantage)``, with a value head trained on the dataset's
discounted returns-to-go. ``beta = 0`` degenerates to plain BC; larger
beta biases the clone toward better-than-average trajectories. The update
is one jitted XLA step, like every learner here.

The offline dataset needs per-timestep ``rewards`` and episode boundaries
(``eps_id`` or ``terminateds``) in addition to obs/actions; returns-to-go
are precomputed host-side once at load.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.bc.bc import BC, BCConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.policy.sample_batch import (
    ACTIONS, EPS_ID, OBS, REWARDS, SampleBatch, TERMINATEDS,
)

RETURNS = "returns_to_go"


def compute_returns_to_go(batch: SampleBatch, gamma: float) -> np.ndarray:
    """Discounted return-to-go per row, episode-aware (rows time-ordered
    within each episode, as recorded data naturally is)."""
    rewards = np.asarray(batch[REWARDS], dtype=np.float32)
    n = len(rewards)
    if EPS_ID in batch:
        ids = np.asarray(batch[EPS_ID])
        new_episode = np.zeros(n, dtype=bool)
        new_episode[0] = True
        new_episode[1:] = ids[1:] != ids[:-1]
    elif TERMINATEDS in batch:
        terms = np.asarray(batch[TERMINATEDS], dtype=bool)
        new_episode = np.zeros(n, dtype=bool)
        new_episode[0] = True
        new_episode[1:] = terms[:-1]
    else:
        new_episode = np.zeros(n, dtype=bool)
        new_episode[0] = True
    returns = np.zeros(n, dtype=np.float32)
    acc = 0.0
    for t in range(n - 1, -1, -1):
        acc = rewards[t] + gamma * acc
        returns[t] = acc
        if new_episode[t]:
            acc = 0.0  # row t starts an episode: nothing flows to t-1
    return returns


class MARWILConfig(BCConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or MARWIL)
        self.beta: float = 1.0
        self.vf_coeff: float = 1.0
        # Clip the advantage exponential (reference keeps a running
        # normalizer; a hard cap is the simple stable variant).
        self.advantage_clip: float = 10.0


class MARWILLearner(Learner):
    def compute_loss(self, params, batch: dict):
        cfg = self.config
        logp, entropy, vf = self.module.action_logp(
            params, batch[OBS], batch[ACTIONS]
        )
        returns = batch[RETURNS]
        advantages = returns - vf
        vf_loss = jnp.mean(advantages**2)
        weights = jnp.exp(
            jnp.clip(
                cfg.get("beta", 1.0)
                * jax.lax.stop_gradient(advantages)
                / jnp.maximum(
                    jax.lax.stop_gradient(jnp.std(returns)), 1e-3
                ),
                -cfg.get("advantage_clip", 10.0),
                cfg.get("advantage_clip", 10.0),
            )
        )
        bc_loss = -jnp.mean(weights * logp)
        total = bc_loss + cfg.get("vf_coeff", 1.0) * vf_loss
        return total, {
            "bc_loss": bc_loss,
            "vf_loss": vf_loss,
            "mean_weight": jnp.mean(weights),
            "entropy": jnp.mean(entropy),
        }


class MARWIL(BC):
    learner_class = MARWILLearner

    def __init__(self, config: MARWILConfig):
        super().__init__(config)
        missing = {REWARDS} - set(self.offline_data.columns)
        if missing:
            raise ValueError(
                f"MARWIL needs column(s) {missing} in the offline dataset "
                "(plus eps_id or terminateds for episode boundaries)"
            )
        self.offline_data._batch[RETURNS] = compute_returns_to_go(
            self.offline_data._batch, config.gamma
        )

    def _learner_config(self) -> dict:
        cfg = super()._learner_config()
        cfg.update(
            beta=self.config.beta,
            vf_coeff=self.config.vf_coeff,
            advantage_clip=self.config.advantage_clip,
        )
        return cfg

"""SAC — soft actor-critic (continuous control, off-policy).

Role-equivalent of rllib/algorithms/sac/sac.py + sac_torch_learner
(SURVEY §2.8): squashed-gaussian actor, twin Q critics with polyak-averaged
targets, automatic temperature tuning against a target entropy — the whole
update (actor + critic + alpha + polyak) is ONE jitted XLA step with
donated buffers, per the north star's jit-compiled learner discipline.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.rl_module import RLModule, RLModuleSpec, _mlp_apply, _mlp_init
from ray_tpu.rllib.policy.sample_batch import (
    ACTIONS, NEXT_OBS, OBS, REWARDS, SampleBatch, TERMINATEDS,
)
from ray_tpu.rllib.utils.replay_buffers import ReplayBuffer

LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


class SACConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or SAC)
        self.lr = 3e-4
        self.train_batch_size = 256
        self.replay_buffer_capacity: int = 100_000
        self.num_steps_sampled_before_learning_starts: int = 1000
        self.tau: float = 0.005  # polyak coefficient
        self.target_entropy: float | str = "auto"  # auto → -act_dim
        self.initial_alpha: float = 1.0
        self.updates_per_iteration: int = 200
        self.rollout_fragment_length = 25
        self.num_envs_per_env_runner = 8
        self.num_env_runners = 1


class SACModule(RLModule):
    """Squashed-gaussian policy + twin Q towers.

    Actions leave the module already tanh-squashed and scaled into the
    env's Box bounds, so the runner's ClipActions connector is a no-op and
    replayed ACTIONS feed the critics unchanged.
    """

    def __init__(self, observation_space, action_space, model_config):
        super().__init__(observation_space, action_space, model_config)
        assert hasattr(action_space, "low"), "SAC requires a Box action space"
        self.hiddens = tuple(model_config.get("fcnet_hiddens", (256, 256)))
        self.obs_dim = int(np.prod(observation_space.shape))
        self.act_dim = int(np.prod(action_space.shape))
        low = np.asarray(action_space.low, dtype=np.float32).reshape(-1)
        high = np.asarray(action_space.high, dtype=np.float32).reshape(-1)
        self.center = jnp.asarray((high + low) / 2.0)
        self.scale = jnp.asarray((high - low) / 2.0)
        self.discrete = False

    def init_params(self, rng) -> dict:
        pi_rng, q1_rng, q2_rng = jax.random.split(rng, 3)
        return {
            "pi": _mlp_init(
                pi_rng, (self.obs_dim, *self.hiddens, 2 * self.act_dim)
            ),
            "q1": _mlp_init(q1_rng, (self.obs_dim + self.act_dim, *self.hiddens, 1)),
            "q2": _mlp_init(q2_rng, (self.obs_dim + self.act_dim, *self.hiddens, 1)),
            "log_alpha": jnp.zeros(()),
        }

    # -- policy ----------------------------------------------------------
    def _pi_dist(self, pi_params, obs):
        obs = obs.reshape(obs.shape[0], -1)
        out = _mlp_apply(pi_params, obs, activation=jax.nn.relu)
        mean, log_std = jnp.split(out, 2, axis=-1)
        return mean, jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)

    def sample_action(self, pi_params, obs, rng):
        """→ (env-scaled actions, logp) with tanh-squash correction."""
        mean, log_std = self._pi_dist(pi_params, obs)
        std = jnp.exp(log_std)
        u = mean + std * jax.random.normal(rng, mean.shape)
        gauss_logp = -0.5 * jnp.sum(
            ((u - mean) / std) ** 2 + 2 * log_std + jnp.log(2 * jnp.pi),
            axis=-1,
        )
        a = jnp.tanh(u)
        # d tanh correction: log det Jacobian of the squash
        logp = gauss_logp - jnp.sum(jnp.log(1.0 - a**2 + 1e-6), axis=-1)
        return a * self.scale + self.center, logp

    def q_values(self, q_params, obs, actions):
        obs = obs.reshape(obs.shape[0], -1)
        x = jnp.concatenate([obs, actions.reshape(obs.shape[0], -1)], axis=-1)
        return _mlp_apply(q_params, x, activation=jax.nn.relu)[..., 0]

    # -- RLModule surface (env runner hooks) -----------------------------
    def forward_exploration(self, params, obs, rng):
        actions, logp = self.sample_action(params["pi"], jnp.asarray(obs), rng)
        return actions, logp, {"vf_preds": jnp.zeros(actions.shape[0])}

    def forward_inference(self, params, obs):
        mean, _ = self._pi_dist(params["pi"], jnp.asarray(obs))
        return jnp.tanh(mean) * self.scale + self.center

    def forward_train(self, params, obs) -> dict:
        mean, log_std = self._pi_dist(params["pi"], jnp.asarray(obs))
        return {"mean": mean, "log_std": log_std,
                "vf": jnp.zeros(mean.shape[0])}


class SACLearner(Learner):
    """One jitted step: critic + actor + alpha losses, polyak targets."""

    def __init__(self, module: SACModule, config: dict, seed: int = 0):
        super().__init__(module, config, seed)
        self.target_params = jax.tree_util.tree_map(
            jnp.copy, {"q1": self.params["q1"], "q2": self.params["q2"]}
        )
        if config.get("initial_alpha") is not None:
            self.params["log_alpha"] = jnp.asarray(
                float(np.log(config["initial_alpha"]))
            )
            self.opt_state = self.optimizer.init(self.params)
        target_entropy = config.get("target_entropy", "auto")
        self._target_entropy = (
            -float(module.act_dim)
            if target_entropy in (None, "auto")
            else float(target_entropy)
        )
        self._rng = jax.random.PRNGKey(seed * 7919 + 13)
        self._sac_step = jax.jit(self._jit_sac_step, donate_argnums=(0, 1, 2))

    def compute_loss(self, params, batch):  # pragma: no cover - unused path
        raise NotImplementedError("SACLearner jits its own combined step")

    def _critic_regularizer(self, p, batch, rng, q1_data, q2_data):
        """Extra critic-loss term, traced inside the jitted step. SAC
        adds nothing; CQL overrides with the conservative penalty."""
        return 0.0, {}

    def _jit_sac_step(self, params, target_params, opt_state, batch, rng):
        module: SACModule = self.module
        cfg = self.config
        gamma = cfg.get("gamma", 0.99)
        tau = cfg.get("tau", 0.005)
        rng_actor, rng_next, rng_reg = jax.random.split(rng, 3)
        obs, actions = batch[OBS], batch[ACTIONS]
        not_done = 1.0 - batch[TERMINATEDS].astype(jnp.float32)

        def loss_fn(p):
            alpha = jnp.exp(p["log_alpha"])
            sg = jax.lax.stop_gradient
            # -- critic target (no grads anywhere inside)
            a_next, logp_next = module.sample_action(
                sg(p["pi"]), batch[NEXT_OBS], rng_next
            )
            q_next = jnp.minimum(
                module.q_values(target_params["q1"], batch[NEXT_OBS], a_next),
                module.q_values(target_params["q2"], batch[NEXT_OBS], a_next),
            )
            target = sg(
                batch[REWARDS]
                + gamma * not_done * (q_next - sg(alpha) * logp_next)
            )
            q1 = module.q_values(p["q1"], obs, actions)
            q2 = module.q_values(p["q2"], obs, actions)
            critic_loss = jnp.mean((q1 - target) ** 2) + jnp.mean(
                (q2 - target) ** 2
            )
            # Critic regularizer hook: zero for SAC; CQL adds the
            # conservative penalty here (rllib/algorithms/cql role).
            reg_loss, reg_metrics = self._critic_regularizer(
                p, batch, rng_reg, q1, q2
            )
            critic_loss = critic_loss + reg_loss
            # -- actor (grads flow to pi only; critics frozen via sg)
            a_pi, logp_pi = module.sample_action(p["pi"], obs, rng_actor)
            q_pi = jnp.minimum(
                module.q_values(sg(p["q1"]), obs, a_pi),
                module.q_values(sg(p["q2"]), obs, a_pi),
            )
            actor_loss = jnp.mean(sg(alpha) * logp_pi - q_pi)
            # -- temperature
            alpha_loss = -jnp.mean(
                p["log_alpha"] * sg(logp_pi + self._target_entropy)
            )
            total = critic_loss + actor_loss + alpha_loss
            return total, {
                "critic_loss": critic_loss,
                "actor_loss": actor_loss,
                "alpha_loss": alpha_loss,
                "alpha": alpha,
                "entropy": -jnp.mean(logp_pi),
                "q_mean": jnp.mean(q1),
                **reg_metrics,
            }

        (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params
        )
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(
            lambda a, b: a + b, params, updates
        )
        new_targets = jax.tree_util.tree_map(
            lambda t, o: (1.0 - tau) * t + tau * o,
            target_params,
            {"q1": params["q1"], "q2": params["q2"]},
        )
        metrics["total_loss"] = loss
        return params, new_targets, opt_state, metrics

    def update(self, batch: SampleBatch) -> dict:
        device_batch = {
            k: jnp.asarray(v)
            for k, v in batch.items()
            if k in (OBS, ACTIONS, REWARDS, NEXT_OBS, TERMINATEDS)
        }
        self._rng, key = jax.random.split(self._rng)
        self.params, self.target_params, self.opt_state, metrics = (
            self._sac_step(
                self.params, self.target_params, self.opt_state,
                device_batch, key,
            )
        )
        return {k: float(v) for k, v in metrics.items()}

    def get_state(self) -> dict:
        state = super().get_state()
        state["target_params"] = jax.device_get(self.target_params)
        return state

    def set_state(self, state: dict) -> None:
        super().set_state(state)
        if "target_params" in state:
            self.target_params = jax.device_put(state["target_params"])


class SAC(Algorithm):
    learner_class = SACLearner

    def __init__(self, config: SACConfig):
        if config.rl_module_spec is None:
            config.rl_module_spec = RLModuleSpec(
                SACModule, dict(config.model)
            )
        super().__init__(config)
        self.replay = ReplayBuffer(
            config.replay_buffer_capacity, seed=config.seed
        )

    def _learner_config(self) -> dict:
        cfg = super()._learner_config()
        cfg.update(
            tau=self.config.tau,
            target_entropy=self.config.target_entropy,
            initial_alpha=self.config.initial_alpha,
        )
        return cfg

    def training_step(self) -> dict:
        config = self.config
        fragment = self.env_runner_group.sample()
        self._total_env_steps += len(fragment)
        self.replay.add(fragment)
        metrics: dict = {"buffer_size": len(self.replay)}
        if len(self.replay) < config.num_steps_sampled_before_learning_starts:
            return metrics
        learner = self.learner_group.local_learner
        assert learner is not None, "SAC uses a local learner (num_learners=0)"
        for _ in range(config.updates_per_iteration):
            batch = self.replay.sample(config.train_batch_size)
            update_metrics = learner.update(batch)
        metrics.update(update_metrics)
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        return metrics

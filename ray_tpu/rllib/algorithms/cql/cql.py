"""CQL — conservative Q-learning (offline continuous control).

Role-equivalent of rllib/algorithms/cql/ (SURVEY §2.8 offline-RL
family): SAC's actor/critic/temperature machinery trained purely from an
offline dataset, with the CQL(H) conservative penalty on both critics —

    alpha_cql * ( E_s[ logsumexp_a Q(s, a) ] - E_(s,a)~D[ Q(s, a) ] )

where the logsumexp is estimated from uniform-random and current-policy
actions with importance correction (the standard CQL estimator). The
penalty pushes Q down on out-of-distribution actions, so the recovered
policy improves on a skewed behavior dataset where naive SAC/BC cannot.
The whole update stays ONE jitted XLA step (SACLearner's step; the
penalty rides the `_critic_regularizer` hook inside it).
"""

from __future__ import annotations

import time as _time

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.sac.sac import (
    SACConfig, SACLearner, SACModule,
)
from ray_tpu.rllib.core.learner import LearnerGroup
from ray_tpu.rllib.core.rl_module import RLModuleSpec
from ray_tpu.rllib.offline.offline_data import OfflineData
from ray_tpu.rllib.policy.sample_batch import (
    ACTIONS, NEXT_OBS, OBS, REWARDS, TERMINATEDS,
)


class CQLConfig(SACConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or CQL)
        self.cql_alpha: float = 5.0
        self.cql_n_actions: int = 10
        self.updates_per_iteration = 100
        # offline: no rollout fleet, no replay warmup
        self.input_: object = None
        self.num_env_runners = 0
        self.num_steps_sampled_before_learning_starts = 0

    def offline_data(self, *, input_=None):
        if input_ is not None:
            self.input_ = input_
        return self

    def validate(self) -> None:
        super().validate()
        if self.input_ is None:
            raise ValueError("CQL needs config.offline_data(input_=...)")


class CQLLearner(SACLearner):
    def _critic_regularizer(self, p, batch, rng, q1_data, q2_data):
        module: SACModule = self.module
        cfg = self.config
        n = int(cfg.get("cql_n_actions", 10))
        alpha_cql = float(cfg.get("cql_alpha", 5.0))
        sg = jax.lax.stop_gradient
        obs = batch[OBS]
        batch_size = obs.shape[0]
        rng_rand, rng_pi = jax.random.split(rng)
        # OOD action set: n uniform-random + n current-policy actions.
        rand_u = jax.random.uniform(
            rng_rand, (n, batch_size, module.act_dim), minval=-1.0,
            maxval=1.0,
        )
        rand_actions = rand_u * module.scale + module.center

        def sample(key):
            return module.sample_action(sg(p["pi"]), obs, key)

        pi_actions, pi_logp = jax.vmap(sample)(jax.random.split(rng_pi, n))
        # importance correction: uniform density over the action box
        log_unif = -jnp.sum(jnp.log(2.0 * module.scale))

        def penalty(q_params, q_data):
            def q_of(actions):
                return jax.vmap(
                    lambda a: module.q_values(q_params, obs, a)
                )(actions)  # [n, B]

            stacked = jnp.concatenate(
                [q_of(rand_actions) - log_unif,
                 q_of(pi_actions) - sg(pi_logp)],
                axis=0,
            )
            lse = jax.scipy.special.logsumexp(stacked, axis=0) - jnp.log(
                2.0 * n
            )
            return jnp.mean(lse) - jnp.mean(q_data)

        gap1 = penalty(p["q1"], q1_data)
        gap2 = penalty(p["q2"], q2_data)
        reg = alpha_cql * (gap1 + gap2)
        return reg, {"cql_penalty": reg, "cql_gap": 0.5 * (gap1 + gap2)}


class _NullRunnerGroup:
    runners: list = []

    def sync_weights(self, params) -> None:
        pass

    def get_metrics(self) -> dict:
        return {"episode_return_mean": np.nan, "episode_len_mean": np.nan,
                "num_episodes": 0}

    def get_connector_state(self) -> dict:
        return {}

    def stop(self) -> None:
        pass


class CQL(Algorithm):
    learner_class = CQLLearner

    def __init__(self, config: CQLConfig):
        # Offline: spaces + learner, no env-runner fleet (BC's shape).
        from ray_tpu.rllib.utils.metrics import MetricsLogger

        self.config = config
        self.iteration = 0
        self._total_env_steps = 0
        self._start = _time.time()
        self.metrics = MetricsLogger()
        spec = config.rl_module_spec or RLModuleSpec(
            SACModule, dict(config.model)
        )
        probe_env = gym.make(config.env, **config.env_config) if isinstance(
            config.env, str
        ) else config.env(config.env_config)
        self.observation_space = probe_env.observation_space
        self.action_space = probe_env.action_space
        self.module_observation_space = self.observation_space
        probe_env.close()
        self.learner_group = LearnerGroup(
            self.learner_class, spec, self.observation_space,
            self.action_space, self._learner_config(), num_learners=0,
        )
        self.env_runner_group = _NullRunnerGroup()
        self.offline_data = OfflineData(config.input_)
        missing = {OBS, ACTIONS, REWARDS, NEXT_OBS, TERMINATEDS} - set(
            self.offline_data.columns
        )
        if missing:
            raise ValueError(f"offline dataset lacks columns: {missing}")

    def _learner_config(self) -> dict:
        cfg = super()._learner_config()
        cfg.update(
            tau=self.config.tau,
            target_entropy=self.config.target_entropy,
            initial_alpha=self.config.initial_alpha,
            cql_alpha=self.config.cql_alpha,
            cql_n_actions=self.config.cql_n_actions,
        )
        return cfg

    def training_step(self) -> dict:
        learner = self.learner_group.local_learner
        assert learner is not None
        metrics: dict = {}
        for _ in range(self.config.updates_per_iteration):
            batch = self.offline_data.sample(self.config.train_batch_size)
            metrics = learner.update(batch)
        metrics["num_samples_trained"] = (
            self.config.updates_per_iteration * self.config.train_batch_size
        )
        return metrics

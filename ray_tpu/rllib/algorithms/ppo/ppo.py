"""PPO — clipped-surrogate policy optimization.

Role-equivalent of rllib/algorithms/ppo/ppo.py :: PPOConfig/PPO and
ppo/ppo_learner.py + torch/ppo_torch_learner.py loss (SURVEY §2.8, §3.5):
GAE advantages (connector math in utils/postprocessing.py), minibatch SGD
epochs over the train batch, clipped surrogate + value loss + entropy
bonus — with the whole update jitted on the learner device (the north
star's "jit-compiled XLA learner").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.policy.sample_batch import (
    ACTION_LOGP, ACTIONS, ADVANTAGES, OBS, SampleBatch, TERMINATEDS,
    TRUNCATEDS, VALUE_TARGETS,
)
from ray_tpu.rllib.utils.postprocessing import compute_gae


class PPOConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or PPO)
        self.lr = 3e-4
        self.train_batch_size = 2000
        self.minibatch_size: int = 128
        self.num_epochs: int = 8
        self.clip_param: float = 0.2
        self.vf_clip_param: float = 10.0
        self.vf_loss_coeff: float = 0.5
        self.entropy_coeff: float = 0.0
        self.lambda_: float = 0.95
        self.kl_target: float = 0.02
        self.use_gae: bool = True


class PPOLearner(Learner):
    def compute_loss(self, params, batch: dict):
        cfg = self.config
        if getattr(self.module, "is_stateful", False):
            # recurrent modules replay the rollout's state trajectory —
            # dones reset the training scan at episode starts
            dones = jnp.logical_or(batch[TERMINATEDS], batch[TRUNCATEDS])
            logp, entropy, vf = self.module.action_logp(
                params, batch[OBS], batch[ACTIONS], dones=dones
            )
        else:
            logp, entropy, vf = self.module.action_logp(
                params, batch[OBS], batch[ACTIONS]
            )
        ratio = jnp.exp(logp - batch[ACTION_LOGP])
        adv = batch[ADVANTAGES]
        clip = cfg.get("clip_param", 0.2)
        surrogate = jnp.minimum(
            ratio * adv, jnp.clip(ratio, 1 - clip, 1 + clip) * adv
        )
        policy_loss = -jnp.mean(surrogate)
        vf_err = (vf - batch[VALUE_TARGETS]) ** 2
        vf_loss = jnp.mean(
            jnp.minimum(vf_err, cfg.get("vf_clip_param", 10.0) ** 2)
        )
        entropy_mean = jnp.mean(entropy)
        total = (
            policy_loss
            + cfg.get("vf_loss_coeff", 0.5) * vf_loss
            - cfg.get("entropy_coeff", 0.0) * entropy_mean
        )
        kl = jnp.mean(batch[ACTION_LOGP] - logp)
        return total, {
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "entropy": entropy_mean,
            "kl": kl,
        }


class PPO(Algorithm):
    learner_class = PPOLearner

    def _value_fn(self):
        """V(obs) under the current learner params, jit-cached once."""
        if not hasattr(self, "_vf_module"):
            from ray_tpu.rllib.core.rl_module import RLModuleSpec

            spec = self.config.rl_module_spec or RLModuleSpec(
                model_config=dict(self.config.model)
            )
            self._vf_module = spec.build(
                getattr(self, "module_observation_space", self.observation_space),
                self.action_space,
            )
            self._vf_jit = jax.jit(
                lambda params, obs: self._vf_module.forward_train(params, obs)["vf"]
            )
        params = self.learner_group.get_weights()
        return lambda obs: self._vf_jit(params, jnp.asarray(obs))

    def _value_fn_for(self, module_id: str):
        """Per-module V(obs) in multi-agent mode."""
        if not hasattr(self, "_vf_modules"):
            self._vf_modules = {}
            self._vf_jits = {}
        if module_id not in self._vf_modules:
            module = self._multi_spec.module_specs[module_id].build(
                self.observation_space[module_id],
                self.action_space[module_id],
            )
            self._vf_modules[module_id] = module
            self._vf_jits[module_id] = jax.jit(
                lambda params, obs, _m=module: _m.forward_train(params, obs)["vf"]
            )
        params = self.learner_group.get_weights()[module_id]
        jit = self._vf_jits[module_id]
        return lambda obs: jit(params, jnp.asarray(obs))

    def _learner_pipeline(self):
        """Learner connector pipeline: user stages + default GAE."""
        if not hasattr(self, "_learner_conn"):
            from ray_tpu.rllib.connectors import (
                ConnectorPipelineV2, GeneralAdvantageEstimation,
            )

            stages = []
            if self.config.learner_connector is not None:
                user = self.config.learner_connector()
                stages.extend(
                    user.connectors if hasattr(user, "connectors") else [user]
                )
            stages.append(
                GeneralAdvantageEstimation(
                    gamma=self.config.gamma, lambda_=self.config.lambda_
                )
            )
            self._learner_conn = ConnectorPipelineV2(stages)
        return self._learner_conn

    def _learner_config(self) -> dict:
        cfg = super()._learner_config()
        cfg.update(
            clip_param=self.config.clip_param,
            vf_clip_param=self.config.vf_clip_param,
            vf_loss_coeff=self.config.vf_loss_coeff,
            entropy_coeff=self.config.entropy_coeff,
        )
        return cfg

    def training_step(self) -> dict:
        if self.config.is_multi_agent:
            return self._training_step_multi_agent()
        config = self.config
        # 1. sample until train_batch_size env steps collected
        batches = []
        steps = 0
        while steps < config.train_batch_size:
            fragment = self.env_runner_group.sample()
            steps += len(fragment)
            batches.append(fragment)
        batch = SampleBatch.concat_samples(batches)
        self._total_env_steps += len(batch)
        # 2. learner connectors: GAE (bootstrap values from current params)
        batch = self._learner_pipeline()(batch, value_fn=self._value_fn())
        # 3. minibatch SGD epochs (recurrent modules get sequence-
        # preserving minibatches: shuffling rows would scramble the
        # lax.scan recurrence windows)
        rng = np.random.default_rng(self.iteration)
        from ray_tpu.rllib.core.rl_module import RLModuleSpec

        spec = config.rl_module_spec or RLModuleSpec(
            model_config=dict(config.model)
        )
        stateful = bool(getattr(spec.module_class, "is_stateful", False))
        metrics: dict = {}
        for _ in range(config.num_epochs):
            if stateful:
                seq_len = int(spec.model_config.get("max_seq_len", 16))
                if config.rollout_fragment_length % seq_len != 0:
                    raise ValueError(
                        "recurrent PPO needs rollout_fragment_length "
                        f"({config.rollout_fragment_length}) divisible by "
                        f"max_seq_len ({seq_len}) — otherwise training "
                        "windows straddle unrelated envs' rows"
                    )
                mbs = batch.seq_minibatches(
                    seq_len, config.minibatch_size, rng,
                )
            else:
                mbs = batch.minibatches(config.minibatch_size, rng)
            for mb in mbs:
                metrics = self.learner_group.update(mb)
        # 4. broadcast fresh weights to runners
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        metrics["num_env_steps_trained"] = len(batch)
        return metrics

    def _training_step_multi_agent(self) -> dict:
        from ray_tpu.rllib.policy.sample_batch import MultiAgentBatch

        config = self.config
        batches = []
        steps = 0
        while steps < config.train_batch_size:
            fragment = self.env_runner_group.sample()
            steps += fragment.env_steps()
            batches.append(fragment)
        batch = MultiAgentBatch.concat_samples(batches)
        self._total_env_steps += batch.env_steps()
        # per-module GAE, then per-module minibatch SGD epochs
        pipeline = self._learner_pipeline()
        processed = {
            mid: pipeline(sub, value_fn=self._value_fn_for(mid))
            for mid, sub in batch.items()
        }
        rng = np.random.default_rng(self.iteration)
        metrics: dict = {}
        for _ in range(config.num_epochs):
            for mid, sub in processed.items():
                for mb in sub.minibatches(config.minibatch_size, rng):
                    metrics[mid] = self.learner_group.update_module(mid, mb)
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        flat = {
            f"{mid}/{k}": v
            for mid, m in metrics.items()
            for k, v in m.items()
        }
        flat["num_env_steps_trained"] = batch.env_steps()
        return flat

"""IMPALA — async sampling + V-trace off-policy correction.

Role-equivalent of rllib/algorithms/impala/impala.py (+ the vtrace math of
rllib/algorithms/impala/torch/vtrace_torch_v2.py, originally the IMPALA
paper's tf implementation), TPU-first (SURVEY §2.8, §3.5): env runners
push rollouts continuously (async queue via EnvRunnerGroup.collect_ready),
the learner consumes whatever arrived — stale-by-k policies corrected with
V-trace importance weights ρ/c — and the whole update is one jitted XLA
function.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.policy.sample_batch import (
    ACTION_LOGP, ACTIONS, EPS_ID, NEXT_OBS, OBS, REWARDS, SampleBatch,
    TERMINATEDS, TRUNCATEDS,
)


def vtrace(
    behaviour_logp,
    target_logp,
    rewards,
    values,
    bootstrap_value,
    discounts,
    clip_rho_threshold: float = 1.0,
    clip_c_threshold: float = 1.0,
):
    """V-trace targets (Espeholt et al. 2018) over one [T] sequence, in
    jax with a backward lax.scan (XLA-friendly — no Python loop)."""
    rhos = jnp.exp(target_logp - behaviour_logp)
    clipped_rhos = jnp.minimum(clip_rho_threshold, rhos)
    clipped_cs = jnp.minimum(clip_c_threshold, rhos)
    next_values = jnp.concatenate([values[1:], bootstrap_value[None]])
    deltas = clipped_rhos * (rewards + discounts * next_values - values)

    def backward(acc, inputs):
        delta_t, discount_t, c_t = inputs
        acc = delta_t + discount_t * c_t * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        backward,
        jnp.zeros_like(bootstrap_value),
        (deltas, discounts, clipped_cs),
        reverse=True,
    )
    vs = vs_minus_v + values
    next_vs = jnp.concatenate([vs[1:], bootstrap_value[None]])
    pg_advantages = clipped_rhos * (rewards + discounts * next_vs - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_advantages)


class IMPALAConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or IMPALA)
        self.lr = 5e-4
        self.train_batch_size = 500
        self.vf_loss_coeff: float = 0.5
        self.entropy_coeff: float = 0.01
        self.clip_rho_threshold: float = 1.0
        self.clip_c_threshold: float = 1.0
        self.max_queue_len: int = 8
        self.rollout_fragment_length = 50


class IMPALALearner(Learner):
    def compute_loss(self, params, batch: dict):
        cfg = self.config
        logp, entropy, vf = self.module.action_logp(
            params, batch[OBS], batch[ACTIONS]
        )
        # [T] sequences laid out env-major & episode-contiguous by the
        # runner; treat the whole fragment as one sequence with discounts
        # zeroed at episode ends (the standard flattened-vtrace trick).
        done = jnp.logical_or(batch[TERMINATEDS], batch[TRUNCATEDS])
        discounts = cfg.get("gamma", 0.99) * (1.0 - done.astype(jnp.float32))
        vs, pg_adv = vtrace(
            batch[ACTION_LOGP],
            logp,
            batch[REWARDS],
            vf,
            batch["bootstrap_value"][0],
            discounts,
            cfg.get("clip_rho_threshold", 1.0),
            cfg.get("clip_c_threshold", 1.0),
        )
        policy_loss = -jnp.mean(logp * pg_adv)
        vf_loss = 0.5 * jnp.mean((vf - vs) ** 2)
        entropy_mean = jnp.mean(entropy)
        total = (
            policy_loss
            + cfg.get("vf_loss_coeff", 0.5) * vf_loss
            - cfg.get("entropy_coeff", 0.01) * entropy_mean
        )
        return total, {
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "entropy": entropy_mean,
        }


class IMPALA(Algorithm):
    learner_class = IMPALALearner

    def _learner_config(self) -> dict:
        cfg = super()._learner_config()
        cfg.update(
            vf_loss_coeff=self.config.vf_loss_coeff,
            entropy_coeff=self.config.entropy_coeff,
            clip_rho_threshold=self.config.clip_rho_threshold,
            clip_c_threshold=self.config.clip_c_threshold,
        )
        return cfg

    def training_step(self) -> dict:
        config = self.config
        # Async harvest: take whatever fragments finished; runners are
        # immediately re-submitted (continuous sampling).
        ready = self.env_runner_group.collect_ready(timeout=10.0)
        if not ready:
            return {}
        metrics: dict = {}
        trained = 0
        for fragment in ready[: config.max_queue_len]:
            self._total_env_steps += len(fragment)
            fragment["bootstrap_value"] = np.full(
                len(fragment), self._bootstrap_value(fragment), dtype=np.float32
            )
            metrics = self.learner_group.update(fragment)
            trained += len(fragment)
        # Weights go back at iteration cadence (runners run off-policy).
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        metrics["num_env_steps_trained"] = trained
        return metrics

    def _bootstrap_value(self, fragment: SampleBatch) -> float:
        if bool(fragment[TERMINATEDS][-1]):
            return 0.0
        if not hasattr(self, "_vf_jit"):
            from ray_tpu.rllib.core.rl_module import RLModuleSpec

            spec = self.config.rl_module_spec or RLModuleSpec(
                model_config=dict(self.config.model)
            )
            self._vf_module = spec.build(
                self.observation_space, self.action_space
            )
            self._vf_jit = jax.jit(
                lambda params, obs: self._vf_module.forward_train(params, obs)["vf"]
            )
        params = self.learner_group.get_weights()
        return float(
            np.asarray(
                self._vf_jit(params, jnp.asarray(fragment[NEXT_OBS][-1][None]))
            )[0]
        )

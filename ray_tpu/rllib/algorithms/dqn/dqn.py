"""DQN — double Q-learning with (prioritized) replay.

Role-equivalent of rllib/algorithms/dqn/dqn.py + dqn_rainbow_learner
(SURVEY §2.8): epsilon-greedy rollouts into a replay buffer, double-DQN
targets (online net argmax, target net value), periodic target sync, and
the TD update jitted end-to-end. Dueling/n-step kept out for clarity;
prioritized replay is config-switchable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.policy.sample_batch import (
    ACTIONS, NEXT_OBS, OBS, REWARDS, SampleBatch, TERMINATEDS,
)
from ray_tpu.rllib.utils.replay_buffers import (
    PrioritizedReplayBuffer, ReplayBuffer,
)


class DQNConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DQN)
        self.lr = 5e-4
        self.train_batch_size = 32
        self.replay_buffer_capacity: int = 50_000
        self.prioritized_replay: bool = False
        self.num_steps_sampled_before_learning_starts: int = 1000
        self.target_network_update_freq: int = 500  # env steps
        self.epsilon_initial: float = 1.0
        self.epsilon_final: float = 0.05
        self.epsilon_timesteps: int = 10_000
        self.double_q: bool = True
        self.updates_per_iteration: int = 50
        self.rollout_fragment_length = 4


class DQNLearner(Learner):
    """Q-net learner; module's pi tower doubles as the Q head."""

    def __init__(self, module, config, seed: int = 0):
        super().__init__(module, config, seed)
        self.target_params = jax.tree_util.tree_map(jnp.copy, self.params)

    def compute_loss(self, params, batch: dict):
        cfg = self.config
        gamma = cfg.get("gamma", 0.99)
        q_all = self.module.forward_train(params, batch[OBS])["logits"]
        actions = batch[ACTIONS].astype(jnp.int32)
        q = jnp.take_along_axis(q_all, actions[:, None], axis=-1)[:, 0]
        q_next_target = self.module.forward_train(
            batch["target_params"], batch[NEXT_OBS]
        )["logits"]
        if cfg.get("double_q", True):
            q_next_online = self.module.forward_train(params, batch[NEXT_OBS])[
                "logits"
            ]
            next_actions = jnp.argmax(q_next_online, axis=-1)
        else:
            next_actions = jnp.argmax(q_next_target, axis=-1)
        q_next = jnp.take_along_axis(
            q_next_target, next_actions[:, None], axis=-1
        )[:, 0]
        not_done = 1.0 - batch[TERMINATEDS].astype(jnp.float32)
        target = batch[REWARDS] + gamma * not_done * jax.lax.stop_gradient(q_next)
        td_error = q - target
        weights = batch.get("weights", jnp.ones_like(q))
        loss = jnp.mean(weights * td_error**2)
        return loss, {
            "td_error_mean": jnp.mean(jnp.abs(td_error)),
            # per-sample |TD| — prioritized replay needs individual
            # priorities, not the batch mean (a constant priority
            # degenerates PER to biased uniform sampling).
            "td_abs": jnp.abs(td_error),
        }

    def update(self, batch: SampleBatch) -> dict:
        device_batch = {k: jnp.asarray(v) for k, v in batch.items()
                        if k != "batch_indexes"}
        device_batch["target_params"] = self.target_params
        self.params, self.opt_state, metrics = self._step(
            self.params, self.opt_state, device_batch
        )
        td_abs = np.asarray(metrics.pop("td_abs"))
        out = {k: float(v) for k, v in metrics.items()}
        out["td_abs"] = td_abs
        return out

    def sync_target(self) -> None:
        self.target_params = jax.tree_util.tree_map(jnp.copy, self.params)


class DQN(Algorithm):
    learner_class = DQNLearner

    def __init__(self, config):
        super().__init__(config)
        buffer_cls = (
            PrioritizedReplayBuffer if config.prioritized_replay else ReplayBuffer
        )
        self.replay = buffer_cls(config.replay_buffer_capacity, seed=config.seed)
        self._steps_since_target_sync = 0

    def _learner_config(self) -> dict:
        cfg = super()._learner_config()
        cfg.update(double_q=self.config.double_q)
        return cfg

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._total_env_steps / max(1, cfg.epsilon_timesteps))
        return cfg.epsilon_initial + frac * (
            cfg.epsilon_final - cfg.epsilon_initial
        )

    def training_step(self) -> dict:
        config = self.config
        # 1. collect with epsilon-greedy IN the runners (greedy action with
        #    prob 1-ε, uniform random with prob ε, applied before env.step
        #    so replay transitions are consistent).
        eps = self._epsilon()
        import ray_tpu as _rt

        _rt.get(
            [
                r.set_epsilon.remote(eps)
                for r in self.env_runner_group.runners
            ],
            timeout=60,
        )
        fragment = self.env_runner_group.sample()
        self._total_env_steps += len(fragment)
        self._steps_since_target_sync += len(fragment)
        self.replay.add(fragment)

        metrics: dict = {"epsilon": eps, "buffer_size": len(self.replay)}
        if len(self.replay) < config.num_steps_sampled_before_learning_starts:
            return metrics
        # 2. replayed TD updates
        learner = self._local_dqn_learner()
        for _ in range(config.updates_per_iteration):
            batch = self.replay.sample(config.train_batch_size)
            update_metrics = learner.update(batch)
            td_abs = update_metrics.pop("td_abs", None)
            if (
                config.prioritized_replay
                and "batch_indexes" in batch
                and td_abs is not None
            ):
                self.replay.update_priorities(batch["batch_indexes"], td_abs)
        metrics.update(update_metrics)
        # 3. target sync + weight broadcast
        if self._steps_since_target_sync >= config.target_network_update_freq:
            learner.sync_target()
            self._steps_since_target_sync = 0
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        return metrics

    def _local_dqn_learner(self) -> DQNLearner:
        assert self.learner_group.local_learner is not None, (
            "DQN uses a local learner (num_learners=0)"
        )
        return self.learner_group.local_learner

"""Algorithm — the top-level RL training loop.

Role-equivalent of rllib/algorithms/algorithm.py :: Algorithm
(SURVEY §2.8, §3.5): owns an EnvRunnerGroup + LearnerGroup; train() runs
one iteration (sample → learner update → weight sync → metrics); save()/
from_checkpoint() round-trip learner + config state; evaluate() runs
greedy episodes. Doubles as a Tune trainable via the same step() protocol
(ray_tpu.tune.Trainable duck-type).
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Any, Optional

import gymnasium as gym
import numpy as np

from ray_tpu._private import atomic_io
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import LearnerGroup
from ray_tpu.rllib.core.rl_module import RLModuleSpec
from ray_tpu.rllib.env.env_runner_group import EnvRunnerGroup
from ray_tpu.rllib.utils.metrics import MetricsLogger


class Algorithm:
    learner_class = None  # subclasses set

    def __init__(self, config: AlgorithmConfig):
        self.config = config
        self.iteration = 0
        self._total_env_steps = 0
        self._start = time.time()
        self.metrics = MetricsLogger(
            window=getattr(config, "metrics_num_episodes_for_smoothing", 100)
        )
        if config.is_multi_agent:
            self._init_multi_agent(config)
        else:
            self._init_single_agent(config)
        self.env_runner_group.sync_weights(self.learner_group.get_weights())

    def _init_single_agent(self, config: AlgorithmConfig) -> None:
        spec = config.rl_module_spec or RLModuleSpec(
            model_config=dict(config.model)
        )
        probe_env = gym.make(config.env, **config.env_config) if isinstance(
            config.env, str
        ) else config.env(config.env_config)
        self.observation_space = probe_env.observation_space
        self.action_space = probe_env.action_space
        # A shape-changing env→module connector (framestack, …) means the
        # module trains on the pipeline's output space, not the env's.
        self.module_observation_space = self.observation_space
        if config.env_to_module_connector is not None:
            probe_pipe = config.env_to_module_connector()
            probe_out = np.asarray(
                probe_pipe(np.asarray(self.observation_space.sample())[None])
            )
            if tuple(probe_out.shape[1:]) != tuple(
                self.observation_space.shape or ()
            ):
                self.module_observation_space = gym.spaces.Box(
                    -np.inf, np.inf, shape=probe_out.shape[1:],
                    dtype=np.float32,
                )
        probe_env.close()

        self.learner_group = LearnerGroup(
            self.learner_class,
            spec,
            self.module_observation_space,
            self.action_space,
            self._learner_config(),
            num_learners=config.num_learners,
        )
        self.env_runner_group = EnvRunnerGroup(
            self._env_creator(),
            spec,
            num_env_runners=config.num_env_runners,
            num_envs_per_runner=config.num_envs_per_env_runner,
            rollout_fragment_length=config.rollout_fragment_length,
            seed=config.seed,
            env_to_module=config.env_to_module_connector,
            module_to_env=config.module_to_env_connector,
        )

    def _init_multi_agent(self, config: AlgorithmConfig) -> None:
        from ray_tpu.rllib.core.learner import MultiAgentLearnerGroup
        from ray_tpu.rllib.core.multi_rl_module import MultiRLModuleSpec
        from ray_tpu.rllib.env.multi_agent_env_runner import (
            MultiAgentEnvRunner,
        )

        if isinstance(config.env, str):
            raise ValueError(
                "multi-agent config.env must be a MultiAgentEnv class or "
                "factory, not a gym id"
            )
        probe = config.env(config.env_config)
        obs_spaces: dict = {}
        act_spaces: dict = {}
        for agent in probe.possible_agents:
            mid = config.policy_mapping_fn(agent)
            if mid not in config.policies:
                raise ValueError(
                    f"policy_mapping_fn({agent!r}) → {mid!r} which is not in "
                    f"config.policies {sorted(config.policies)}"
                )
            obs_spaces.setdefault(mid, probe.get_observation_space(agent))
            act_spaces.setdefault(mid, probe.get_action_space(agent))
        probe.close()
        # module ids with no agent mapped to them would have no spaces
        missing = set(config.policies) - set(obs_spaces)
        if missing:
            raise ValueError(f"no agent maps to policies {sorted(missing)}")
        self.observation_space = obs_spaces
        self.action_space = act_spaces
        self.module_observation_space = obs_spaces

        multi_spec = MultiRLModuleSpec(
            {
                mid: (
                    spec
                    or RLModuleSpec(model_config=dict(config.model))
                )
                for mid, spec in config.policies.items()
            }
        )
        self._multi_spec = multi_spec
        self.learner_group = MultiAgentLearnerGroup(
            self.learner_class,
            multi_spec,
            obs_spaces,
            act_spaces,
            self._learner_config(),
        )
        env_cls, env_config = config.env, dict(config.env_config)

        def creator():
            return env_cls(env_config)

        self.env_runner_group = EnvRunnerGroup(
            creator,
            multi_spec,
            num_env_runners=config.num_env_runners,
            num_envs_per_runner=1,
            rollout_fragment_length=config.rollout_fragment_length,
            seed=config.seed,
            env_to_module=config.env_to_module_connector,
            module_to_env=config.module_to_env_connector,
            runner_class=MultiAgentEnvRunner,
            runner_kwargs={"policy_mapping_fn": config.policy_mapping_fn},
        )

    def _env_creator(self):
        config = self.config

        if isinstance(config.env, str):
            env_id = config.env
            env_config = dict(config.env_config)

            def creator(num_envs: int):
                return gym.make_vec(env_id, num_envs=num_envs, **env_config)

            return creator
        return config.env

    def _learner_config(self) -> dict:
        return self.config.learner_config_dict()

    # -- the iteration ---------------------------------------------------
    def training_step(self) -> dict:
        raise NotImplementedError

    def train(self) -> dict:
        if not hasattr(self, "metrics"):
            # offline algorithms (BC/MARWIL/CQL) build their own __init__
            self.metrics = MetricsLogger()
        steps_before = self._total_env_steps
        metrics = self.training_step() or {}
        self.iteration += 1
        runner_metrics = self.env_runner_group.get_metrics()
        result = {
            "training_iteration": self.iteration,
            "num_env_steps_sampled_lifetime": self._total_env_steps,
            "time_total_s": time.time() - self._start,
            "env_runners": runner_metrics,
            **{f"learner/{k}": v for k, v in metrics.items()},
        }
        result["episode_return_mean"] = runner_metrics.get(
            "episode_return_mean", np.nan
        )
        # Windowed aggregation (rllib/utils/metrics :: MetricsLogger
        # role): sliding-window return stats, learner-loss windows, and
        # sampling throughput ride every result under "metrics".
        self.metrics.log_throughput(
            "num_env_steps_sampled", self._total_env_steps - steps_before
        )
        ret = result["episode_return_mean"]
        if not np.isnan(ret):
            self.metrics.log_value("episode_return", float(ret))
        self.metrics.log_dict(metrics, prefix="learner_")
        result["metrics"] = self.metrics.reduce()
        if (
            self.config.evaluation_interval
            and self.iteration % self.config.evaluation_interval == 0
        ):
            result["evaluation"] = self.evaluate()
        return result

    # tune.Trainable duck-type
    def step(self) -> dict:
        return self.train()

    def evaluate(self) -> dict:
        """Greedy episodes on a fresh env (evaluation duck-type of the
        reference's evaluation workers)."""
        if self.config.is_multi_agent:
            return self._evaluate_multi_agent()
        env = (
            gym.make(self.config.env, **self.config.env_config)
            if isinstance(self.config.env, str)
            else self.config.env(self.config.env_config)
        )
        spec = self.config.rl_module_spec or RLModuleSpec(
            model_config=dict(self.config.model)
        )
        # Params are shaped for the CONNECTOR's output space; evaluation
        # must run observations through the same pipeline the runners use.
        module = spec.build(
            getattr(self, "module_observation_space", self.observation_space),
            self.action_space,
        )
        from ray_tpu.rllib.connectors import default_env_to_module

        import jax

        params = self.learner_group.get_weights()
        fwd = jax.jit(module.forward_inference)
        # Running statistics (NormalizeObservations) must come from
        # training — a fresh normalizer would map early eval observations
        # to ~0, a distribution the trained policy never saw.
        connector_state = self.env_runner_group.get_connector_state()
        returns = []
        for _ in range(self.config.evaluation_duration):
            # Fresh pipeline per episode: stateful connectors (framestack)
            # must not carry history across episode boundaries —
            # get_state() excludes per-episode history, so restoring it
            # here only seeds the running statistics.
            pipeline = (
                self.config.env_to_module_connector()
                if self.config.env_to_module_connector
                else default_env_to_module()
            )
            if connector_state:
                pipeline.set_state(connector_state)
            obs, _ = env.reset()
            total, done = 0.0, False
            stateful = getattr(module, "is_stateful", False)
            state = module.initial_state(1) if stateful else None
            while not done:
                module_obs = pipeline(np.asarray(obs)[None])
                if stateful:
                    action_arr, state = fwd(params, module_obs, state)
                else:
                    action_arr = fwd(params, module_obs)
                action = np.asarray(action_arr)[0]
                obs, reward, term, trunc, _ = env.step(
                    action.item() if action.shape == () else action
                )
                total += reward
                done = term or trunc
            returns.append(total)
        env.close()
        return {
            "episode_return_mean": float(np.mean(returns)),
            "num_episodes": len(returns),
        }

    def _evaluate_multi_agent(self) -> dict:
        import jax

        env = self.config.env(self.config.env_config)
        modules = {
            mid: self._multi_spec.module_specs[mid].build(
                self.observation_space[mid], self.action_space[mid]
            )
            for mid in self.config.policies
        }
        fwd = {
            mid: jax.jit(m.forward_inference) for mid, m in modules.items()
        }
        params = self.learner_group.get_weights()
        mapping = self.config.policy_mapping_fn
        returns = []
        for _ in range(self.config.evaluation_duration):
            obs, _ = env.reset()
            total, done = 0.0, False
            while not done and obs:
                actions = {}
                for agent, o in obs.items():
                    mid = mapping(agent)
                    a = np.asarray(
                        fwd[mid](
                            params[mid],
                            np.asarray(o, dtype=np.float32).reshape(1, -1),
                        )
                    )[0]
                    actions[agent] = a.item() if a.shape == () else a
                obs, rewards, terms, truncs, _ = env.step(actions)
                total += sum(rewards.values())
                done = terms.get("__all__", False) or truncs.get(
                    "__all__", False
                )
                obs = {
                    a: o
                    for a, o in obs.items()
                    if not (terms.get(a, False) or truncs.get(a, False))
                }
            returns.append(total)
        env.close()
        return {
            "episode_return_mean": float(np.mean(returns)),
            "num_episodes": len(returns),
        }

    # -- checkpointing ----------------------------------------------------
    def save(self, checkpoint_dir: str | None = None) -> str:
        checkpoint_dir = checkpoint_dir or os.path.join(
            os.path.expanduser("~/ray_tpu_results"),
            f"{type(self).__name__.lower()}_ckpt_{self.iteration}",
        )
        os.makedirs(checkpoint_dir, exist_ok=True)
        state = {
            "learner": self.learner_group.get_state(),
            "iteration": self.iteration,
            "total_env_steps": self._total_env_steps,
            "config": self.config.to_dict(),
            "algo_class": type(self).__name__,
        }
        atomic_io.atomic_write_pickle(
            os.path.join(checkpoint_dir, "algorithm_state.pkl"), state
        )
        return checkpoint_dir

    def restore(self, checkpoint_dir: str) -> None:
        with open(os.path.join(checkpoint_dir, "algorithm_state.pkl"), "rb") as f:
            state = pickle.load(f)
        self.learner_group.set_state(state["learner"])
        self.iteration = state["iteration"]
        self._total_env_steps = state["total_env_steps"]
        self.env_runner_group.sync_weights(self.learner_group.get_weights())

    @classmethod
    def from_checkpoint(cls, checkpoint_dir: str, config: AlgorithmConfig):
        algo = config.build_algo()
        algo.restore(checkpoint_dir)
        return algo

    # tune.Trainable duck-type
    def save_checkpoint(self) -> Any:
        state = {
            "learner": self.learner_group.get_state(),
            "iteration": self.iteration,
            "total_env_steps": self._total_env_steps,
        }
        return pickle.dumps(state)

    def load_checkpoint(self, blob: Any) -> None:
        state = pickle.loads(blob)
        self.learner_group.set_state(state["learner"])
        self.iteration = state["iteration"]
        self._total_env_steps = state["total_env_steps"]
        self.env_runner_group.sync_weights(self.learner_group.get_weights())

    def stop(self) -> None:
        self.env_runner_group.stop()
        self.learner_group.stop()

"""APPO — asynchronous PPO: IMPALA's pipeline + PPO's clipped surrogate.

Role-equivalent of rllib/algorithms/appo/appo.py (SURVEY §2.8): env
runners sample continuously (the IMPALA async harvest), V-trace corrects
the off-policyness of stale fragments, and the policy update applies the
PPO clipped surrogate over the V-trace advantages instead of IMPALA's
plain policy gradient — bounded-step updates on an asynchronous data
path. The whole SGD step remains one jitted XLA function.
"""

from __future__ import annotations

import jax.numpy as jnp

from ray_tpu.rllib.algorithms.impala.impala import (
    IMPALA, IMPALAConfig, IMPALALearner, vtrace,
)
from ray_tpu.rllib.policy.sample_batch import (
    ACTION_LOGP, ACTIONS, OBS, REWARDS, TERMINATEDS, TRUNCATEDS,
)


class APPOConfig(IMPALAConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or APPO)
        self.clip_param: float = 0.3
        self.lr = 5e-4


class APPOLearner(IMPALALearner):
    def compute_loss(self, params, batch: dict):
        cfg = self.config
        logp, entropy, vf = self.module.action_logp(
            params, batch[OBS], batch[ACTIONS]
        )
        done = jnp.logical_or(batch[TERMINATEDS], batch[TRUNCATEDS])
        discounts = cfg.get("gamma", 0.99) * (1.0 - done.astype(jnp.float32))
        vs, pg_adv = vtrace(
            batch[ACTION_LOGP],
            logp,
            batch[REWARDS],
            vf,
            batch["bootstrap_value"][0],
            discounts,
            cfg.get("clip_rho_threshold", 1.0),
            cfg.get("clip_c_threshold", 1.0),
        )
        # PPO clipped surrogate over the V-trace advantages (the APPO
        # twist: bounded policy steps on asynchronous data).
        clip = cfg.get("clip_param", 0.3)
        ratio = jnp.exp(logp - batch[ACTION_LOGP])
        surrogate = jnp.minimum(
            ratio * pg_adv,
            jnp.clip(ratio, 1.0 - clip, 1.0 + clip) * pg_adv,
        )
        policy_loss = -jnp.mean(surrogate)
        vf_loss = 0.5 * jnp.mean((vf - vs) ** 2)
        entropy_mean = jnp.mean(entropy)
        total = (
            policy_loss
            + cfg.get("vf_loss_coeff", 0.5) * vf_loss
            - cfg.get("entropy_coeff", 0.01) * entropy_mean
        )
        return total, {
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "entropy": entropy_mean,
            "mean_ratio": jnp.mean(ratio),
        }


class APPO(IMPALA):
    learner_class = APPOLearner

    def _learner_config(self) -> dict:
        cfg = super()._learner_config()
        cfg.update(clip_param=self.config.clip_param)
        return cfg

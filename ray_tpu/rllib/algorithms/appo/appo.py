"""APPO — asynchronous PPO: IMPALA's pipeline + PPO's clipped surrogate.

Role-equivalent of rllib/algorithms/appo/appo.py + appo_learner (SURVEY
§2.8): env runners sample continuously (the IMPALA async harvest),
V-trace corrects the off-policyness of stale fragments, and the policy
update applies the PPO clipped surrogate over the V-trace advantages —
bounded-step updates on an asynchronous data path. The reference APPO's
stabilizers are both here:

  * a TARGET NETWORK — a periodically-synced copy of the policy
    (``target_network_update_freq`` updates per hard sync) that anchors
    the KL regularizer, so many async minibatch steps cannot drift the
    policy arbitrarily far between syncs;
  * an ADAPTIVE KL LOSS (``use_kl_loss``/``kl_coeff``/``kl_target``) —
    KL(target || current) joins the loss; the coefficient grows 1.5x
    when measured KL exceeds 2x target and halves below 0.5x target
    (the reference's adaptive schedule). In multi-learner DP mode the
    KL loss and target sync stay active on the gradient path, but the
    coefficient keeps its configured value (per-shard metrics don't
    flow back there).

The whole SGD step remains one jitted XLA function: the target
network's distribution is computed by a separate jitted forward and
rides the batch as constants, so the generic Learner step signature
(params, opt_state, batch) is unchanged.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ray_tpu.rllib.algorithms.impala.impala import (
    IMPALA, IMPALAConfig, IMPALALearner, vtrace,
)
from ray_tpu.rllib.policy.sample_batch import (
    ACTION_LOGP, ACTIONS, OBS, REWARDS, TERMINATEDS, TRUNCATEDS,
)


class APPOConfig(IMPALAConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or APPO)
        self.clip_param: float = 0.3
        self.lr = 5e-4
        self.use_kl_loss: bool = True
        self.kl_coeff: float = 0.2
        self.kl_target: float = 0.01
        self.target_network_update_freq: int = 4  # learner updates / sync


class APPOLearner(IMPALALearner):
    def __init__(self, module, config: dict, seed: int = 0):
        super().__init__(module, config, seed)
        self._use_kl = bool(config.get("use_kl_loss", True))
        self._updates_since_sync = 0
        self._kl_coeff = float(config.get("kl_coeff", 0.2))
        if self._use_kl:
            self.target_params = jax.tree_util.tree_map(jnp.copy, self.params)
            self._target_fwd = jax.jit(self.module.forward_train)
        else:
            self.target_params = None
            self._target_fwd = None

    def _inject_target(self, batch) -> None:
        """Attach the target network's distribution (and the current KL
        coefficient) to the batch as constants — shared by the local
        update() and the DP-mode compute_gradients() path, so the KL
        regularizer is active under both."""
        target_out = self._target_fwd(self.target_params, batch[OBS])
        if "logits" in target_out:
            batch["target_logits"] = target_out["logits"]
        else:
            batch["target_mean"] = target_out["mean"]
            batch["target_log_std"] = target_out["log_std"]
        batch["kl_coeff"] = jnp.full((1,), self._kl_coeff)

    def _maybe_sync_target(self) -> None:
        self._updates_since_sync += 1
        if self._updates_since_sync >= self.config.get(
            "target_network_update_freq", 4
        ):
            self._updates_since_sync = 0
            self.target_params = jax.tree_util.tree_map(
                jnp.copy, self.params
            )

    def compute_loss(self, params, batch: dict):
        cfg = self.config
        logp, entropy, vf = self.module.action_logp(
            params, batch[OBS], batch[ACTIONS]
        )
        done = jnp.logical_or(batch[TERMINATEDS], batch[TRUNCATEDS])
        discounts = cfg.get("gamma", 0.99) * (1.0 - done.astype(jnp.float32))
        vs, pg_adv = vtrace(
            batch[ACTION_LOGP],
            logp,
            batch[REWARDS],
            vf,
            batch["bootstrap_value"][0],
            discounts,
            cfg.get("clip_rho_threshold", 1.0),
            cfg.get("clip_c_threshold", 1.0),
        )
        # PPO clipped surrogate over the V-trace advantages (the APPO
        # twist: bounded policy steps on asynchronous data).
        clip = cfg.get("clip_param", 0.3)
        ratio = jnp.exp(logp - batch[ACTION_LOGP])
        surrogate = jnp.minimum(
            ratio * pg_adv,
            jnp.clip(ratio, 1.0 - clip, 1.0 + clip) * pg_adv,
        )
        policy_loss = -jnp.mean(surrogate)
        vf_loss = 0.5 * jnp.mean((vf - vs) ** 2)
        entropy_mean = jnp.mean(entropy)
        total = (
            policy_loss
            + cfg.get("vf_loss_coeff", 0.5) * vf_loss
            - cfg.get("entropy_coeff", 0.01) * entropy_mean
        )
        metrics = {
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "entropy": entropy_mean,
            "mean_ratio": jnp.mean(ratio),
        }
        if "target_logits" in batch:
            # KL(target || current) over the batch states (discrete)
            current = self.module.forward_train(params, batch[OBS])
            p_t = jax.nn.softmax(batch["target_logits"])
            kl = jnp.mean(
                jnp.sum(
                    p_t
                    * (
                        jax.nn.log_softmax(batch["target_logits"])
                        - jax.nn.log_softmax(current["logits"])
                    ),
                    axis=-1,
                )
            )
            total = total + batch["kl_coeff"][0] * kl
            metrics["kl"] = kl
        elif "target_mean" in batch:
            # diagonal-gaussian KL(target || current)
            current = self.module.forward_train(params, batch[OBS])
            t_mean, t_log_std = batch["target_mean"], batch["target_log_std"]
            c_mean, c_log_std = current["mean"], current["log_std"]
            kl = jnp.mean(
                jnp.sum(
                    c_log_std
                    - t_log_std
                    + (
                        jnp.exp(2 * t_log_std)
                        + (t_mean - c_mean) ** 2
                    ) / (2 * jnp.exp(2 * c_log_std))
                    - 0.5,
                    axis=-1,
                )
            )
            total = total + batch["kl_coeff"][0] * kl
            metrics["kl"] = kl
        return total, metrics

    def update(self, batch) -> dict:
        cfg = self.config
        if self._use_kl:
            # target distribution as batch constants (computed by a
            # separate jitted forward — the main step signature stays
            # (params, opt_state, batch))
            self._inject_target(batch)
        metrics = super().update(batch)
        if "kl" in metrics:
            # reference adaptive schedule: grow 1.5x / halve outside the
            # [0.5, 2] x target band
            kl = metrics["kl"]
            target = cfg.get("kl_target", 0.01)
            if kl > 2.0 * target:
                self._kl_coeff = min(self._kl_coeff * 1.5, 1e3)
            elif kl < 0.5 * target:
                self._kl_coeff = max(self._kl_coeff * 0.5, 1e-6)
            metrics["kl_coeff"] = self._kl_coeff
        if self._use_kl:
            self._maybe_sync_target()
        return metrics

    # DP mode (num_learners >= 2): shards flow through
    # compute_gradients/apply_gradients, not update() — keep the KL
    # regularizer and target sync active on that path too.
    def compute_gradients(self, batch):
        if self._use_kl:
            self._inject_target(batch)
        return super().compute_gradients(batch)

    def apply_gradients(self, grads) -> None:
        super().apply_gradients(grads)
        if self._use_kl:
            self._maybe_sync_target()

    def get_state(self) -> dict:
        state = super().get_state()
        if self._use_kl:
            state["target_params"] = jax.device_get(self.target_params)
        state["kl_coeff"] = self._kl_coeff
        state["updates_since_sync"] = self._updates_since_sync
        return state

    def set_state(self, state: dict) -> None:
        super().set_state(state)
        if self._use_kl:
            if "target_params" in state:
                self.target_params = jax.device_put(state["target_params"])
            else:
                # base-Learner-shaped checkpoint: anchor the target to the
                # restored params rather than keeping fresh-init values
                # (which would read as a huge KL until the first sync)
                self.target_params = jax.tree_util.tree_map(
                    jnp.copy, self.params
                )
        self._kl_coeff = float(state.get("kl_coeff", self._kl_coeff))
        self._updates_since_sync = int(state.get("updates_since_sync", 0))


class APPO(IMPALA):
    learner_class = APPOLearner

    def _learner_config(self) -> dict:
        cfg = super()._learner_config()
        cfg.update(
            clip_param=self.config.clip_param,
            use_kl_loss=self.config.use_kl_loss,
            kl_coeff=self.config.kl_coeff,
            kl_target=self.config.kl_target,
            target_network_update_freq=self.config.target_network_update_freq,
        )
        return cfg

"""AlgorithmConfig — the fluent, validated config object.

Role-equivalent of rllib/algorithms/algorithm_config.py :: AlgorithmConfig
(SURVEY §2.8): chained .environment().env_runners().training().learners()
 .evaluation() setters, .build_algo() to construct the Algorithm. Copyable
and serializable; algorithm subclasses extend `training()` kwargs.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Optional, Type


def default_policy_mapping_fn(agent_id, *args, **kwargs) -> str:
    """Single-module default: every agent maps to 'default_policy'."""
    return "default_policy"


class AlgorithmConfig:
    def __init__(self, algo_class: Optional[Type] = None):
        self.algo_class = algo_class
        # environment
        self.env: Any = None
        self.env_config: dict = {}
        # env runners
        self.num_env_runners: int = 2
        self.num_envs_per_env_runner: int = 1
        self.rollout_fragment_length: int = 200
        self.explore: bool = True
        # training (common)
        self.gamma: float = 0.99
        self.lr: float = 5e-4
        self.train_batch_size: int = 4000
        self.grad_clip: float = 40.0
        self.model: dict = {"fcnet_hiddens": (256, 256)}
        # learners
        self.num_learners: int = 0
        self.num_tpus_per_learner: int = 0
        # evaluation
        self.evaluation_interval: int = 0
        self.evaluation_duration: int = 5
        # connectors (ConnectorV2 pipelines; factories so every runner /
        # learner builds its own stateful instance)
        self.env_to_module_connector: Optional[Callable] = None
        self.module_to_env_connector: Optional[Callable] = None
        self.learner_connector: Optional[Callable] = None
        # multi-agent (reference: config.multi_agent(policies=...,
        # policy_mapping_fn=...)). ``policies`` maps module_id → None
        # (infer spaces from the env) or an RLModuleSpec.
        self.policies: Optional[dict] = None
        self.policy_mapping_fn: Callable = default_policy_mapping_fn
        # reproducibility
        self.seed: Optional[int] = None
        # RLModule override
        self.rl_module_spec = None

    # -- fluent setters --------------------------------------------------
    def environment(self, env: Any = None, *, env_config: dict | None = None):
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = dict(env_config)
        return self

    def env_runners(
        self,
        *,
        num_env_runners: int | None = None,
        num_envs_per_env_runner: int | None = None,
        rollout_fragment_length: int | None = None,
        explore: bool | None = None,
        env_to_module_connector: Callable | None = None,
        module_to_env_connector: Callable | None = None,
    ):
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if explore is not None:
            self.explore = explore
        if env_to_module_connector is not None:
            self.env_to_module_connector = env_to_module_connector
        if module_to_env_connector is not None:
            self.module_to_env_connector = module_to_env_connector
        return self

    def multi_agent(
        self,
        *,
        policies: dict | None = None,
        policy_mapping_fn: Callable | None = None,
    ):
        if policies is not None:
            # Accept {"p0", "p1"} set/list or {"p0": spec_or_None} dict.
            if isinstance(policies, (set, list, tuple)):
                policies = {p: None for p in policies}
            self.policies = dict(policies)
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self

    @property
    def is_multi_agent(self) -> bool:
        return self.policies is not None

    def training(self, **kwargs):
        for key, value in kwargs.items():
            if not hasattr(self, key):
                raise ValueError(f"unknown training option {key!r}")
            setattr(self, key, value)
        return self

    def learners(
        self,
        *,
        num_learners: int | None = None,
        num_tpus_per_learner: int | None = None,
    ):
        if num_learners is not None:
            self.num_learners = num_learners
        if num_tpus_per_learner is not None:
            self.num_tpus_per_learner = num_tpus_per_learner
        return self

    def evaluation(
        self,
        *,
        evaluation_interval: int | None = None,
        evaluation_duration: int | None = None,
    ):
        if evaluation_interval is not None:
            self.evaluation_interval = evaluation_interval
        if evaluation_duration is not None:
            self.evaluation_duration = evaluation_duration
        return self

    def rl_module(self, *, rl_module_spec=None, model_config: dict | None = None):
        if rl_module_spec is not None:
            self.rl_module_spec = rl_module_spec
        if model_config is not None:
            self.model.update(model_config)
        return self

    def debugging(self, *, seed: Optional[int] = None):
        if seed is not None:
            self.seed = seed
        return self

    # -- materialization -------------------------------------------------
    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def validate(self) -> None:
        if self.env is None:
            raise ValueError("config.environment(env=...) is required")
        if self.train_batch_size <= 0:
            raise ValueError("train_batch_size must be positive")

    def learner_config_dict(self) -> dict:
        return {
            "lr": self.lr,
            "gamma": self.gamma,
            "grad_clip": self.grad_clip,
        }

    def build_algo(self):
        if self.algo_class is None:
            raise ValueError("no algorithm class bound to this config")
        from ray_tpu._private import usage

        usage.record_feature("rllib")
        self.validate()
        return self.algo_class(self.copy())

    # reference alias
    build = build_algo

    def to_dict(self) -> dict:
        out = {}
        for key, value in self.__dict__.items():
            if key in ("algo_class", "rl_module_spec"):
                continue
            out[key] = value
        return out

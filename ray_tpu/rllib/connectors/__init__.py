from ray_tpu.rllib.connectors.connector import (
    ClipActions,
    ConnectorPipelineV2,
    ConnectorV2,
    FlattenObservations,
    FrameStack,
    GeneralAdvantageEstimation,
    LambdaConnector,
    NormalizeObservations,
    default_env_to_module,
    default_module_to_env,
)

__all__ = [
    "ClipActions",
    "ConnectorPipelineV2",
    "ConnectorV2",
    "FlattenObservations",
    "FrameStack",
    "GeneralAdvantageEstimation",
    "LambdaConnector",
    "NormalizeObservations",
    "default_env_to_module",
    "default_module_to_env",
]

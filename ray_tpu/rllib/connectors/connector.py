"""ConnectorV2 — composable observation/action/learner pipelines.

Role-equivalent of rllib/connectors/ :: ConnectorV2 and the per-role
pipelines (env→module, module→env, learner) from SURVEY §2.8. A connector
is a pure callable over a batch dict; pipelines compose them in order.
Env runners run the env→module pipeline on raw observations before the
module forward and the module→env pipeline on sampled actions before
``env.step``; algorithms run the learner pipeline (e.g. GAE) on collected
SampleBatches before the jitted update.

Connectors are plain Python/numpy on the rollout path (CPU-side, outside
jit) — the learner connector's output feeds the XLA update, so it must
produce static-shape arrays.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional

import numpy as np

from ray_tpu.rllib.policy.sample_batch import (
    ADVANTAGES, SampleBatch, VALUE_TARGETS,
)


class ConnectorV2:
    """One stage of a pipeline. Subclasses override __call__.

    ``batch`` is a dict (raw obs / action dicts on the env paths, a
    SampleBatch on the learner path). Extra context arrives as kwargs:
    ``module``, ``params``, ``spaces``, ``value_fn`` — connectors take
    what they need and ignore the rest.
    """

    # Stateful connectors carry per-stream state (framestacks, running
    # normalizers): callers that would need to run a batch through the
    # pipeline more than once per step must check this.
    stateful: bool = False

    def __call__(self, batch: Any, **kwargs) -> Any:
        raise NotImplementedError

    def get_state(self) -> dict:
        """Cross-episode state worth syncing between pipelines (running
        statistics). Per-episode state (framestack history) stays out."""
        return {}

    def set_state(self, state: dict) -> None:
        pass

    @property
    def name(self) -> str:
        return type(self).__name__


class ConnectorPipelineV2(ConnectorV2):
    """Ordered composition; also the container API (append/prepend/insert)."""

    def __init__(self, connectors: Iterable[ConnectorV2] = ()):  # noqa: D401
        self.connectors: list[ConnectorV2] = list(connectors)

    def __call__(self, batch: Any, **kwargs) -> Any:
        for connector in self.connectors:
            batch = connector(batch, **kwargs)
        return batch

    def append(self, connector: ConnectorV2) -> "ConnectorPipelineV2":
        self.connectors.append(connector)
        return self

    def prepend(self, connector: ConnectorV2) -> "ConnectorPipelineV2":
        self.connectors.insert(0, connector)
        return self

    def remove(self, name: str) -> "ConnectorPipelineV2":
        self.connectors = [c for c in self.connectors if c.name != name]
        return self

    def __getitem__(self, idx: int) -> ConnectorV2:
        return self.connectors[idx]

    def __len__(self) -> int:
        return len(self.connectors)

    @property
    def stateful(self) -> bool:  # type: ignore[override]
        return any(c.stateful for c in self.connectors)

    def get_state(self) -> dict:
        return {
            i: state
            for i, c in enumerate(self.connectors)
            if (state := c.get_state())
        }

    def set_state(self, state: dict) -> None:
        for i, sub in state.items():
            idx = int(i)
            if 0 <= idx < len(self.connectors):
                self.connectors[idx].set_state(sub)


# ---------------------------------------------------------------------------
# env → module
# ---------------------------------------------------------------------------
class FlattenObservations(ConnectorV2):
    """[B, ...] observations → [B, prod(...)] float32 (fcnet input).

    Image observations ([B, H, W, C]) pass through UNCHANGED — the vision
    net consumes them as pixels (and uint8 stays uint8 until the module's
    in-jit normalize), matching the reference where the flattener serves
    the fcnet path and conv inputs bypass it."""

    def __call__(self, batch, **kwargs):
        obs = np.asarray(batch)
        if obs.ndim >= 4:  # [B, H, W, C]: conv input, keep shape + dtype
            return obs
        return obs.reshape(obs.shape[0], -1).astype(np.float32, copy=False)


class NormalizeObservations(ConnectorV2):
    """Running mean/std normalization (per-runner statistics)."""

    stateful = True

    def __init__(self, epsilon: float = 1e-8, clip: float = 10.0):
        self.count = epsilon
        self.mean: Optional[np.ndarray] = None
        self.var: Optional[np.ndarray] = None
        self.clip = clip

    def __call__(self, batch, **kwargs):
        obs = np.asarray(batch, dtype=np.float32)
        flat = obs.reshape(obs.shape[0], -1)
        if self.mean is None:
            self.mean = np.zeros(flat.shape[1], dtype=np.float64)
            self.var = np.ones(flat.shape[1], dtype=np.float64)
        batch_mean = flat.mean(axis=0)
        batch_var = flat.var(axis=0)
        batch_count = flat.shape[0]
        delta = batch_mean - self.mean
        total = self.count + batch_count
        self.mean = self.mean + delta * batch_count / total
        m_a = self.var * self.count
        m_b = batch_var * batch_count
        m2 = m_a + m_b + delta**2 * self.count * batch_count / total
        self.var = m2 / total
        self.count = total
        normalized = (flat - self.mean) / np.sqrt(self.var + 1e-8)
        return np.clip(normalized, -self.clip, self.clip).astype(np.float32)

    def get_state(self) -> dict:
        if self.mean is None:
            return {}
        return {
            "count": float(self.count),
            "mean": self.mean.copy(),
            "var": self.var.copy(),
        }

    def set_state(self, state: dict) -> None:
        if not state:
            return
        self.count = state["count"]
        self.mean = np.asarray(state["mean"], dtype=np.float64).copy()
        self.var = np.asarray(state["var"], dtype=np.float64).copy()


class FrameStack(ConnectorV2):
    """Stacks the last N observations along the feature axis.

    Episode boundaries: callers pass ``dones`` (bool mask per batch row of
    the PREVIOUS step) so a finished env's history is zeroed before its
    reset observation enters the stack — otherwise the first frames of a
    new episode would be stacked with the previous (dead) episode's tail.
    The env runner wires this automatically; a pipeline reused across
    episodes without dones (e.g. a hand-rolled eval loop) should call
    ``reset()`` between episodes.
    """

    stateful = True

    def __init__(self, num_frames: int = 4):
        self.num_frames = num_frames
        self._stack: list[np.ndarray] = []

    def reset(self) -> None:
        self._stack = []

    def __call__(self, batch, *, dones=None, **kwargs):
        obs = np.asarray(batch, dtype=np.float32)
        flat = obs.reshape(obs.shape[0], -1)
        if dones is not None and self._stack:
            done_idx = np.nonzero(np.asarray(dones))[0]
            if len(done_idx):
                for frame in self._stack:
                    frame[done_idx] = 0.0
        self._stack.append(flat)
        if len(self._stack) > self.num_frames:
            self._stack.pop(0)
        while len(self._stack) < self.num_frames:
            self._stack.insert(0, np.zeros_like(flat))
        return np.concatenate(self._stack, axis=-1)


# ---------------------------------------------------------------------------
# module → env
# ---------------------------------------------------------------------------
class ClipActions(ConnectorV2):
    """Clip continuous actions into the env's Box bounds (no-op discrete)."""

    def __call__(self, batch, *, action_space=None, **kwargs):
        if action_space is None or not hasattr(action_space, "low"):
            return batch
        return np.clip(
            np.asarray(batch), action_space.low, action_space.high
        )


# ---------------------------------------------------------------------------
# learner
# ---------------------------------------------------------------------------
class GeneralAdvantageEstimation(ConnectorV2):
    """GAE as a learner connector (reference: connectors/learner/
    general_advantage_estimation.py). Wraps the pure-numpy pass in
    utils/postprocessing.py; ``value_fn`` arrives from the algorithm."""

    def __init__(
        self, gamma: float = 0.99, lambda_: float = 0.95,
        standardize: bool = True,
    ):
        self.gamma = gamma
        self.lambda_ = lambda_
        self.standardize = standardize

    def __call__(self, batch: SampleBatch, *, value_fn=None, **kwargs):
        from ray_tpu.rllib.utils.postprocessing import compute_gae

        if ADVANTAGES in batch and VALUE_TARGETS in batch:
            return batch
        return compute_gae(
            batch,
            gamma=self.gamma,
            lambda_=self.lambda_,
            value_fn=value_fn,
            standardize=self.standardize,
        )


class LambdaConnector(ConnectorV2):
    """Wrap a plain function as a connector stage."""

    def __init__(self, fn: Callable, name: str | None = None):
        self.fn = fn
        self._name = name or getattr(fn, "__name__", "LambdaConnector")

    def __call__(self, batch, **kwargs):
        return self.fn(batch, **kwargs)

    @property
    def name(self) -> str:
        return self._name


def default_env_to_module() -> ConnectorPipelineV2:
    return ConnectorPipelineV2([FlattenObservations()])


def default_module_to_env() -> ConnectorPipelineV2:
    return ConnectorPipelineV2([ClipActions()])

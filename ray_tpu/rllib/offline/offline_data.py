"""OfflineData — dataset-backed training input.

Role-equivalent of rllib/offline/ :: OfflineData (and the legacy
JsonReader) from SURVEY §2.8: experience comes from a ray_tpu.data
Dataset (or a parquet/json path read through it) instead of env runners.
Rows are per-timestep records with SampleBatch column names ("obs",
"actions", optionally "rewards", "new_obs", "terminateds", "action_logp").
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ray_tpu.rllib.policy.sample_batch import SampleBatch


class OfflineData:
    def __init__(self, source: Any, shuffle_seed: int | None = 0):
        self._batch = self._load(source)
        self._rng = np.random.default_rng(shuffle_seed)
        self._order = np.arange(len(self._batch))
        self._cursor = len(self._batch)  # force shuffle on first sample

    @staticmethod
    def _load(source: Any) -> SampleBatch:
        if isinstance(source, SampleBatch):
            return source
        if isinstance(source, dict):
            return SampleBatch(source)
        if isinstance(source, str):
            from ray_tpu import data as rt_data

            if source.endswith(".json") or source.endswith(".jsonl"):
                dataset = rt_data.read_json(source)
            else:
                dataset = rt_data.read_parquet(source)
            return OfflineData._rows_to_batch(dataset.take_all())
        if hasattr(source, "take_all"):  # ray_tpu.data.Dataset
            return OfflineData._rows_to_batch(source.take_all())
        raise TypeError(f"unsupported offline input: {type(source)!r}")

    @staticmethod
    def _rows_to_batch(rows: list[dict]) -> SampleBatch:
        if not rows:
            raise ValueError("offline dataset is empty")
        cols: dict[str, list] = {k: [] for k in rows[0]}
        for row in rows:
            for key, value in row.items():
                cols[key].append(value)
        return SampleBatch({k: np.asarray(v) for k, v in cols.items()})

    def __len__(self) -> int:
        return len(self._batch)

    @property
    def columns(self):
        return self._batch.keys()

    def sample(self, batch_size: int) -> SampleBatch:
        """Epoch-shuffled minibatch (reshuffles when the epoch wraps)."""
        if self._cursor + batch_size > len(self._order):
            self._rng.shuffle(self._order)
            self._cursor = 0
        idx = self._order[self._cursor : self._cursor + batch_size]
        self._cursor += batch_size
        return SampleBatch({k: v[idx] for k, v in self._batch.items()})

from ray_tpu.rllib.offline.offline_data import OfflineData

__all__ = ["OfflineData"]

"""RLModule — the framework-native policy/value network.

Role-equivalent of rllib/core/rl_module/rl_module.py :: RLModule (and
torch/torch_rl_module.py) re-designed for jax (SURVEY §2.8, §3.5): a pure
function suite over a params pytree — forward_inference (greedy),
forward_exploration (sample + logp), forward_train (logits + values) —
so the learner can jit the whole update and env runners call the same
functions on CPU. `MLPModule` is the default catalog net (fcnet-equivalent
of rllib/models :: ModelCatalog).
"""

from __future__ import annotations

from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np


class RLModuleSpec:
    def __init__(self, module_class=None, model_config: dict | None = None):
        self.model_config = dict(model_config or {})
        if module_class is None:
            # Catalog selection (reference ModelCatalog: use_lstm flag,
            # conv_filters pick the vision net). Image-shaped observation
            # spaces also select the vision net, but the space is only
            # known at build() — module_class stays None until then.
            if self.model_config.get("use_lstm"):
                module_class = LSTMModule
            elif self.model_config.get("conv_filters"):
                module_class = ConvModule
        self.module_class = module_class

    def build(self, observation_space, action_space) -> "RLModule":
        module_class = self.module_class
        if module_class is None:
            shape = getattr(observation_space, "shape", None)
            # Auto-route image-SHAPED spaces to the vision net only when
            # the default filter stack fits (min spatial dim >= 10 for the
            # small stack); tiny 3-D obs keep training via MLP flatten as
            # before. Explicit conv_filters always force ConvModule.
            module_class = (
                ConvModule
                if shape is not None and len(shape) == 3
                and min(int(shape[0]), int(shape[1])) >= 10
                else MLPModule
            )
        return module_class(
            observation_space, action_space, self.model_config
        )


class RLModule:
    """Stateless apart from construction metadata; params live outside."""

    def __init__(self, observation_space, action_space, model_config: dict):
        self.observation_space = observation_space
        self.action_space = action_space
        self.model_config = model_config

    def init_params(self, rng: jax.Array) -> Any:
        raise NotImplementedError

    def forward_train(self, params, obs) -> dict:
        """returns {"logits"| "mean/log_std", "vf"}"""
        raise NotImplementedError

    def forward_inference(self, params, obs) -> jnp.ndarray:
        raise NotImplementedError

    def forward_exploration(self, params, obs, rng) -> tuple:
        """returns (actions, logp, extra)"""
        raise NotImplementedError


def _mlp_init(rng, sizes):
    params = []
    for i, (n_in, n_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        rng, key = jax.random.split(rng)
        scale = jnp.sqrt(2.0 / n_in)
        params.append(
            {
                "w": jax.random.normal(key, (n_in, n_out)) * scale,
                "b": jnp.zeros((n_out,)),
            }
        )
    return params


def _mlp_apply(layers, x, activation=jax.nn.tanh):
    for layer in layers[:-1]:
        x = activation(x @ layer["w"] + layer["b"])
    last = layers[-1]
    return x @ last["w"] + last["b"]


class MLPModule(RLModule):
    """Separate policy and value MLP towers (fcnet default: 2x256 tanh)."""

    def __init__(self, observation_space, action_space, model_config):
        super().__init__(observation_space, action_space, model_config)
        self.hiddens = tuple(model_config.get("fcnet_hiddens", (256, 256)))
        self.obs_dim = int(np.prod(observation_space.shape))
        self.discrete = hasattr(action_space, "n")
        if self.discrete:
            self.num_outputs = int(action_space.n)
        else:
            self.act_dim = int(np.prod(action_space.shape))
            self.num_outputs = 2 * self.act_dim  # mean + log_std

    def init_params(self, rng) -> dict:
        pi_rng, vf_rng = jax.random.split(rng)
        return {
            "pi": _mlp_init(pi_rng, (self.obs_dim, *self.hiddens, self.num_outputs)),
            "vf": _mlp_init(vf_rng, (self.obs_dim, *self.hiddens, 1)),
        }

    def forward_train(self, params, obs) -> dict:
        obs = obs.reshape(obs.shape[0], -1)
        out = _mlp_apply(params["pi"], obs)
        vf = _mlp_apply(params["vf"], obs)[..., 0]
        if self.discrete:
            return {"logits": out, "vf": vf}
        mean, log_std = jnp.split(out, 2, axis=-1)
        return {"mean": mean, "log_std": jnp.clip(log_std, -20, 2), "vf": vf}

    def forward_inference(self, params, obs):
        fwd = self.forward_train(params, obs)
        if self.discrete:
            return jnp.argmax(fwd["logits"], axis=-1)
        return fwd["mean"]

    def forward_exploration(self, params, obs, rng):
        fwd = self.forward_train(params, obs)
        if self.discrete:
            logits = fwd["logits"]
            actions = jax.random.categorical(rng, logits)
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, actions[:, None], axis=-1
            )[:, 0]
            return actions, logp, {"vf_preds": fwd["vf"]}
        mean, log_std = fwd["mean"], fwd["log_std"]
        std = jnp.exp(log_std)
        noise = jax.random.normal(rng, mean.shape)
        actions = mean + std * noise
        logp = -0.5 * jnp.sum(
            ((actions - mean) / std) ** 2 + 2 * log_std + jnp.log(2 * jnp.pi),
            axis=-1,
        )
        return actions, logp, {"vf_preds": fwd["vf"]}

    def action_logp(self, params, obs, actions) -> tuple:
        """(logp(actions), entropy, vf) — used inside losses."""
        fwd = self.forward_train(params, obs)
        if self.discrete:
            logp_all = jax.nn.log_softmax(fwd["logits"])
            logp = jnp.take_along_axis(
                logp_all, actions[:, None].astype(jnp.int32), axis=-1
            )[:, 0]
            entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
            return logp, entropy, fwd["vf"]
        mean, log_std = fwd["mean"], fwd["log_std"]
        std = jnp.exp(log_std)
        logp = -0.5 * jnp.sum(
            ((actions - mean) / std) ** 2 + 2 * log_std + jnp.log(2 * jnp.pi),
            axis=-1,
        )
        entropy = jnp.sum(log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e), axis=-1)
        return logp, entropy, fwd["vf"]


class ConvModule(MLPModule):
    """Vision net (reference: rllib/models :: ModelCatalog conv path /
    VisionNetwork), TPU-first: a shared NHWC conv trunk — XLA maps the
    convs straight onto the MXU; NHWC is the TPU-native layout — with a
    dense projection and separate pi/vf heads (the Atari-standard
    [[32,8,4],[64,4,2],[64,3,1]] + 512 trunk by default).

    model_config:
      conv_filters: [[out_channels, kernel, stride], ...] (VALID padding)
      conv_activation: "relu" (default) | "tanh"
      post_fcnet_hiddens: (512,) dense trunk after flatten
      normalize_images: True — scales uint8-style pixel obs by 1/255
        inside the jitted forward (no host-side preprocessing pass).
    """

    def __init__(self, observation_space, action_space, model_config):
        super().__init__(observation_space, action_space, model_config)
        shape = observation_space.shape
        if len(shape) != 3:
            raise ValueError(
                f"ConvModule needs [H, W, C] observations, got {shape}"
            )
        self.obs_shape = tuple(int(s) for s in shape)
        # Size-aware defaults (reference ModelCatalog picks per-resolution
        # filter stacks the same way: 84x84 → the Atari stack).
        if min(self.obs_shape[0], self.obs_shape[1]) >= 60:
            default_filters = ((32, 8, 4), (64, 4, 2), (64, 3, 1))
        else:
            default_filters = ((16, 4, 2), (32, 4, 2))
        self.filters = [
            tuple(int(x) for x in f)
            for f in model_config.get("conv_filters", default_filters)
        ]
        self.post_hiddens = tuple(
            model_config.get("post_fcnet_hiddens", (512,))
        )
        self.normalize = bool(model_config.get("normalize_images", True))
        self.activation = (
            jax.nn.tanh
            if model_config.get("conv_activation") == "tanh"
            else jax.nn.relu
        )
        # Flattened conv-out size from the VALID-padding shape recurrence
        # (static — jit sees fixed shapes).
        h, w = self.obs_shape[0], self.obs_shape[1]
        for _out, k, s in self.filters:
            h = (h - k) // s + 1
            w = (w - k) // s + 1
            if h <= 0 or w <= 0:
                raise ValueError(
                    f"conv_filters {self.filters} shrink {self.obs_shape} "
                    "below 1x1 — remove a layer or pad the observations"
                )
        self.conv_out_dim = h * w * self.filters[-1][0]

    def init_params(self, rng) -> dict:
        conv_rng, trunk_rng, pi_rng, vf_rng = jax.random.split(rng, 4)
        convs = []
        in_ch = self.obs_shape[2]
        for i, (out_ch, k, _s) in enumerate(self.filters):
            key = jax.random.fold_in(conv_rng, i)
            fan_in = k * k * in_ch
            convs.append(
                {
                    # HWIO kernel layout (jax conv convention for NHWC)
                    "w": jax.random.normal(key, (k, k, in_ch, out_ch))
                    * jnp.sqrt(2.0 / fan_in),
                    "b": jnp.zeros((out_ch,)),
                }
            )
            in_ch = out_ch
        trunk_sizes = (self.conv_out_dim, *self.post_hiddens)
        feat = trunk_sizes[-1]
        return {
            "conv": convs,
            "trunk": _mlp_init(trunk_rng, trunk_sizes),
            "pi": _mlp_init(pi_rng, (feat, self.num_outputs)),
            "vf": _mlp_init(vf_rng, (feat, 1)),
        }

    def _features(self, params, obs):
        x = obs.astype(jnp.float32)
        if self.normalize:
            x = x * (1.0 / 255.0)
        for layer, (_out, _k, s) in zip(params["conv"], self.filters):
            x = jax.lax.conv_general_dilated(
                x,
                layer["w"],
                window_strides=(s, s),
                padding="VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
            x = self.activation(x + layer["b"])
        x = x.reshape(x.shape[0], -1)
        for layer in params["trunk"]:
            x = self.activation(x @ layer["w"] + layer["b"])
        return x

    def forward_train(self, params, obs) -> dict:
        feat = self._features(params, obs)
        out = _mlp_apply(params["pi"], feat)
        vf = _mlp_apply(params["vf"], feat)[..., 0]
        if self.discrete:
            return {"logits": out, "vf": vf}
        mean, log_std = jnp.split(out, 2, axis=-1)
        return {"mean": mean, "log_std": jnp.clip(log_std, -20, 2), "vf": vf}


class LSTMModule(MLPModule):
    """Recurrent module (reference: model catalog ``use_lstm`` — the
    rllib/models LSTM wrapper role), TPU-first: training runs the whole
    recurrence as one ``lax.scan`` over fixed-length subsequences (static
    shapes, XLA-fusable), rollouts thread an explicit (h, c) state per
    env through ``forward_*`` (the env runner owns the state).

    Training-time state handling matches the reference's default
    zero-init-per-sequence simplification: the episode-contiguous batch
    is chopped into ``max_seq_len`` windows, each starting from zeros
    (no cross-window carryover); use PPO's sequence-preserving
    minibatcher so windows stay intact.
    """

    is_stateful = True

    def __init__(self, observation_space, action_space, model_config):
        super().__init__(observation_space, action_space, model_config)
        self.cell_size = int(model_config.get("lstm_cell_size", 128))
        self.max_seq_len = int(model_config.get("max_seq_len", 16))

    def init_params(self, rng) -> dict:
        enc_rng, lstm_rng, pi_rng, vf_rng = jax.random.split(rng, 4)
        hidden = self.hiddens[0] if self.hiddens else 128
        scale_x = jnp.sqrt(1.0 / hidden)
        scale_h = jnp.sqrt(1.0 / self.cell_size)
        return {
            "enc": _mlp_init(enc_rng, (self.obs_dim, hidden)),
            "lstm": {
                "wx": jax.random.normal(
                    lstm_rng, (hidden, 4 * self.cell_size)
                ) * scale_x,
                "wh": jax.random.normal(
                    jax.random.fold_in(lstm_rng, 1),
                    (self.cell_size, 4 * self.cell_size),
                ) * scale_h,
                "b": jnp.zeros((4 * self.cell_size,)),
            },
            "pi": _mlp_init(pi_rng, (self.cell_size, self.num_outputs)),
            "vf": _mlp_init(vf_rng, (self.cell_size, 1)),
        }

    # -- recurrence -----------------------------------------------------
    def initial_state(self, batch_size: int):
        zeros = jnp.zeros((batch_size, self.cell_size))
        return (zeros, zeros)

    def _encode(self, params, obs):
        obs = obs.reshape(obs.shape[0], -1)
        return jax.nn.tanh(_mlp_apply(params["enc"], obs))

    def _cell(self, params, x, state):
        h, c = state
        gates = x @ params["lstm"]["wx"] + h @ params["lstm"]["wh"] + params["lstm"]["b"]
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c = jax.nn.sigmoid(f + 1.0) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return h, (h, c)

    def _heads(self, params, features) -> dict:
        out = _mlp_apply(params["pi"], features)
        vf = _mlp_apply(params["vf"], features)[..., 0]
        if self.discrete:
            return {"logits": out, "vf": vf}
        mean, log_std = jnp.split(out, 2, axis=-1)
        return {"mean": mean, "log_std": jnp.clip(log_std, -20, 2), "vf": vf}

    def forward_train(self, params, obs, dones=None) -> dict:
        """[B, ...] episode-contiguous rows -> heads, recurrence scanned
        over max_seq_len windows (zero state per window, padded tail).
        ``dones`` (row-aligned, done AT that step) resets the scan state
        at episode starts INSIDE a window — matching the rollout, which
        zeroes the per-env state after every done."""
        n = obs.shape[0]
        seq = self.max_seq_len
        pad = (-n) % seq
        x = self._encode(params, obs)
        if dones is None:
            dones_f = jnp.zeros((n,))
        else:
            dones_f = jnp.asarray(dones).astype(jnp.float32).reshape(-1)
        # state entering step t is zeroed when step t-1 ended an episode
        starts = jnp.concatenate([jnp.zeros((1,)), dones_f[:-1]])
        if pad:
            x = jnp.concatenate([x, jnp.zeros((pad, x.shape[1]))], axis=0)
            starts = jnp.concatenate([starts, jnp.zeros((pad,))])
        windows = x.reshape(-1, seq, x.shape[1])  # [S, L, H]
        time_major = jnp.swapaxes(windows, 0, 1)  # [L, S, H]
        reset_tm = jnp.swapaxes(starts.reshape(-1, seq), 0, 1)  # [L, S]
        state0 = self.initial_state(windows.shape[0])

        def step(state, inputs):
            xt, reset_t = inputs
            keep = (1.0 - reset_t)[:, None]
            state = jax.tree_util.tree_map(lambda s: s * keep, state)
            h, state = self._cell(params, xt, state)
            return state, h

        _, hs = jax.lax.scan(step, state0, (time_major, reset_tm))
        features = jnp.swapaxes(hs, 0, 1).reshape(-1, self.cell_size)[:n]
        return self._heads(params, features)

    def action_logp(self, params, obs, actions, dones=None) -> tuple:
        """(logp, entropy, vf) with episode-reset-aware recurrence."""
        fwd = self.forward_train(params, obs, dones=dones)
        if self.discrete:
            logp_all = jax.nn.log_softmax(fwd["logits"])
            logp = jnp.take_along_axis(
                logp_all, actions[:, None].astype(jnp.int32), axis=-1
            )[:, 0]
            entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
            return logp, entropy, fwd["vf"]
        mean, log_std = fwd["mean"], fwd["log_std"]
        std = jnp.exp(log_std)
        logp = -0.5 * jnp.sum(
            ((actions - mean) / std) ** 2 + 2 * log_std + jnp.log(2 * jnp.pi),
            axis=-1,
        )
        entropy = jnp.sum(log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e), axis=-1)
        return logp, entropy, fwd["vf"]

    # -- stateful rollout steps ----------------------------------------
    def forward_exploration(self, params, obs, rng, state=None):
        if state is None:
            state = self.initial_state(obs.shape[0])
        x = self._encode(params, obs)
        features, new_state = self._cell(params, x, state)
        fwd = self._heads(params, features)
        if self.discrete:
            logits = fwd["logits"]
            actions = jax.random.categorical(rng, logits)
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(logp_all, actions[:, None], axis=-1)[:, 0]
            return actions, logp, {"vf_preds": fwd["vf"]}, new_state
        mean, log_std = fwd["mean"], fwd["log_std"]
        std = jnp.exp(log_std)
        actions = mean + std * jax.random.normal(rng, mean.shape)
        logp = -0.5 * jnp.sum(
            ((actions - mean) / std) ** 2 + 2 * log_std + jnp.log(2 * jnp.pi),
            axis=-1,
        )
        return actions, logp, {"vf_preds": fwd["vf"]}, new_state

    def forward_inference(self, params, obs, state=None):
        if state is None:
            state = self.initial_state(obs.shape[0])
        x = self._encode(params, obs)
        features, new_state = self._cell(params, x, state)
        fwd = self._heads(params, features)
        if self.discrete:
            return jnp.argmax(fwd["logits"], axis=-1), new_state
        return fwd["mean"], new_state

"""RLModule — the framework-native policy/value network.

Role-equivalent of rllib/core/rl_module/rl_module.py :: RLModule (and
torch/torch_rl_module.py) re-designed for jax (SURVEY §2.8, §3.5): a pure
function suite over a params pytree — forward_inference (greedy),
forward_exploration (sample + logp), forward_train (logits + values) —
so the learner can jit the whole update and env runners call the same
functions on CPU. `MLPModule` is the default catalog net (fcnet-equivalent
of rllib/models :: ModelCatalog).
"""

from __future__ import annotations

from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np


class RLModuleSpec:
    def __init__(self, module_class=None, model_config: dict | None = None):
        self.module_class = module_class or MLPModule
        self.model_config = dict(model_config or {})

    def build(self, observation_space, action_space) -> "RLModule":
        return self.module_class(
            observation_space, action_space, self.model_config
        )


class RLModule:
    """Stateless apart from construction metadata; params live outside."""

    def __init__(self, observation_space, action_space, model_config: dict):
        self.observation_space = observation_space
        self.action_space = action_space
        self.model_config = model_config

    def init_params(self, rng: jax.Array) -> Any:
        raise NotImplementedError

    def forward_train(self, params, obs) -> dict:
        """returns {"logits"| "mean/log_std", "vf"}"""
        raise NotImplementedError

    def forward_inference(self, params, obs) -> jnp.ndarray:
        raise NotImplementedError

    def forward_exploration(self, params, obs, rng) -> tuple:
        """returns (actions, logp, extra)"""
        raise NotImplementedError


def _mlp_init(rng, sizes):
    params = []
    for i, (n_in, n_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        rng, key = jax.random.split(rng)
        scale = jnp.sqrt(2.0 / n_in)
        params.append(
            {
                "w": jax.random.normal(key, (n_in, n_out)) * scale,
                "b": jnp.zeros((n_out,)),
            }
        )
    return params


def _mlp_apply(layers, x, activation=jax.nn.tanh):
    for layer in layers[:-1]:
        x = activation(x @ layer["w"] + layer["b"])
    last = layers[-1]
    return x @ last["w"] + last["b"]


class MLPModule(RLModule):
    """Separate policy and value MLP towers (fcnet default: 2x256 tanh)."""

    def __init__(self, observation_space, action_space, model_config):
        super().__init__(observation_space, action_space, model_config)
        self.hiddens = tuple(model_config.get("fcnet_hiddens", (256, 256)))
        self.obs_dim = int(np.prod(observation_space.shape))
        self.discrete = hasattr(action_space, "n")
        if self.discrete:
            self.num_outputs = int(action_space.n)
        else:
            self.act_dim = int(np.prod(action_space.shape))
            self.num_outputs = 2 * self.act_dim  # mean + log_std

    def init_params(self, rng) -> dict:
        pi_rng, vf_rng = jax.random.split(rng)
        return {
            "pi": _mlp_init(pi_rng, (self.obs_dim, *self.hiddens, self.num_outputs)),
            "vf": _mlp_init(vf_rng, (self.obs_dim, *self.hiddens, 1)),
        }

    def forward_train(self, params, obs) -> dict:
        obs = obs.reshape(obs.shape[0], -1)
        out = _mlp_apply(params["pi"], obs)
        vf = _mlp_apply(params["vf"], obs)[..., 0]
        if self.discrete:
            return {"logits": out, "vf": vf}
        mean, log_std = jnp.split(out, 2, axis=-1)
        return {"mean": mean, "log_std": jnp.clip(log_std, -20, 2), "vf": vf}

    def forward_inference(self, params, obs):
        fwd = self.forward_train(params, obs)
        if self.discrete:
            return jnp.argmax(fwd["logits"], axis=-1)
        return fwd["mean"]

    def forward_exploration(self, params, obs, rng):
        fwd = self.forward_train(params, obs)
        if self.discrete:
            logits = fwd["logits"]
            actions = jax.random.categorical(rng, logits)
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, actions[:, None], axis=-1
            )[:, 0]
            return actions, logp, {"vf_preds": fwd["vf"]}
        mean, log_std = fwd["mean"], fwd["log_std"]
        std = jnp.exp(log_std)
        noise = jax.random.normal(rng, mean.shape)
        actions = mean + std * noise
        logp = -0.5 * jnp.sum(
            ((actions - mean) / std) ** 2 + 2 * log_std + jnp.log(2 * jnp.pi),
            axis=-1,
        )
        return actions, logp, {"vf_preds": fwd["vf"]}

    def action_logp(self, params, obs, actions) -> tuple:
        """(logp(actions), entropy, vf) — used inside losses."""
        fwd = self.forward_train(params, obs)
        if self.discrete:
            logp_all = jax.nn.log_softmax(fwd["logits"])
            logp = jnp.take_along_axis(
                logp_all, actions[:, None].astype(jnp.int32), axis=-1
            )[:, 0]
            entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
            return logp, entropy, fwd["vf"]
        mean, log_std = fwd["mean"], fwd["log_std"]
        std = jnp.exp(log_std)
        logp = -0.5 * jnp.sum(
            ((actions - mean) / std) ** 2 + 2 * log_std + jnp.log(2 * jnp.pi),
            axis=-1,
        )
        entropy = jnp.sum(log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e), axis=-1)
        return logp, entropy, fwd["vf"]

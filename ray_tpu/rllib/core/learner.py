"""Learner / LearnerGroup — the accelerator-side update.

Role-equivalents of rllib/core/learner/learner.py :: Learner and
learner_group.py :: LearnerGroup (SURVEY §2.8, §3.5), TPU-first per the
north star: the entire SGD step — loss, grads, optimizer — is ONE jitted
XLA function (donated params/opt-state, bfloat16-friendly), so on TPU the
update never leaves the device. Multi-learner data parallelism shards the
train batch across learner actors and ring-allreduces gradients through
ray_tpu.util.collective (ICI's psum inside jit when the learners share a
jax mesh; the eager ring on CPU twins).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu
from ray_tpu.rllib.policy.sample_batch import SampleBatch


class Learner:
    """Owns params + optimizer; subclasses define compute_loss."""

    def __init__(self, module, config: dict, seed: int = 0):
        self.module = module
        self.config = dict(config)
        self.params = module.init_params(jax.random.PRNGKey(seed))
        self.optimizer = self._build_optimizer()
        self.opt_state = self.optimizer.init(self.params)
        self._step = jax.jit(self._jit_step, donate_argnums=(0, 1))
        self._grad_only = jax.jit(jax.grad(self._loss_for_grads))
        self._apply = jax.jit(self._jit_apply, donate_argnums=(0, 1))

    def _build_optimizer(self):
        lr = self.config.get("lr", 5e-4)
        clip = self.config.get("grad_clip", 40.0)
        return optax.chain(
            optax.clip_by_global_norm(clip),
            optax.adam(lr),
        )

    # -- subclass surface -----------------------------------------------
    def compute_loss(self, params, batch: dict) -> tuple[jnp.ndarray, dict]:
        raise NotImplementedError

    # -- jitted internals -----------------------------------------------
    def _loss_for_grads(self, params, batch):
        loss, _ = self.compute_loss(params, batch)
        return loss

    def _jit_step(self, params, opt_state, batch):
        (loss, metrics), grads = jax.value_and_grad(
            self.compute_loss, has_aux=True
        )(params, batch)
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics["total_loss"] = loss
        return params, opt_state, metrics

    def _jit_apply(self, params, opt_state, grads):
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state

    # -- public ----------------------------------------------------------
    @staticmethod
    def _to_device(batch: SampleBatch) -> dict:
        # Non-numeric bookkeeping columns (AGENT_ID strings, …) stay host-side.
        return {
            k: jnp.asarray(v)
            for k, v in batch.items()
            if np.asarray(v).dtype.kind in "biuf"
        }

    def update(self, batch: SampleBatch) -> dict:
        device_batch = self._to_device(batch)
        self.params, self.opt_state, metrics = self._step(
            self.params, self.opt_state, device_batch
        )
        return {k: float(v) for k, v in metrics.items()}

    def compute_gradients(self, batch: SampleBatch):
        device_batch = self._to_device(batch)
        return self._grad_only(self.params, device_batch)

    def apply_gradients(self, grads) -> None:
        self.params, self.opt_state = self._apply(
            self.params, self.opt_state, grads
        )

    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, params) -> None:
        self.params = jax.device_put(params)

    def get_state(self) -> dict:
        return {
            "params": jax.device_get(self.params),
            "opt_state": jax.device_get(self.opt_state),
        }

    def set_state(self, state: dict) -> None:
        self.params = jax.device_put(state["params"])
        self.opt_state = jax.device_put(state["opt_state"])


class _LearnerActor:
    """Hosts one Learner shard for multi-learner DP."""

    def __init__(self, learner_cls, module_spec, obs_space, act_space,
                 config: dict, rank: int, world_size: int, group_name: str):
        module = module_spec.build(obs_space, act_space)
        self.learner: Learner = learner_cls(module, config, seed=0)
        self.rank = rank
        self.world_size = world_size
        self.group_name = group_name
        if world_size > 1:
            from ray_tpu.util.collective import collective

            collective.init_collective_group(
                world_size, rank, backend="ring", group_name=group_name
            )

    def update_shard(self, batch: SampleBatch) -> dict:
        """DDP step: local grads → ring allreduce → apply (SURVEY §3.5)."""
        if self.world_size == 1:
            return self.learner.update(batch)
        from ray_tpu.util.collective import collective

        grads = self.learner.compute_gradients(batch)
        flat, tree = jax.tree_util.tree_flatten(grads)
        group = collective.get_group(self.group_name)
        reduced = []
        for g in flat:
            arr = np.asarray(g)
            group.allreduce(arr)
            reduced.append(arr / self.world_size)
        self.learner.apply_gradients(jax.tree_util.tree_unflatten(tree, reduced))
        return {"total_loss": float("nan")}

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, params) -> str:
        self.learner.set_weights(params)
        return "ok"

    def get_state(self):
        return self.learner.get_state()

    def set_state(self, state) -> str:
        self.learner.set_state(state)
        return "ok"

    def ping(self) -> str:
        return "ok"


class LearnerGroup:
    """num_learners=0 → local in-process learner (default, single chip).
    num_learners>=1 → learner actors with DP grad-allreduce."""

    def __init__(
        self,
        learner_cls,
        module_spec,
        observation_space,
        action_space,
        config: dict,
        num_learners: int = 0,
    ):
        self.num_learners = num_learners
        if num_learners == 0:
            module = module_spec.build(observation_space, action_space)
            self.local_learner: Optional[Learner] = learner_cls(module, config)
            self.actors = []
        else:
            self.local_learner = None
            actor_cls = ray_tpu.remote(_LearnerActor)
            group_name = f"learner-dp-{id(self) & 0xFFFF:x}"
            self.actors = [
                actor_cls.options(num_cpus=1).remote(
                    learner_cls, module_spec, observation_space, action_space,
                    config, rank, num_learners, group_name,
                )
                for rank in range(num_learners)
            ]
            ray_tpu.get([a.ping.remote() for a in self.actors], timeout=180)

    def update(self, batch: SampleBatch) -> dict:
        if self.local_learner is not None:
            return self.local_learner.update(batch)
        n = len(self.actors)
        shard = max(1, len(batch) // n)
        shards = [batch.slice(i * shard, (i + 1) * shard) for i in range(n)]
        metrics = ray_tpu.get(
            [a.update_shard.remote(s) for a, s in zip(self.actors, shards)],
            timeout=600,
        )
        return metrics[0]

    def get_weights(self):
        if self.local_learner is not None:
            return self.local_learner.get_weights()
        return ray_tpu.get(self.actors[0].get_weights.remote(), timeout=120)

    def set_weights(self, params) -> None:
        if self.local_learner is not None:
            self.local_learner.set_weights(params)
        else:
            ray_tpu.get(
                [a.set_weights.remote(params) for a in self.actors], timeout=120
            )

    def get_state(self) -> dict:
        if self.local_learner is not None:
            return self.local_learner.get_state()
        return ray_tpu.get(self.actors[0].get_state.remote(), timeout=120)

    def set_state(self, state: dict) -> None:
        if self.local_learner is not None:
            self.local_learner.set_state(state)
        else:
            ray_tpu.get(
                [a.set_state.remote(state) for a in self.actors], timeout=120
            )

    def stop(self) -> None:
        for actor in self.actors:
            try:
                ray_tpu.kill(actor)
            except Exception:  # rtlint: disable=swallowed-exception - actor already dead
                pass


class MultiAgentLearnerGroup:
    """One Learner per module id over a MultiRLModule.

    Role-equivalent of the Learner's MultiRLModule support in the
    reference (rllib/core/learner/learner.py multi-module update): each
    module's update stays its own jitted XLA function; weights/state are
    dicts keyed by module id, which is what MultiAgentEnvRunner expects
    from sync_weights.
    """

    def __init__(
        self,
        learner_cls,
        multi_spec,  # MultiRLModuleSpec
        observation_spaces: dict,
        action_spaces: dict,
        config: dict,
    ):
        multi_module = multi_spec.build(observation_spaces, action_spaces)
        self.learners: dict[str, Learner] = {
            mid: learner_cls(module, config, seed=i)
            for i, (mid, module) in enumerate(sorted(multi_module.items()))
        }

    @property
    def module_ids(self):
        return self.learners.keys()

    def update(self, batch) -> dict:
        """``batch``: MultiAgentBatch → {module_id: metrics}."""
        return {
            mid: self.learners[mid].update(sub)
            for mid, sub in batch.items()
            if len(sub)
        }

    def update_module(self, module_id: str, batch: SampleBatch) -> dict:
        return self.learners[module_id].update(batch)

    def get_weights(self) -> dict:
        return {mid: l.get_weights() for mid, l in self.learners.items()}

    def set_weights(self, params: dict) -> None:
        for mid, p in params.items():
            self.learners[mid].set_weights(p)

    def get_state(self) -> dict:
        return {mid: l.get_state() for mid, l in self.learners.items()}

    def set_state(self, state: dict) -> None:
        for mid, s in state.items():
            self.learners[mid].set_state(s)

    def stop(self) -> None:
        pass

"""MultiRLModule — a dict of RLModules keyed by module id.

Role-equivalent of rllib/core/rl_module/multi_rl_module.py ::
MultiRLModule(Spec): holds one RLModule per policy/module id; params are a
dict pytree {module_id: module_params}, so the whole multi-agent update
stays one jit-friendly structure. Agent→module routing happens in the
runner via ``policy_mapping_fn`` — the module itself is agnostic.
"""

from __future__ import annotations

from typing import Mapping

import jax

from ray_tpu.rllib.core.rl_module import RLModule, RLModuleSpec


class MultiRLModuleSpec:
    """module_id → RLModuleSpec (or None for the default MLP catalog)."""

    def __init__(self, module_specs: Mapping[str, RLModuleSpec | None]):
        self.module_specs = {
            mid: (spec or RLModuleSpec()) for mid, spec in module_specs.items()
        }

    def build(
        self,
        observation_spaces: Mapping[str, object],
        action_spaces: Mapping[str, object],
    ) -> "MultiRLModule":
        modules = {
            mid: spec.build(observation_spaces[mid], action_spaces[mid])
            for mid, spec in self.module_specs.items()
        }
        return MultiRLModule(modules)


class MultiRLModule:
    def __init__(self, modules: Mapping[str, RLModule]):
        self._modules = dict(modules)

    def __getitem__(self, module_id: str) -> RLModule:
        return self._modules[module_id]

    def __contains__(self, module_id: str) -> bool:
        return module_id in self._modules

    def keys(self):
        return self._modules.keys()

    def items(self):
        return self._modules.items()

    def init_params(self, rng: jax.Array) -> dict:
        keys = jax.random.split(rng, len(self._modules))
        return {
            mid: module.init_params(key)
            for (mid, module), key in zip(sorted(self._modules.items()), keys)
        }

"""SampleBatch — the lingua-franca tensor dict.

Role-equivalent of rllib/policy/sample_batch.py :: SampleBatch
(SURVEY §2.8): a dict of aligned numpy arrays with standard keys, slicing,
concatenation, and minibatch shuffling. Flows env-runner → learner through
the object store (pickle-5 zero copy).
"""

from __future__ import annotations

from typing import Iterator, Mapping

import numpy as np

OBS = "obs"
NEXT_OBS = "new_obs"
ACTIONS = "actions"
REWARDS = "rewards"
TERMINATEDS = "terminateds"
TRUNCATEDS = "truncateds"
ACTION_LOGP = "action_logp"
VF_PREDS = "vf_preds"
ADVANTAGES = "advantages"
VALUE_TARGETS = "value_targets"
EPS_ID = "eps_id"
AGENT_ID = "agent_id"


class SampleBatch(dict):
    def __init__(self, data: Mapping[str, np.ndarray] | None = None, **kwargs):
        super().__init__()
        for key, value in {**(data or {}), **kwargs}.items():
            self[key] = np.asarray(value)

    def __len__(self) -> int:
        for value in self.values():
            return len(value)
        return 0

    @property
    def count(self) -> int:
        return len(self)

    def slice(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: v[start:end] for k, v in self.items()})

    def shuffle(self, rng: np.random.Generator | None = None) -> "SampleBatch":
        rng = rng or np.random.default_rng()
        perm = rng.permutation(len(self))
        return SampleBatch({k: v[perm] for k, v in self.items()})

    def minibatches(
        self, minibatch_size: int, rng: np.random.Generator | None = None
    ) -> Iterator["SampleBatch"]:
        shuffled = self.shuffle(rng)
        for start in range(0, len(self), minibatch_size):
            mb = shuffled.slice(start, start + minibatch_size)
            if len(mb) == minibatch_size:
                yield mb

    def seq_minibatches(
        self,
        seq_len: int,
        minibatch_size: int,
        rng: np.random.Generator | None = None,
    ) -> Iterator["SampleBatch"]:
        """Sequence-preserving minibatches for recurrent modules: rows
        chop into contiguous seq_len windows, WINDOWS shuffle (never rows
        — that would scramble the recurrence), and each minibatch is a
        whole number of windows."""
        rng = rng or np.random.default_rng()
        n_windows = len(self) // seq_len
        if n_windows == 0:
            yield self
            return
        # never yield ZERO minibatches (a batch smaller than the requested
        # minibatch must still train once)
        per_mb = min(max(1, minibatch_size // seq_len), n_windows)
        order = rng.permutation(n_windows)
        for start in range(0, n_windows - per_mb + 1, per_mb):
            idx = np.concatenate(
                [
                    np.arange(w * seq_len, (w + 1) * seq_len)
                    for w in order[start:start + per_mb]
                ]
            )
            yield SampleBatch({k: v[idx] for k, v in self.items()})

    @staticmethod
    def concat_samples(batches: list["SampleBatch"]) -> "SampleBatch":
        batches = [b for b in batches if len(b)]
        if not batches:
            return SampleBatch()
        keys = set(batches[0])
        return SampleBatch(
            {k: np.concatenate([b[k] for b in batches]) for k in keys}
        )

    def split_by_episode(self) -> list["SampleBatch"]:
        if EPS_ID not in self:
            return [self]
        out = []
        ids = self[EPS_ID]
        boundaries = np.nonzero(np.diff(ids))[0] + 1
        start = 0
        for end in list(boundaries) + [len(self)]:
            out.append(self.slice(start, end))
            start = end
        return [b for b in out if len(b)]


class MultiAgentBatch:
    """Per-module SampleBatches + the env-step count they came from.

    Role-equivalent of rllib/policy/sample_batch.py :: MultiAgentBatch:
    ``policy_batches`` maps module_id → SampleBatch of that module's
    agent-steps; ``env_steps`` counts underlying environment steps (one
    env step can contribute a row to several modules).
    """

    def __init__(self, policy_batches: Mapping[str, SampleBatch], env_steps: int):
        self.policy_batches: dict[str, SampleBatch] = dict(policy_batches)
        self._env_steps = int(env_steps)

    def env_steps(self) -> int:
        return self._env_steps

    def agent_steps(self) -> int:
        return sum(len(b) for b in self.policy_batches.values())

    def __len__(self) -> int:
        return self._env_steps

    def __getitem__(self, module_id: str) -> SampleBatch:
        return self.policy_batches[module_id]

    def __contains__(self, module_id: str) -> bool:
        return module_id in self.policy_batches

    def keys(self):
        return self.policy_batches.keys()

    def items(self):
        return self.policy_batches.items()

    @staticmethod
    def concat_samples(batches: list["MultiAgentBatch"]) -> "MultiAgentBatch":
        merged: dict[str, list[SampleBatch]] = {}
        steps = 0
        for batch in batches:
            steps += batch.env_steps()
            for mid, sub in batch.policy_batches.items():
                merged.setdefault(mid, []).append(sub)
        return MultiAgentBatch(
            {m: SampleBatch.concat_samples(bs) for m, bs in merged.items()},
            steps,
        )

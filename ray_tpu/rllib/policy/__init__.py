from ray_tpu.rllib.policy.sample_batch import SampleBatch

__all__ = ["SampleBatch"]

"""ray_tpu.rllib — reinforcement learning (RLlib-equivalent, TPU-first).

New-API-stack architecture only (SURVEY §2.8): RLModule (jax nets),
Learner/LearnerGroup (jitted XLA updates, DP grad-allreduce), EnvRunner
actors (CPU gymnasium vector envs), ConnectorV2 pipelines, SampleBatch /
MultiAgentBatch, GAE/vtrace in jax, and PPO / IMPALA / DQN / SAC
algorithms (single- and multi-agent) with fluent AlgorithmConfigs.
"""

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.appo.appo import APPO, APPOConfig
from ray_tpu.rllib.algorithms.bc.bc import BC, BCConfig
from ray_tpu.rllib.algorithms.cql.cql import CQL, CQLConfig
from ray_tpu.rllib.algorithms.dqn.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.impala.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.algorithms.marwil.marwil import MARWIL, MARWILConfig
from ray_tpu.rllib.algorithms.ppo.ppo import PPO, PPOConfig
from ray_tpu.rllib.algorithms.sac.sac import SAC, SACConfig
from ray_tpu.rllib.core.learner import (
    Learner, LearnerGroup, MultiAgentLearnerGroup,
)
from ray_tpu.rllib.core.multi_rl_module import MultiRLModule, MultiRLModuleSpec
from ray_tpu.rllib.core.rl_module import MLPModule, RLModule, RLModuleSpec
from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner
from ray_tpu.rllib.env.env_runner_group import EnvRunnerGroup
from ray_tpu.rllib.env.multi_agent_env import MultiAgentCartPole, MultiAgentEnv
from ray_tpu.rllib.env.multi_agent_env_runner import MultiAgentEnvRunner
from ray_tpu.rllib.policy.sample_batch import MultiAgentBatch, SampleBatch

__all__ = [
    "Algorithm", "AlgorithmConfig", "PPO", "PPOConfig", "IMPALA",
    "IMPALAConfig", "APPO", "APPOConfig", "DQN", "DQNConfig", "BC", "BCConfig", "CQL", "CQLConfig", "MARWIL", "MARWILConfig", "SAC", "SACConfig", "Learner",
    "LearnerGroup", "MultiAgentLearnerGroup", "MultiRLModule",
    "MultiRLModuleSpec", "RLModule", "RLModuleSpec", "MLPModule",
    "SingleAgentEnvRunner", "EnvRunnerGroup", "MultiAgentEnv",
    "MultiAgentCartPole", "MultiAgentEnvRunner", "SampleBatch",
    "MultiAgentBatch",
]

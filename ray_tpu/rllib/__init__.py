"""ray_tpu.rllib — reinforcement learning (RLlib-equivalent, TPU-first).

New-API-stack architecture only (SURVEY §2.8): RLModule (jax nets),
Learner/LearnerGroup (jitted XLA updates, DP grad-allreduce), EnvRunner
actors (CPU gymnasium vector envs), SampleBatch, GAE/vtrace in jax, and
PPO / IMPALA / DQN algorithms with fluent AlgorithmConfigs.
"""

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.dqn.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.impala.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.algorithms.ppo.ppo import PPO, PPOConfig
from ray_tpu.rllib.core.learner import Learner, LearnerGroup
from ray_tpu.rllib.core.rl_module import MLPModule, RLModule, RLModuleSpec
from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner
from ray_tpu.rllib.env.env_runner_group import EnvRunnerGroup
from ray_tpu.rllib.policy.sample_batch import SampleBatch

__all__ = [
    "Algorithm", "AlgorithmConfig", "PPO", "PPOConfig", "IMPALA",
    "IMPALAConfig", "DQN", "DQNConfig", "Learner", "LearnerGroup",
    "RLModule", "RLModuleSpec", "MLPModule", "SingleAgentEnvRunner",
    "EnvRunnerGroup", "SampleBatch",
]

"""Replay buffers for off-policy algorithms.

Role-equivalent of rllib/utils/replay_buffers/ (SURVEY §2.8):
ReplayBuffer (uniform ring) and PrioritizedReplayBuffer (proportional
prioritization with importance-sampling weights, Schaul et al. 2016 —
sum-tree replaced by numpy cumsum sampling, fine at these capacities).
"""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.policy.sample_batch import SampleBatch


class ReplayBuffer:
    def __init__(self, capacity: int = 100_000, seed: int | None = None):
        self.capacity = capacity
        self._storage: dict[str, np.ndarray] = {}
        self._size = 0
        self._next_idx = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add(self, batch: SampleBatch) -> None:
        n = len(batch)
        if not self._storage:
            for key, value in batch.items():
                self._storage[key] = np.zeros(
                    (self.capacity,) + value.shape[1:], dtype=value.dtype
                )
        for i in range(n):
            idx = self._next_idx
            for key, value in batch.items():
                self._storage[key][idx] = value[i]
            self._on_add(idx)
            self._next_idx = (self._next_idx + 1) % self.capacity
            self._size = min(self._size + 1, self.capacity)

    def _on_add(self, idx: int) -> None:
        pass

    def sample(self, num_items: int) -> SampleBatch:
        idx = self._rng.integers(0, self._size, size=num_items)
        return self._take(idx)

    def _take(self, idx: np.ndarray) -> SampleBatch:
        out = SampleBatch({k: v[idx] for k, v in self._storage.items()})
        out["batch_indexes"] = idx
        return out


class PrioritizedReplayBuffer(ReplayBuffer):
    def __init__(
        self,
        capacity: int = 100_000,
        alpha: float = 0.6,
        beta: float = 0.4,
        seed: int | None = None,
    ):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.beta = beta
        self._priorities = np.zeros(capacity, dtype=np.float64)
        self._max_priority = 1.0

    def _on_add(self, idx: int) -> None:
        self._priorities[idx] = self._max_priority ** self.alpha

    def sample(self, num_items: int) -> SampleBatch:
        prios = self._priorities[: self._size]
        probs = prios / prios.sum()
        idx = self._rng.choice(self._size, size=num_items, p=probs)
        batch = self._take(idx)
        weights = (self._size * probs[idx]) ** (-self.beta)
        batch["weights"] = (weights / weights.max()).astype(np.float32)
        return batch

    def update_priorities(self, idx: np.ndarray, td_errors: np.ndarray) -> None:
        # _max_priority stays in RAW priority units; **alpha is applied
        # exactly once when writing _priorities (also in _on_add, which
        # exponentiates _max_priority itself).
        raw = np.abs(td_errors) + 1e-6
        self._priorities[np.asarray(idx)] = raw ** self.alpha
        self._max_priority = max(self._max_priority, float(raw.max()))

"""Advantage estimation (GAE) — the env→learner connector math.

Role-equivalent of the GAE connector in rllib (connectors/learner/
general_advantage_estimation.py; historically postprocessing.py ::
compute_gae_for_sample_batch). Pure numpy over rollout fragments: each
episode slice gets its own backward pass; fragments that end mid-episode
bootstrap from the value prediction of the final next_obs.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.policy.sample_batch import (
    ADVANTAGES, NEXT_OBS, REWARDS, SampleBatch, TERMINATEDS, TRUNCATEDS,
    VALUE_TARGETS, VF_PREDS,
)


def compute_gae(
    batch: SampleBatch,
    *,
    gamma: float = 0.99,
    lambda_: float = 0.95,
    value_fn=None,
    standardize: bool = True,
) -> SampleBatch:
    """Adds ADVANTAGES and VALUE_TARGETS, episode-aware."""
    advantages = np.zeros(len(batch), dtype=np.float32)
    targets = np.zeros(len(batch), dtype=np.float32)
    for episode in _episode_slices(batch):
        start, end = episode
        rewards = batch[REWARDS][start:end]
        values = batch[VF_PREDS][start:end]
        terminated = bool(batch[TERMINATEDS][end - 1])
        truncated = bool(batch[TRUNCATEDS][end - 1])
        if terminated:
            bootstrap = 0.0
        else:
            # Mid-fragment cut or truncation: bootstrap from V(next_obs).
            if value_fn is not None:
                bootstrap = float(
                    np.asarray(
                        value_fn(batch[NEXT_OBS][end - 1][None])
                    ).reshape(-1)[0]
                )
            else:
                bootstrap = float(values[-1])
        next_values = np.append(values[1:], bootstrap)
        deltas = rewards + gamma * next_values - values
        adv = np.zeros_like(deltas)
        acc = 0.0
        for t in range(len(deltas) - 1, -1, -1):
            acc = deltas[t] + gamma * lambda_ * acc
            adv[t] = acc
        advantages[start:end] = adv
        targets[start:end] = adv + values
    if standardize and len(advantages) > 1:
        advantages = (advantages - advantages.mean()) / max(
            advantages.std(), 1e-6
        )
    batch[ADVANTAGES] = advantages
    batch[VALUE_TARGETS] = targets
    return batch


def _episode_slices(batch: SampleBatch) -> list[tuple[int, int]]:
    from ray_tpu.rllib.policy.sample_batch import EPS_ID

    if EPS_ID not in batch:
        return [(0, len(batch))]
    ids = batch[EPS_ID]
    boundaries = list(np.nonzero(np.diff(ids))[0] + 1)
    edges = [0] + boundaries + [len(batch)]
    return [(edges[i], edges[i + 1]) for i in range(len(edges) - 1)]

"""Windowed metrics aggregation for Algorithm results.

Role-equivalent of rllib/utils/metrics/metrics_logger.py ::
MetricsLogger + the Stats windowing underneath (SURVEY §2.8): training
code logs raw values as they happen; `reduce()` produces the windowed
mean/min/max (plus lifetime sums and throughputs) that land in
Algorithm.train() results — instead of ad-hoc per-iteration means.
"""

from __future__ import annotations

import collections
import time
from typing import Any


class _WindowStat:
    __slots__ = ("window", "values", "lifetime_sum", "lifetime_count")

    def __init__(self, window: int):
        self.window = window
        self.values: collections.deque = collections.deque(maxlen=window)
        self.lifetime_sum = 0.0
        self.lifetime_count = 0

    def push(self, value: float) -> None:
        self.values.append(value)
        self.lifetime_sum += value
        self.lifetime_count += 1


class _Throughput:
    __slots__ = ("total", "_last_total", "_last_ts", "rate")

    def __init__(self):
        self.total = 0.0
        self._last_total = 0.0
        self._last_ts: float | None = None
        self.rate = 0.0

    def push(self, count: float) -> None:
        self.total += count

    def tick(self, now: float) -> None:
        if self._last_ts is not None and now > self._last_ts:
            self.rate = (self.total - self._last_total) / (now - self._last_ts)
        self._last_total = self.total
        self._last_ts = now


class MetricsLogger:
    """log_value / log_dict in hot paths, reduce() once per iteration.

    * ``log_value(key, v)`` — windowed stat: reduce() reports
      ``<key>_mean/_min/_max`` over the last ``window`` values.
    * ``log_value(key, v, reduce="sum")`` — lifetime counter: reduce()
      reports the running total under ``<key>``.
    * ``log_throughput(key, n)`` — counter + per-second rate between
      reduce() calls: ``<key>`` (lifetime) and ``<key>_throughput``.
    """

    def __init__(self, window: int = 100):
        self.window = window
        self._stats: dict[str, _WindowStat] = {}
        self._sums: dict[str, float] = {}
        self._throughputs: dict[str, _Throughput] = {}

    # -- logging --------------------------------------------------------
    def log_value(
        self, key: str, value: float, *, reduce: str = "window",
        window: int | None = None,
    ) -> None:
        if reduce == "sum":
            self._sums[key] = self._sums.get(key, 0.0) + float(value)
            return
        stat = self._stats.get(key)
        if stat is None:
            stat = self._stats[key] = _WindowStat(window or self.window)
        stat.push(float(value))

    def log_dict(self, values: dict, *, prefix: str = "") -> None:
        for key, value in values.items():
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                self.log_value(f"{prefix}{key}", value)

    def log_throughput(self, key: str, count: float) -> None:
        tp = self._throughputs.get(key)
        if tp is None:
            tp = self._throughputs[key] = _Throughput()
        tp.push(float(count))

    # -- reduction ------------------------------------------------------
    def peek(self, key: str) -> float | None:
        """Current windowed mean of ``key`` (None when nothing logged)."""
        stat = self._stats.get(key)
        if stat is None or not stat.values:
            return None
        return sum(stat.values) / len(stat.values)

    def reduce(self) -> dict[str, Any]:
        now = time.monotonic()
        out: dict[str, Any] = {}
        for key, stat in self._stats.items():
            if not stat.values:
                continue
            vals = stat.values
            out[f"{key}_mean"] = sum(vals) / len(vals)
            out[f"{key}_min"] = min(vals)
            out[f"{key}_max"] = max(vals)
        for key, total in self._sums.items():
            out[key] = total
        for key, tp in self._throughputs.items():
            tp.tick(now)
            out[key] = tp.total
            out[f"{key}_throughput"] = tp.rate
        return out

    def reset(self) -> None:
        self._stats.clear()
        self._sums.clear()
        self._throughputs.clear()

"""Compiled graphs (aDAG-equivalent) — static actor DAGs with channels.

Role-equivalent of python/ray/dag/ :: InputNode / DAGNode /
.experimental_compile (SURVEY §2.2): a static graph of actor method calls
is compiled once; every `execute()` then flows actor→actor over direct
worker RPC channels with ZERO driver round-trips between stages — the
pipeline-parallel inference substrate. On TPU, stage payloads are host
arrays; device arrays stay in each stage's HBM between its jitted calls
(and intra-slice stages exchange via in-jit collectives, not channels).

Overlap comes for free: execute() is async (returns a DAGRef), so seq k+1
enters stage 0 while seq k is in stage 1 — microbatch pipelining.

    with InputNode() as inp:
        x = worker_a.preprocess.bind(inp)
        out = worker_b.infer.bind(x)
    dag = out.experimental_compile()
    ref = dag.execute(batch)          # non-blocking
    result = ref.get(timeout=60)
"""

from __future__ import annotations

import itertools
import uuid
from typing import Any, Optional

from ray_tpu._private import serialization, worker as worker_mod

_node_counter = itertools.count()


class DAGNode:
    def __init__(self):
        self.node_id = next(_node_counter)

    def experimental_compile(self) -> "CompiledDAG":
        return CompiledDAG(self)

    def _upstream(self) -> list["DAGNode"]:
        return []


class InputNode(DAGNode):
    """The DAG's input placeholder; context-manager form mirrors the
    reference (`with InputNode() as inp:`)."""

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc) -> None:
        return None


class ClassMethodNode(DAGNode):
    def __init__(self, actor_handle, method_name: str, args: tuple):
        super().__init__()
        self.actor = actor_handle
        self.method_name = method_name
        self.args = args
        for arg in args:
            if isinstance(arg, ClassMethodNode) and arg.actor._actor_id == (
                actor_handle._actor_id
            ):
                raise ValueError(
                    "compiled DAGs cannot chain two stages on the same actor"
                )

    def _upstream(self) -> list[DAGNode]:
        return [a for a in self.args if isinstance(a, DAGNode)]

    def execute(self, *input_values) -> Any:
        """Interpreted (uncompiled) execution via normal actor calls."""

        def resolve(node, memo):
            if node.node_id in memo:
                return memo[node.node_id]
            if isinstance(node, InputNode):
                value = input_values[0] if len(input_values) == 1 else input_values
            else:
                import ray_tpu

                args = [
                    resolve(a, memo) if isinstance(a, DAGNode) else a
                    for a in node.args
                ]
                method = getattr(node.actor, node.method_name)
                value = ray_tpu.get(method.remote(*args), timeout=300)
            memo[node.node_id] = value
            return value

        return resolve(self, {})


class _BoundMethod:
    """`actor.method.bind(...)` — installed on ActorMethod lazily."""

    def __init__(self, handle, name):
        self.handle = handle
        self.name = name

    def bind(self, *args) -> ClassMethodNode:
        return ClassMethodNode(self.handle, self.name, args)


def _install_bind() -> None:
    """Give ActorMethod a .bind() without import cycles."""
    from ray_tpu.actor import ActorMethod

    if not hasattr(ActorMethod, "bind"):
        def bind(self, *args):
            return ClassMethodNode(self._handle, self._name, args)

        ActorMethod.bind = bind


_install_bind()


class DAGRef:
    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq

    def get(self, timeout: float = 300.0) -> Any:
        return self._dag._pop(self._seq, timeout)


class CompiledDAG:
    def __init__(self, output_node: DAGNode):
        if isinstance(output_node, InputNode):
            raise ValueError("cannot compile a bare InputNode")
        self.dag_id = f"dag-{uuid.uuid4().hex[:8]}"
        self.output_node = output_node
        self._seq = itertools.count()
        self._ctx = worker_mod.get_global_context()
        self._stages: dict[int, dict] = {}  # node_id → stage spec
        self._input_targets: list[tuple[str, str]] = []  # (actor_id, slot)
        self._compile()

    # -- graph lowering --------------------------------------------------
    def _compile(self) -> None:
        nodes: dict[int, DAGNode] = {}

        def walk(node: DAGNode):
            if node.node_id in nodes:
                return
            nodes[node.node_id] = node
            for up in node._upstream():
                walk(up)

        walk(self.output_node)
        method_nodes = [
            n for n in nodes.values() if isinstance(n, ClassMethodNode)
        ]
        actor_ids = [n.actor._actor_id for n in method_nodes]
        if len(set(actor_ids)) != len(actor_ids):
            raise ValueError(
                "compiled DAGs need one stage per actor (an actor appears "
                "in two nodes)"
            )
        # Build stage specs: slots for DAG-node args; constants are baked in
        # by wrapping... constants unsupported beyond being pre-bound: keep
        # the reference restriction that bind args are nodes.
        for node in method_nodes:
            slots = []
            for i, arg in enumerate(node.args):
                if isinstance(arg, DAGNode):
                    slots.append(f"a{i}")
                else:
                    raise ValueError(
                        "compiled DAG args must be upstream nodes or the "
                        "InputNode (got a constant; close over it in the "
                        "actor instead)"
                    )
            self._stages[node.node_id] = {
                "actor_id": node.actor._actor_id,
                "method": node.method_name,
                "slots": slots,
                "downstream": [],
                "is_output": node.node_id == self.output_node.node_id,
            }
        # Wire edges.
        for node in method_nodes:
            for i, arg in enumerate(node.args):
                slot = f"a{i}"
                if isinstance(arg, InputNode):
                    self._input_targets.append(
                        (self._stages[node.node_id]["actor_id"], slot)
                    )
                elif isinstance(arg, ClassMethodNode):
                    self._stages[arg.node_id]["downstream"].append(
                        {
                            "actor_id": self._stages[node.node_id]["actor_id"],
                            "slot": slot,
                        }
                    )
        self._output_actor = self._stages[self.output_node.node_id]["actor_id"]
        # Register every stage with its hosting worker.
        for stage in self._stages.values():
            self._call_actor(
                stage["actor_id"],
                "dag_register",
                {"dag_id": self.dag_id, "stage": stage},
            )

    # -- worker RPC helpers ----------------------------------------------
    def _call_actor(self, actor_id: str, method: str, payload: dict) -> dict:
        async def call():
            client = await self._ctx._actor_client(actor_id)
            return await client.call(method, payload)

        return self._ctx.io.run(call())

    # -- execution -------------------------------------------------------
    def execute(self, value: Any) -> DAGRef:
        seq = next(self._seq)
        raw, _ = serialization.serialize(value)
        for actor_id, slot in self._input_targets:
            self._call_actor(
                actor_id,
                "dag_push",
                {"dag_id": self.dag_id, "seq": seq, "slot": slot, "value": raw},
            )
        return DAGRef(self, seq)

    def _pop(self, seq: int, timeout: float) -> Any:
        resp = self._call_actor(
            self._output_actor,
            "dag_pop",
            {"dag_id": self.dag_id, "seq": seq, "timeout": timeout},
        )
        if resp["status"] == "timeout":
            raise TimeoutError(f"dag output seq={seq} not ready in {timeout}s")
        value = serialization.deserialize(resp["value"], zero_copy=False)
        from ray_tpu import exceptions

        if isinstance(value, exceptions.TaskError):
            raise value
        return value

    def teardown(self) -> None:
        pass  # stages are garbage-collected with their actors

"""Compiled graphs (aDAG-equivalent) — static actor DAGs with channels.

Role-equivalent of python/ray/dag/ :: InputNode / DAGNode /
.experimental_compile (SURVEY §2.2): a static graph of actor method calls
is compiled once; every `execute()` then flows actor→actor over direct
worker RPC channels with ZERO driver round-trips between stages — the
pipeline-parallel inference substrate. On TPU, stage payloads are host
arrays; device arrays stay in each stage's HBM between its jitted calls
(and intra-slice stages exchange via in-jit collectives, not channels).

Overlap comes for free: execute() is async (returns a DAGRef), so seq k+1
enters stage 0 while seq k is in stage 1 — microbatch pipelining.

    with InputNode() as inp:
        x = worker_a.preprocess.bind(inp)
        out = worker_b.infer.bind(x)
    dag = out.experimental_compile()
    ref = dag.execute(batch)          # non-blocking
    result = ref.get(timeout=60)
"""

from __future__ import annotations

import itertools
import time
import uuid
from typing import Any, Optional

from ray_tpu._private import serialization, worker as worker_mod

_node_counter = itertools.count()


class DAGNode:
    def __init__(self):
        self.node_id = next(_node_counter)

    def experimental_compile(self) -> "CompiledDAG":
        return CompiledDAG(self)

    def _upstream(self) -> list["DAGNode"]:
        return []


class InputNode(DAGNode):
    """The DAG's input placeholder; context-manager form mirrors the
    reference (`with InputNode() as inp:`)."""

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc) -> None:
        return None


class ClassMethodNode(DAGNode):
    def __init__(self, actor_handle, method_name: str, args: tuple):
        super().__init__()
        self.actor = actor_handle
        self.method_name = method_name
        self.args = args

    def _upstream(self) -> list[DAGNode]:
        return [a for a in self.args if isinstance(a, DAGNode)]

    def execute(self, *input_values) -> Any:
        """Interpreted (uncompiled) execution via normal actor calls."""

        def resolve(node, memo):
            if node.node_id in memo:
                return memo[node.node_id]
            if isinstance(node, InputNode):
                value = input_values[0] if len(input_values) == 1 else input_values
            else:
                import ray_tpu

                args = [
                    resolve(a, memo) if isinstance(a, DAGNode) else a
                    for a in node.args
                ]
                method = getattr(node.actor, node.method_name)
                value = ray_tpu.get(method.remote(*args), timeout=300)
            memo[node.node_id] = value
            return value

        return resolve(self, {})


class _BoundMethod:
    """`actor.method.bind(...)` — installed on ActorMethod lazily."""

    def __init__(self, handle, name):
        self.handle = handle
        self.name = name

    def bind(self, *args) -> ClassMethodNode:
        return ClassMethodNode(self.handle, self.name, args)


def _install_bind() -> None:
    """Give ActorMethod a .bind() without import cycles."""
    from ray_tpu.actor import ActorMethod

    if not hasattr(ActorMethod, "bind"):
        def bind(self, *args):
            return ClassMethodNode(self._handle, self._name, args)

        ActorMethod.bind = bind


_install_bind()


class DAGRef:
    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq

    def get(self, timeout: float = 300.0) -> Any:
        return self._dag._pop(self._seq, timeout)


class CompiledDAG:
    """v2 compiled graph: multi-stage actors, pre-allocated shared-memory
    channels (co-located edges move ONE tiny notify RPC per hop — the
    payload rides the node's shm store in a bounded ring, reference
    shared_memory_channel.py role), and real teardown()."""

    CHANNEL_DEPTH = 8  # ring slots per edge = max pipelined seqs in flight

    def __init__(self, output_node: DAGNode):
        if isinstance(output_node, InputNode):
            raise ValueError("cannot compile a bare InputNode")
        self.dag_id = f"dag-{uuid.uuid4().hex[:8]}"
        self.output_node = output_node
        self._seq = itertools.count()
        self._ctx = worker_mod.get_global_context()
        self._stages: dict[int, dict] = {}  # node_id → stage spec
        self._input_targets: list[dict] = []
        self._torn_down = False
        self._inflight: set[int] = set()
        self._compile()

    def _actor_node(self, actor_id: str) -> str | None:
        """Which cluster node hosts this actor (channel co-location).
        Waits for placement: compile typically runs right after actor
        creation, before scheduling assigns a node."""
        try:
            info = self._ctx.io.run(
                self._ctx.controller.call(
                    "get_actor_info",
                    {"actor_id": actor_id, "wait_ready": True},
                    timeout=60,
                ),
                timeout=70,
            )
        except Exception:  # rtlint: disable=swallowed-exception - placement unknown: caller treats None as no co-location
            return None
        return info.get("node_id")

    # -- graph lowering --------------------------------------------------
    def _compile(self) -> None:
        nodes: dict[int, DAGNode] = {}

        def walk(node: DAGNode):
            if node.node_id in nodes:
                return
            nodes[node.node_id] = node
            for up in node._upstream():
                walk(up)

        walk(self.output_node)
        method_nodes = [
            n for n in nodes.values() if isinstance(n, ClassMethodNode)
        ]
        # Build stage specs: slots for DAG-node args; constants stay the
        # reference restriction (close over them in the actor).
        actor_nodes: dict[str, str | None] = {}
        for node in method_nodes:
            slots = []
            for i, arg in enumerate(node.args):
                if isinstance(arg, DAGNode):
                    slots.append(f"a{i}")
                else:
                    raise ValueError(
                        "compiled DAG args must be upstream nodes or the "
                        "InputNode (got a constant; close over it in the "
                        "actor instead)"
                    )
            actor_id = node.actor._actor_id
            if actor_id not in actor_nodes:
                actor_nodes[actor_id] = self._actor_node(actor_id)
            self._stages[node.node_id] = {
                "node": node.node_id,
                "actor_id": actor_id,
                "cluster_node": actor_nodes[actor_id],
                "method": node.method_name,
                "slots": slots,
                "downstream": [],
                "in_channels": [],
                "is_output": node.node_id == self.output_node.node_id,
                "depth": self.CHANNEL_DEPTH,
            }
        driver_node = self._ctx.node_id
        # Wire edges; co-located endpoints get a shm channel.
        for node in method_nodes:
            stage = self._stages[node.node_id]
            for i, arg in enumerate(node.args):
                slot = f"a{i}"
                if isinstance(arg, InputNode):
                    chan = None
                    if stage["cluster_node"] == driver_node:
                        chan = (
                            f"dagch-{self.dag_id}-in-{node.node_id}-{slot}"
                        )
                        stage["in_channels"].append(chan)
                    self._input_targets.append(
                        {
                            "actor_id": stage["actor_id"],
                            "node": node.node_id,
                            "slot": slot,
                            "channel": chan,
                        }
                    )
                elif isinstance(arg, ClassMethodNode):
                    src = self._stages[arg.node_id]
                    chan = None
                    if (
                        src["cluster_node"] is not None
                        and src["cluster_node"] == stage["cluster_node"]
                        and src["actor_id"] != stage["actor_id"]
                    ):
                        chan = (
                            f"dagch-{self.dag_id}-e{arg.node_id}-"
                            f"{node.node_id}-{slot}"
                        )
                        stage["in_channels"].append(chan)
                    src["downstream"].append(
                        {
                            "actor_id": stage["actor_id"],
                            "node": node.node_id,
                            "slot": slot,
                            "channel": chan,
                        }
                    )
        out_stage = self._stages[self.output_node.node_id]
        self._output_actor = out_stage["actor_id"]
        self._out_channel = None
        if out_stage["cluster_node"] == driver_node:
            self._out_channel = f"dagch-{self.dag_id}-out"
            out_stage["out_channel"] = self._out_channel
        # Register every stage with its hosting worker (channels are part
        # of the registration — pre-allocated at compile time).
        for stage in self._stages.values():
            self._call_actor(
                stage["actor_id"],
                "dag_register",
                {"dag_id": self.dag_id, "stage": stage},
            )

    # -- worker RPC helpers ----------------------------------------------
    def _call_actor(
        self, actor_id: str, method: str, payload: dict,
        timeout: float = 300.0,
    ) -> dict:
        ctx = self._ctx
        # Fast lane: channel notifies and pops ride the native call table
        # straight from this thread (no io-loop round trip per hop).
        conn = (
            ctx._direct_actor_conn(actor_id)
            if ctx._engine is not None
            else None
        )
        if conn is not None:
            import ctypes
            import msgpack

            from ray_tpu import _native
            from ray_tpu._private.rpc import REP, RpcError

            engine = ctx._engine
            raw = msgpack.packb(payload, use_bin_type=True)
            lib = (
                engine.pylib
                if len(raw) < engine._PYLIB_MAX_PAYLOAD
                else engine.lib
            )
            handle = lib.rt_call_start(
                engine.handle, conn[0], method.encode(), len(method),
                raw, len(raw),
            )
            if handle:
                view = _native.RtMsgView()
                rc = engine.lib.rt_call_wait(
                    engine.handle, handle, int(timeout * 1000),
                    ctypes.byref(view),
                )
                if rc == 1:
                    kind = view.kind
                    out = (
                        msgpack.unpackb(
                            ctypes.string_at(view.payload, view.plen),
                            raw=False,
                        )
                        if view.plen
                        else None
                    )
                    engine.pylib.rt_msg_free(view.opaque)
                    if kind == REP:
                        return out
                    raise RpcError(out)
                # dag methods are NOT idempotent (a pop consumes the
                # result, a push feeds a slot): once the request is on the
                # wire we must never re-issue it — surface the failure.
                engine.pylib.rt_call_abandon(engine.handle, handle)
                if rc == 0:
                    raise TimeoutError(
                        f"{method} to {actor_id} timed out after {timeout}s"
                    )
                from ray_tpu._private.rpc import ConnectionLost

                raise ConnectionLost(
                    f"{method}: connection to actor {actor_id} lost"
                )

        async def call():
            client = await ctx._actor_client(actor_id)
            return await client.call(method, payload, timeout=timeout)

        return ctx.io.run(call(), timeout=timeout + 30)

    # -- execution -------------------------------------------------------
    def execute(self, value: Any) -> DAGRef:
        if self._torn_down:
            raise RuntimeError(f"{self.dag_id} is torn down")
        # Bounded in-flight executions (the reference's max-inflight cap):
        # channel rings hold CHANNEL_DEPTH seqs per edge, so admitting
        # more un-popped executions than the ring depth would wedge the
        # submitting thread against its own un-issued pops.
        if len(self._inflight) >= self.CHANNEL_DEPTH:
            raise RuntimeError(
                f"{self.dag_id}: {len(self._inflight)} executions already "
                f"in flight (max {self.CHANNEL_DEPTH}); get() earlier "
                "results before submitting more"
            )
        seq = next(self._seq)
        self._inflight.add(seq)
        parts, total, _ = serialization.serialize_parts(value)
        raw = None
        written: set[str] = set()
        for target in self._input_targets:
            chan = target["channel"]
            msg = {
                "dag_id": self.dag_id,
                "node": target["node"],
                "seq": seq,
                "slot": target["slot"],
            }
            if chan is not None:
                if chan not in written:
                    self._chan_put(chan, seq, parts, total)
                    written.add(chan)
                msg["channel"] = chan
            else:
                if raw is None:
                    raw = serialization.join_parts(parts)
                msg["value"] = raw
            self._call_actor(target["actor_id"], "dag_push", msg)
        return DAGRef(self, seq)

    def _chan_put(self, base: str, seq: int, parts, total: int) -> None:
        """Driver-side producer: streamed ring-slot write with
        backpressure (slot freed when the consumer deletes it)."""
        from ray_tpu.dag import channel

        name = channel.slot_name(base, seq, self.CHANNEL_DEPTH)
        deadline = time.monotonic() + 120.0
        while not channel.try_write(self._ctx.store, name, parts, total):
            if time.monotonic() > deadline:
                raise TimeoutError(f"channel slot {name} stuck for 120s")
            time.sleep(0.002)

    def _pop(self, seq: int, timeout: float) -> Any:
        self._inflight.discard(seq)
        # Client deadline strictly AFTER the server-side pop wait, so the
        # timeout reply always beats the transport deadline (an abandoned
        # pop would consume the result into a dropped reply).
        resp = self._call_actor(
            self._output_actor,
            "dag_pop",
            {"dag_id": self.dag_id, "seq": seq, "timeout": timeout},
            timeout=timeout + 15,
        )
        if resp["status"] == "timeout":
            raise TimeoutError(f"dag output seq={seq} not ready in {timeout}s")
        if resp.get("channel"):
            from ray_tpu.dag import channel

            value = channel.read_consume(
                self._ctx.store,
                channel.slot_name(resp["channel"], seq, self.CHANNEL_DEPTH),
            )
        else:
            value = serialization.deserialize(resp["value"], zero_copy=False)
        from ray_tpu import exceptions

        if isinstance(value, exceptions.TaskError):
            raise value
        return value

    async def _teardown_async(self) -> None:
        for actor_id in {s["actor_id"] for s in self._stages.values()}:
            try:
                client = await self._ctx._actor_client(actor_id)
                await client.call(
                    "dag_teardown", {"dag_id": self.dag_id}, timeout=10
                )
            except Exception:  # rtlint: disable=swallowed-exception - actor may be dead; teardown is idempotent
                pass
        # Driver-owned output ring: freed here too, so the __del__ path
        # (which can only fire-and-forget this coroutine) leaks nothing.
        if self._out_channel:
            for i in range(self.CHANNEL_DEPTH):
                try:
                    self._ctx.store.delete(f"{self._out_channel}-{i}")
                except Exception:  # rtlint: disable=swallowed-exception - ring slot already freed
                    pass

    def teardown(self) -> None:
        """Release stage registrations, buffered inputs, and channel slots
        on every participating worker (and the driver's output ring)."""
        if self._torn_down:
            return
        self._torn_down = True
        try:
            import asyncio

            on_io_loop = asyncio.get_running_loop() is self._ctx.io.loop
        except RuntimeError:
            on_io_loop = False
        if on_io_loop or getattr(self._ctx, "_shutdown", False):
            # Never block the io loop (a GC-triggered __del__ can run
            # on ANY thread, including the loop itself): fire and
            # forget — worker-side teardown is idempotent.
            self._spawn_teardown()
        else:
            try:
                self._ctx.io.run(self._teardown_async(), timeout=30)
            except Exception:  # rtlint: disable=swallowed-exception - teardown race with shutdown; worker side is idempotent
                pass

    def _spawn_teardown(self) -> None:
        """Fire-and-forget teardown that never leaks an unawaited
        coroutine: if the io loop is already gone (interpreter/cluster
        shutdown), the coroutine is closed instead of dropped — a dropped
        one surfaces as a 'never awaited' RuntimeWarning, which the test
        suite escalates to an error."""
        coro = self._teardown_async()
        try:
            self._ctx.io.spawn(coro)
        except Exception:
            coro.close()

    def __del__(self):  # best-effort: a dropped DAG must not leak slots
        try:
            if not self._torn_down:
                self._torn_down = True
                self._spawn_teardown()
        except Exception:  # rtlint: disable=swallowed-exception - __del__ during interpreter teardown
            pass

"""rtdag — compiled dataflow graphs on pre-opened channels.

Role-equivalent of python/ray/dag/ :: InputNode / DAGNode /
MultiOutputNode / .experimental_compile (SURVEY §2.2): a static graph of
actor method calls is compiled ONCE — the compile-time placement plan
(dag/placement.py) pins every actor, assigns device-plane ranks, and
pre-opens every edge's channel — and every `execute()` then flows
actor→actor over those channels with ZERO controller RPCs per step.

Channel families (dag/channels.py), chosen per edge by the plan:
shm ring (co-located host payloads, pure write/poll), device plane
(collective p2p send/recv, exact or PR-7-quantized — the aDAG "NCCL
channel" role), in-process local delivery (same-actor edges), and a
legacy socket fallback. Workers run one resident executor loop per
stage (dag/executor.py); bounded in-flight `execute()` pipelining gets
its backpressure from the ring depth.

Every channel op records into the comm flight ring under
``flight.site("dag")`` and device tags follow the rtgraph skeleton
convention, so the watchdog/hang-doctor/commgraph planes cover compiled
graphs like any other wire.

    with InputNode() as inp:
        x = worker_a.preprocess.bind(inp)
        out = worker_b.infer.bind(x)
    dag = out.experimental_compile()      # or compile(channel="device")
    ref = dag.execute(batch)              # non-blocking, zero RPCs
    result = ref.get(timeout=60)
    dag.close()                           # drain + free + stop loops
"""

from __future__ import annotations

import asyncio
import itertools
import time
import uuid
import weakref
from typing import Any

from ray_tpu import exceptions
from ray_tpu._private import serialization, worker as worker_mod
from ray_tpu.dag import placement
from ray_tpu.dag.channels import DeviceChannel, ShmChannel

_node_counter = itertools.count()

_CHANNEL_FAMILIES = (None, "auto", "shm", "device", "socket")

# Live compiled graphs, closed from the driver shutdown path so resident
# worker loops and ring slots never outlive the session.
_LIVE_DAGS: "weakref.WeakValueDictionary[str, CompiledDAG]" = (
    weakref.WeakValueDictionary()
)


def shutdown_all() -> None:
    """Tear down every live compiled DAG (driver shutdown hook)."""
    for dag in list(_LIVE_DAGS.values()):
        try:
            dag.teardown()
        except Exception:  # rtlint: disable=swallowed-exception - shutdown must proceed past a dead graph
            pass


class DAGNode:
    def __init__(self):
        self.node_id = next(_node_counter)
        self.channel_hint: str | None = None

    def with_channel(self, family: str) -> "DAGNode":
        """Per-node channel-family hint for the edges that feed this
        node (and its output edge when it is a DAG output): "shm",
        "device", "socket", or "auto" (clear the hint)."""
        if family not in ("auto", "shm", "device", "socket"):
            raise ValueError(
                f"unknown channel family {family!r} "
                "(use 'auto', 'shm', 'device', or 'socket')"
            )
        self.channel_hint = None if family == "auto" else family
        return self

    def experimental_compile(
        self, channel: str | None = None, quantize_wire: str | None = None
    ) -> "CompiledDAG":
        return CompiledDAG(
            self, channel=channel, quantize_wire=quantize_wire
        )

    def _upstream(self) -> list["DAGNode"]:
        return []


class InputNode(DAGNode):
    """The DAG's input placeholder; context-manager form mirrors the
    reference (`with InputNode() as inp:`)."""

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc) -> None:
        return None


def _interpret(node: "DAGNode", input_values: tuple, memo: dict) -> Any:
    """Shared interpreted (uncompiled) executor — one actor call per
    node, memoized so fan-out nodes run once."""
    if node.node_id in memo:
        return memo[node.node_id]
    if isinstance(node, InputNode):
        value = input_values[0] if len(input_values) == 1 else input_values
    else:
        import ray_tpu

        args = [
            _interpret(a, input_values, memo) if isinstance(a, DAGNode)
            else a
            for a in node.args
        ]
        method = getattr(node.actor, node.method_name)
        value = ray_tpu.get(method.remote(*args), timeout=300)
    memo[node.node_id] = value
    return value


class ClassMethodNode(DAGNode):
    def __init__(self, actor_handle, method_name: str, args: tuple):
        super().__init__()
        self.actor = actor_handle
        self.method_name = method_name
        self.args = args

    def _upstream(self) -> list[DAGNode]:
        return [a for a in self.args if isinstance(a, DAGNode)]

    def execute(self, *input_values) -> Any:
        """Interpreted (uncompiled) execution via normal actor calls."""
        return _interpret(self, input_values, {})


class MultiOutputNode(DAGNode):
    """Marks several graph nodes as the DAG's outputs: `execute().get()`
    returns their values as a list, each member riding its own output
    channel (the reference's MultiOutputNode role)."""

    def __init__(self, nodes):
        super().__init__()
        self.nodes = list(nodes)
        if not self.nodes:
            raise ValueError("MultiOutputNode needs at least one node")
        for n in self.nodes:
            if not isinstance(n, ClassMethodNode):
                raise ValueError(
                    "MultiOutputNode members must be actor method nodes "
                    f"(got {type(n).__name__})"
                )

    def _upstream(self) -> list[DAGNode]:
        return list(self.nodes)

    def execute(self, *input_values) -> list:
        memo: dict = {}
        return [_interpret(n, input_values, memo) for n in self.nodes]


class _BoundMethod:
    """`actor.method.bind(...)` — installed on ActorMethod lazily."""

    def __init__(self, handle, name):
        self.handle = handle
        self.name = name

    def bind(self, *args) -> ClassMethodNode:
        return ClassMethodNode(self.handle, self.name, args)


def _install_bind() -> None:
    """Give ActorMethod a .bind() without import cycles."""
    from ray_tpu.actor import ActorMethod

    if not hasattr(ActorMethod, "bind"):
        def bind(self, *args):
            return ClassMethodNode(self._handle, self._name, args)

        ActorMethod.bind = bind


_install_bind()


class DAGRef:
    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq

    def get(self, timeout: float = 300.0) -> Any:
        return self._dag._pop(self._seq, timeout)


class _OutReader:
    """Driver-side in-order consumer of ONE output edge. Channel seqs
    are strictly ordered, so an out-of-order get() buffers the earlier
    seqs it drains on the way."""

    def __init__(self, dag: "CompiledDAG", actor_id: str, out: dict,
                 chan):
        self._dag = dag
        self._actor_id = actor_id
        self._out = out
        self._chan = chan
        self._next = 0
        self._ready: dict[int, Any] = {}

    def read(self, seq: int, deadline: float) -> Any:
        if self._out["family"] == "socket":
            return self._socket_pop(seq, deadline)
        while seq not in self._ready:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(f"dag output seq={seq} not ready")
            if self._out["family"] == "shm":
                value = self._chan.pop(self._next, timeout=remaining)
            else:
                value = self._chan.pop_edge(timeout=remaining)
            self._ready[self._next] = value
            self._next += 1
        return self._ready.pop(seq)

    def _socket_pop(self, seq: int, deadline: float) -> Any:
        remaining = max(0.1, deadline - time.monotonic())
        # Client deadline strictly AFTER the server-side pop wait, so the
        # timeout reply always beats the transport deadline (an abandoned
        # pop would consume the result into a dropped reply).
        resp = self._dag._call_actor(
            self._actor_id, "dag_pop",
            {"dag_id": self._dag.dag_id, "seq": seq, "timeout": remaining},
            timeout=remaining + 15,
        )
        if resp.get("status") == "timeout":
            raise TimeoutError(f"dag output seq={seq} not ready")
        if resp.get("status") != "ok":
            raise RuntimeError(
                f"dag_pop failed: {resp.get('error', resp)!r}"
            )
        return serialization.deserialize(resp["value"], zero_copy=False)


class CompiledDAG:
    """rtdag compiled graph: placement-planned stages, pre-opened
    channels on every edge, resident worker loops, bounded in-flight
    pipelining with ring-depth backpressure, and real close()."""

    CHANNEL_DEPTH = 8  # ring slots per edge = max pipelined seqs in flight

    def __init__(self, output_node: DAGNode, *, channel: str | None = None,
                 quantize_wire: str | None = None):
        if isinstance(output_node, InputNode):
            raise ValueError("cannot compile a bare InputNode")
        if channel not in _CHANNEL_FAMILIES:
            raise ValueError(
                f"unknown channel family {channel!r} "
                f"(use one of {_CHANNEL_FAMILIES[1:]})"
            )
        self.dag_id = f"dag-{uuid.uuid4().hex[:8]}"
        self.output_node = output_node
        self._channel_override = None if channel == "auto" else channel
        self._quantize_wire = quantize_wire
        self._out_nodes = (
            list(output_node.nodes)
            if isinstance(output_node, MultiOutputNode)
            else [output_node]
        )
        self._multi_output = isinstance(output_node, MultiOutputNode)
        self._seq = itertools.count()
        self._ctx = worker_mod.get_global_context()
        self._stages: dict[int, dict] = {}  # node_id → stage spec
        self._input_targets: list[dict] = []
        self._out_readers: list[_OutReader] = []
        self._out_channel = None  # first output channel (back-compat)
        self._all_shm_bases: list[str] = []
        self._group = None
        self._torn_down = False
        self._inflight: set[int] = set()
        self._compile()
        _LIVE_DAGS[self.dag_id] = self

    # -- graph lowering --------------------------------------------------
    def _compile(self) -> None:
        nodes: dict[int, DAGNode] = {}

        def walk(node: DAGNode):
            if node.node_id in nodes:
                return
            nodes[node.node_id] = node
            for up in node._upstream():
                walk(up)

        walk(self.output_node)
        method_nodes = sorted(
            (n for n in nodes.values() if isinstance(n, ClassMethodNode)),
            key=lambda n: n.node_id,
        )
        if not method_nodes:
            raise ValueError("DAG has no actor method nodes")
        # Stage skeletons: slots for DAG-node args; constants stay the
        # reference restriction (close over them in the actor).
        for node in method_nodes:
            slots = []
            for i, arg in enumerate(node.args):
                if isinstance(arg, DAGNode):
                    slots.append(f"a{i}")
                else:
                    raise ValueError(
                        "compiled DAG args must be upstream nodes or the "
                        "InputNode (got a constant; close over it in the "
                        "actor instead)"
                    )
            self._stages[node.node_id] = {
                "node": node.node_id,
                "actor_id": node.actor._actor_id,
                "method": node.method_name,
                "slots": slots,
                "in_edges": [],
                "downstream": [],
                "outs": [],
                "is_output": False,
                "depth": self.CHANNEL_DEPTH,
            }
        # Explicit compile-time placement (no swallowed probe): pins each
        # actor's node, assigns device-plane ranks, raises on failure.
        ordered_actors: list[str] = []
        for node in method_nodes:
            aid = node.actor._actor_id
            if aid not in ordered_actors:
                ordered_actors.append(aid)
        self._actor_ids = ordered_actors
        plan = placement.PlacementPlan.resolve(self._ctx, ordered_actors)
        self._plan = plan
        families: set[str] = set()

        # -- wire edges --------------------------------------------------
        for node in method_nodes:
            stage = self._stages[node.node_id]
            dst_aid = stage["actor_id"]
            for i, arg in enumerate(node.args):
                slot = f"a{i}"
                if isinstance(arg, InputNode):
                    fam = placement.edge_family(
                        plan, None, dst_aid, node.channel_hint,
                        self._channel_override,
                    )
                    families.add(fam)
                    edge = {
                        "slot": slot, "family": fam, "src": arg.node_id,
                        "dst": node.node_id, "slot_id": i,
                    }
                    target = {
                        "actor_id": dst_aid, "node": node.node_id,
                        "slot": slot, "family": fam, "channel": None,
                        "src": arg.node_id, "dst": node.node_id,
                        "slot_id": i, "chan": None,
                    }
                    if fam == "shm":
                        base = f"dagch-{self.dag_id}-in-{node.node_id}-{slot}"
                        edge["channel"] = base
                        target["channel"] = base
                        self._all_shm_bases.append(base)
                    elif fam == "device":
                        edge["peer_rank"] = 0
                        target["channel"] = (
                            f"dagch:e{arg.node_id}:{node.node_id}:{i}"
                        )
                    stage["in_edges"].append(edge)
                    self._input_targets.append(target)
                else:  # ClassMethodNode
                    src_stage = self._stages[arg.node_id]
                    src_aid = src_stage["actor_id"]
                    fam = placement.edge_family(
                        plan, src_aid, dst_aid, node.channel_hint,
                        self._channel_override,
                    )
                    families.add(fam)
                    common = {
                        "src": arg.node_id, "dst": node.node_id,
                        "slot_id": i,
                    }
                    in_edge = {"slot": slot, "family": fam, **common}
                    down = {
                        "actor_id": dst_aid, "node": node.node_id,
                        "slot": slot, "family": fam, **common,
                    }
                    if fam == "shm":
                        base = (
                            f"dagch-{self.dag_id}-e{arg.node_id}-"
                            f"{node.node_id}-{slot}"
                        )
                        in_edge["channel"] = base
                        down["channel"] = base
                        self._all_shm_bases.append(base)
                    elif fam == "device":
                        in_edge["peer_rank"] = plan.rank_of(src_aid)
                        down["peer_rank"] = plan.rank_of(dst_aid)
                    src_stage["downstream"].append(down)
                    stage["in_edges"].append(in_edge)
        # -- output edges ------------------------------------------------
        out_specs: list[tuple[str, dict]] = []
        for k, out_node in enumerate(self._out_nodes):
            stage = self._stages[out_node.node_id]
            stage["is_output"] = True
            aid = stage["actor_id"]
            fam = placement.edge_family(
                plan, aid, None, out_node.channel_hint,
                self._channel_override,
            )
            families.add(fam)
            out = {
                "family": fam, "src": out_node.node_id,
                "dst": next(_node_counter), "slot_id": 0,
            }
            if fam == "shm":
                out["channel"] = f"dagch-{self.dag_id}-out-{k}"
                self._all_shm_bases.append(out["channel"])
            elif fam == "device":
                out["peer_rank"] = 0
            stage["outs"].append(out)
            out_specs.append((aid, out))
            if self._out_channel is None:
                self._out_channel = out.get("channel") or (
                    f"dagch:e{out['src']}:{out['dst']}:0"
                    if fam == "device" else None
                )
        if (
            self._multi_output
            and sum(1 for _, o in out_specs if o["family"] == "socket") > 1
        ):
            raise ValueError(
                "the socket fallback supports a single output edge; use "
                "shm or device channels for MultiOutputNode graphs"
            )
        self._register(plan, need_group="device" in families)
        # -- driver-side channel objects ---------------------------------
        wire_cfg, ef = self._make_wire_codec()
        store = self._ctx.store
        for t in self._input_targets:
            if t["family"] == "shm":
                t["chan"] = ShmChannel(
                    store, t["channel"], self.CHANNEL_DEPTH,
                    group=self.dag_id,
                )
            elif t["family"] == "device":
                t["chan"] = DeviceChannel(
                    self._group, plan.rank_of(t["actor_id"]),
                    src=t["src"], dst=t["dst"], slot=t["slot_id"],
                    wire_cfg=wire_cfg, ef=ef,
                )
        for aid, out in out_specs:
            chan = None
            if out["family"] == "shm":
                chan = ShmChannel(
                    store, out["channel"], self.CHANNEL_DEPTH,
                    group=self.dag_id,
                )
            elif out["family"] == "device":
                chan = DeviceChannel(
                    self._group, plan.rank_of(aid), src=out["src"],
                    dst=out["dst"], slot=out["slot_id"],
                )
            self._out_readers.append(_OutReader(self, aid, out, chan))

    def _make_wire_codec(self):
        if not self._quantize_wire:
            return None, None
        from ray_tpu.util.collective.quantization import (
            CollectiveConfig,
            ErrorFeedback,
        )

        cfg = CollectiveConfig(quantize_activations=self._quantize_wire)
        return cfg.activation_wire_config(), ErrorFeedback()

    def _register(self, plan: placement.PlacementPlan,
                  need_group: bool) -> None:
        """Register stage bundles on every participating worker; when
        device edges exist, rendezvous the per-DAG collective group (the
        driver is rank 0). The register RPCs are issued CONCURRENTLY
        with the driver's own group init — each worker's handler blocks
        in the group rendezvous until all ranks (driver included) have
        registered, so awaiting acks first would deadlock."""
        by_actor: dict[str, list] = {}
        for stage in self._stages.values():
            by_actor.setdefault(stage["actor_id"], []).append(stage)
        ctx = self._ctx

        async def _register_all():
            async def one(aid: str):
                client = await ctx._actor_client(aid)
                resp = await client.call("dag_register", {
                    "dag_id": self.dag_id,
                    "stages": by_actor[aid],
                    "depth": self.CHANNEL_DEPTH,
                    "wire_quant": self._quantize_wire,
                    "group": (
                        {
                            "name": self.dag_id,
                            "world_size": plan.world_size,
                            "rank": plan.rank_of(aid),
                        }
                        if need_group else None
                    ),
                }, timeout=120)
                if (resp or {}).get("status") != "ok":
                    raise RuntimeError(
                        f"dag_register failed on actor {aid}: {resp!r}"
                    )

            await asyncio.gather(*[one(aid) for aid in by_actor])

        if not need_group:
            ctx.io.run(_register_all(), timeout=180)
            return
        from ray_tpu.util.collective import collective

        fut = asyncio.run_coroutine_threadsafe(_register_all(), ctx.io.loop)
        try:
            collective.init_collective_group(
                plan.world_size, 0, backend="ring", group_name=self.dag_id
            )
            self._group = collective.get_group(self.dag_id)
            fut.result(timeout=180)
        except Exception:
            fut.cancel()
            self._destroy_group(sync=True)
            raise

    # -- worker RPC helpers ----------------------------------------------
    def _call_actor(
        self, actor_id: str, method: str, payload: dict,
        timeout: float = 300.0,
    ) -> dict:
        ctx = self._ctx
        # Fast lane: socket-family pushes and pops ride the native call
        # table straight from this thread (no io-loop round trip per hop).
        conn = (
            ctx._direct_actor_conn(actor_id)
            if ctx._engine is not None
            else None
        )
        if conn is not None:
            import ctypes
            import msgpack

            from ray_tpu import _native
            from ray_tpu._private.rpc import REP, RpcError

            engine = ctx._engine
            raw = msgpack.packb(payload, use_bin_type=True)
            lib = (
                engine.pylib
                if len(raw) < engine._PYLIB_MAX_PAYLOAD
                else engine.lib
            )
            handle = lib.rt_call_start(
                engine.handle, conn[0], method.encode(), len(method),
                raw, len(raw),
            )
            if handle:
                view = _native.RtMsgView()
                rc = engine.lib.rt_call_wait(
                    engine.handle, handle, int(timeout * 1000),
                    ctypes.byref(view),
                )
                if rc == 1:
                    kind = view.kind
                    out = (
                        msgpack.unpackb(
                            ctypes.string_at(view.payload, view.plen),
                            raw=False,
                        )
                        if view.plen
                        else None
                    )
                    engine.pylib.rt_msg_free(view.opaque)
                    if kind == REP:
                        return out
                    raise RpcError(out)
                # dag methods are NOT idempotent (a pop consumes the
                # result, a push feeds a slot): once the request is on the
                # wire we must never re-issue it — surface the failure.
                engine.pylib.rt_call_abandon(engine.handle, handle)
                if rc == 0:
                    raise TimeoutError(
                        f"{method} to {actor_id} timed out after {timeout}s"
                    )
                from ray_tpu._private.rpc import ConnectionLost

                raise ConnectionLost(
                    f"{method}: connection to actor {actor_id} lost"
                )

        async def call():
            client = await ctx._actor_client(actor_id)
            return await client.call(method, payload, timeout=timeout)

        return ctx.io.run(call(), timeout=timeout + 30)

    # -- execution -------------------------------------------------------
    def execute(self, value: Any) -> DAGRef:
        if self._torn_down:
            raise RuntimeError(f"{self.dag_id} is torn down")
        # Bounded in-flight executions (the reference's max-inflight cap):
        # channel rings hold CHANNEL_DEPTH seqs per edge, so admitting
        # more un-popped executions than the ring depth would wedge the
        # submitting thread against its own un-issued pops.
        if len(self._inflight) >= self.CHANNEL_DEPTH:
            raise RuntimeError(
                f"{self.dag_id}: {len(self._inflight)} executions already "
                f"in flight (max {self.CHANNEL_DEPTH}); get() earlier "
                "results before submitting more"
            )
        seq = next(self._seq)
        self._inflight.add(seq)
        parts = total = raw = None
        for target in self._input_targets:
            fam = target["family"]
            if fam == "shm":
                if parts is None:
                    parts, total, _ = serialization.serialize_parts(value)
                target["chan"].push_parts(seq, parts, total)
            elif fam == "device":
                target["chan"].push_edge(value)
            else:  # socket fallback: one RPC per push
                if raw is None:
                    raw = serialization.join_parts(
                        serialization.serialize_parts(value)[0]
                    )
                self._call_actor(target["actor_id"], "dag_push", {
                    "dag_id": self.dag_id, "node": target["node"],
                    "seq": seq, "slot": target["slot"], "value": raw,
                })
        return DAGRef(self, seq)

    def _pop(self, seq: int, timeout: float) -> Any:
        self._inflight.discard(seq)
        deadline = time.monotonic() + timeout
        values = []
        for reader in self._out_readers:
            try:
                values.append(reader.read(seq, deadline))
            except (TimeoutError, asyncio.TimeoutError):
                self._raise_pop_timeout(seq, timeout)
        errors = [v for v in values if isinstance(v, exceptions.TaskError)]
        if errors:
            raise errors[0]
        return values if self._multi_output else values[0]

    def _raise_pop_timeout(self, seq: int, timeout: float) -> None:
        """A pop timeout on a static graph means either a dead stage or a
        genuinely slow one — probe actor liveness so the caller gets a
        typed death error instead of a bare timeout."""
        for aid in self._actor_ids:
            try:
                info = self._ctx.io.run(
                    self._ctx.controller.call(
                        "get_actor_info", {"actor_id": aid}, timeout=10
                    ),
                    timeout=15,
                )
            except Exception:  # rtlint: disable=swallowed-exception - controller unreachable: fall through to the plain timeout
                continue
            if (info or {}).get("state") == "DEAD":
                raise exceptions.DAGActorDiedError(
                    self.dag_id, aid, self._plan.rank_of(aid),
                    detail=str((info or {}).get("death_cause") or ""),
                )
        raise TimeoutError(
            f"dag output seq={seq} not ready in {timeout}s"
        )

    # -- teardown ---------------------------------------------------------
    def close(self, timeout: float = 30.0) -> None:
        """Drain in-flight executions, stop the resident worker loops,
        and free every channel ring slot. Idempotent."""
        if self._torn_down:
            return
        self._torn_down = True
        _LIVE_DAGS.pop(self.dag_id, None)
        # Drain admitted-but-unpopped seqs so no worker loop is wedged
        # mid-push when the teardown RPC lands.
        for seq in sorted(self._inflight):
            deadline = time.monotonic() + min(5.0, timeout)
            for reader in self._out_readers:
                try:
                    reader.read(seq, deadline)
                except Exception:  # rtlint: disable=swallowed-exception - draining a dead or torn graph; slots are freed below regardless
                    pass
        self._inflight.clear()
        try:
            self._ctx.io.run(self._teardown_async(), timeout=timeout)
        except Exception:  # rtlint: disable=swallowed-exception - teardown race with shutdown; worker side is idempotent
            pass
        self._destroy_group(sync=True)

    def teardown(self) -> None:
        """Back-compat alias for close(); safe to call from the io loop
        or a GC finalizer (falls back to fire-and-forget there)."""
        if self._torn_down:
            return
        try:
            on_io_loop = asyncio.get_running_loop() is self._ctx.io.loop
        except RuntimeError:
            on_io_loop = False
        if on_io_loop or getattr(self._ctx, "_shutdown", False):
            # Never block the io loop (a GC-triggered __del__ can run
            # on ANY thread, including the loop itself): fire and
            # forget — worker-side teardown is idempotent.
            self._torn_down = True
            _LIVE_DAGS.pop(self.dag_id, None)
            self._spawn_teardown()
            self._destroy_group(sync=False)
        else:
            self.close()

    async def _teardown_async(self) -> None:
        for actor_id in self._actor_ids:
            try:
                client = await self._ctx._actor_client(actor_id)
                await client.call(
                    "dag_teardown", {"dag_id": self.dag_id}, timeout=10
                )
            except Exception:  # rtlint: disable=swallowed-exception - actor may be dead; teardown is idempotent
                pass
        # Driver-side backstop: every shm ring slot of this DAG (input,
        # inter-stage, and output rings) — a dead worker must not leak
        # its consumer-owned slots, and the driver-owned output ring is
        # freed here so the __del__ fire-and-forget path leaks nothing.
        for base in self._all_shm_bases:
            for i in range(self.CHANNEL_DEPTH):
                try:
                    self._ctx.store.delete(f"{base}-{i}")
                except Exception:  # rtlint: disable=swallowed-exception - ring slot already freed
                    pass

    def _destroy_group(self, sync: bool) -> None:
        if self._group is None:
            return
        from ray_tpu.util.collective import collective

        if sync:
            try:
                collective.destroy_collective_group(self.dag_id)
            except Exception:  # rtlint: disable=swallowed-exception - rendezvous keys die with the controller; the registry entry is what must go
                collective._groups.pop(self.dag_id, None)
        else:
            # destroy() round-trips the controller KV via the io loop we
            # may be ON: drop the registry entry only.
            collective._groups.pop(self.dag_id, None)
        self._group = None

    def _spawn_teardown(self) -> None:
        """Fire-and-forget teardown that never leaks an unawaited
        coroutine: if the io loop is already gone (interpreter/cluster
        shutdown), the coroutine is closed instead of dropped — a dropped
        one surfaces as a 'never awaited' RuntimeWarning, which the test
        suite escalates to an error."""
        coro = self._teardown_async()
        try:
            self._ctx.io.spawn(coro)
        except Exception:
            coro.close()

    def __del__(self):  # best-effort: a dropped DAG must not leak slots
        try:
            if not self._torn_down:
                self._torn_down = True
                self._spawn_teardown()
                self._destroy_group(sync=False)
        except Exception:  # rtlint: disable=swallowed-exception - __del__ during interpreter teardown
            pass

"""rtdag — compiled dataflow graphs on pre-opened channels.

Role-equivalent of python/ray/dag/ :: InputNode / DAGNode /
MultiOutputNode / .experimental_compile (SURVEY §2.2): a static graph of
actor method calls is compiled ONCE — the compile-time placement plan
(dag/placement.py) pins every actor, assigns device-plane ranks, and
pre-opens every edge's channel — and every `execute()` then flows
actor→actor over those channels with ZERO controller RPCs per step.

Channel families (dag/channels.py), chosen per edge by the plan:
shm ring (co-located host payloads, pure write/poll), device plane
(collective p2p send/recv, exact or PR-7-quantized — the aDAG "NCCL
channel" role), in-process local delivery (same-actor edges), and a
legacy socket fallback. Workers run one resident executor loop per
stage (dag/executor.py); bounded in-flight `execute()` pipelining gets
its backpressure from the ring depth.

Every channel op records into the comm flight ring under
``flight.site("dag")`` and device tags follow the rtgraph skeleton
convention, so the watchdog/hang-doctor/commgraph planes cover compiled
graphs like any other wire.

    with InputNode() as inp:
        x = worker_a.preprocess.bind(inp)
        out = worker_b.infer.bind(x)
    dag = out.experimental_compile()      # or compile(channel="device")
    ref = dag.execute(batch)              # non-blocking, zero RPCs
    result = ref.get(timeout=60)
    dag.close()                           # drain + free + stop loops
"""

from __future__ import annotations

import asyncio
import itertools
import threading
import time
import uuid
import weakref
from typing import Any

from ray_tpu import exceptions
from ray_tpu._private import serialization, worker as worker_mod
from ray_tpu.dag import placement
from ray_tpu.dag.channels import DeviceChannel, ShmChannel
from ray_tpu.util import tracing

_node_counter = itertools.count()

_CHANNEL_FAMILIES = (None, "auto", "shm", "device", "socket")

# Live compiled graphs, closed from the driver shutdown path so resident
# worker loops and ring slots never outlive the session.
_LIVE_DAGS: "weakref.WeakValueDictionary[str, CompiledDAG]" = (
    weakref.WeakValueDictionary()
)


def shutdown_all() -> None:
    """Tear down every live compiled DAG (driver shutdown hook)."""
    for dag in list(_LIVE_DAGS.values()):
        try:
            dag.teardown()
        except Exception:  # rtlint: disable=swallowed-exception - shutdown must proceed past a dead graph
            pass


class DAGNode:
    def __init__(self):
        self.node_id = next(_node_counter)
        self.channel_hint: str | None = None

    def with_channel(self, family: str) -> "DAGNode":
        """Per-node channel-family hint for the edges that feed this
        node (and its output edge when it is a DAG output): "shm",
        "device", "socket", or "auto" (clear the hint)."""
        if family not in ("auto", "shm", "device", "socket"):
            raise ValueError(
                f"unknown channel family {family!r} "
                "(use 'auto', 'shm', 'device', or 'socket')"
            )
        self.channel_hint = None if family == "auto" else family
        return self

    def experimental_compile(
        self, channel: str | None = None, quantize_wire: str | None = None,
        supervise: bool = False, max_recoveries: int = 3,
    ) -> "CompiledDAG":
        return CompiledDAG(
            self, channel=channel, quantize_wire=quantize_wire,
            supervise=supervise, max_recoveries=max_recoveries,
        )

    def _upstream(self) -> list["DAGNode"]:
        return []


class InputNode(DAGNode):
    """The DAG's input placeholder; context-manager form mirrors the
    reference (`with InputNode() as inp:`)."""

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc) -> None:
        return None


def _interpret(node: "DAGNode", input_values: tuple, memo: dict) -> Any:
    """Shared interpreted (uncompiled) executor — one actor call per
    node, memoized so fan-out nodes run once."""
    if node.node_id in memo:
        return memo[node.node_id]
    if isinstance(node, InputNode):
        value = input_values[0] if len(input_values) == 1 else input_values
    else:
        import ray_tpu

        args = [
            _interpret(a, input_values, memo) if isinstance(a, DAGNode)
            else a
            for a in node.args
        ]
        method = getattr(node.actor, node.method_name)
        value = ray_tpu.get(method.remote(*args), timeout=300)
    memo[node.node_id] = value
    return value


class ClassMethodNode(DAGNode):
    def __init__(self, actor_handle, method_name: str, args: tuple):
        super().__init__()
        self.actor = actor_handle
        self.method_name = method_name
        self.args = args

    def _upstream(self) -> list[DAGNode]:
        return [a for a in self.args if isinstance(a, DAGNode)]

    def execute(self, *input_values) -> Any:
        """Interpreted (uncompiled) execution via normal actor calls."""
        return _interpret(self, input_values, {})


class MultiOutputNode(DAGNode):
    """Marks several graph nodes as the DAG's outputs: `execute().get()`
    returns their values as a list, each member riding its own output
    channel (the reference's MultiOutputNode role)."""

    def __init__(self, nodes):
        super().__init__()
        self.nodes = list(nodes)
        if not self.nodes:
            raise ValueError("MultiOutputNode needs at least one node")
        for n in self.nodes:
            if not isinstance(n, ClassMethodNode):
                raise ValueError(
                    "MultiOutputNode members must be actor method nodes "
                    f"(got {type(n).__name__})"
                )

    def _upstream(self) -> list[DAGNode]:
        return list(self.nodes)

    def execute(self, *input_values) -> list:
        memo: dict = {}
        return [_interpret(n, input_values, memo) for n in self.nodes]


class _BoundMethod:
    """`actor.method.bind(...)` — installed on ActorMethod lazily."""

    def __init__(self, handle, name):
        self.handle = handle
        self.name = name

    def bind(self, *args) -> ClassMethodNode:
        return ClassMethodNode(self.handle, self.name, args)


def _install_bind() -> None:
    """Give ActorMethod a .bind() without import cycles."""
    from ray_tpu.actor import ActorMethod

    if not hasattr(ActorMethod, "bind"):
        def bind(self, *args):
            return ClassMethodNode(self._handle, self._name, args)

        ActorMethod.bind = bind


_install_bind()


class DAGRef:
    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq

    def get(self, timeout: float = 300.0) -> Any:
        return self._dag._pop(self._seq, timeout)


# Supervised driver pops run in short slices so the supervisor can probe
# actor liveness while blocked (unsupervised pops stay full-timeout — the
# blocked record is what feeds the comm watchdog's stall detection).
_DRIVER_POP_SLICE_S = 0.5


class _OutReader:
    """Driver-side in-order consumer of ONE output edge. Channel seqs
    are strictly ordered, so an out-of-order get() buffers the earlier
    seqs it drains on the way.

    Recovery support: ``_next`` is the CHANNEL cursor (next seq to pop
    off the wire); ``_discard_below`` is the replay-dedup frontier. After
    a crash recovery the supervisor refits this reader onto the
    re-opened epoch and rewinds the channel cursor to the replay base —
    replayed frames below the old cursor are popped and dropped, so the
    caller never sees a duplicate."""

    def __init__(self, dag: "CompiledDAG", actor_id: str, out: dict,
                 chan):
        self._dag = dag
        self._actor_id = actor_id
        self._out = out
        self._chan = chan
        self._next = 0
        self._discard_below = 0
        self._ready: dict[int, Any] = {}

    def refit(self, out: dict, chan, start_seq: int) -> None:
        """Point this reader at the post-recovery channel (new epoch,
        possibly a new family if the replacement actor moved nodes) and
        rewind the channel cursor to the replay base; everything already
        drained stays deduplicated via ``_discard_below``."""
        self._out = out
        self._chan = chan
        self._discard_below = max(self._discard_below, self._next)
        self._next = start_seq

    def read(self, seq: int, deadline: float) -> Any:
        if self._out["family"] == "socket":
            return self._socket_pop(seq, deadline)
        while seq not in self._ready:
            self.drain_one(deadline)
        return self._ready.pop(seq)

    def drain_one(self, deadline: float) -> None:
        """Pop the next channel seq into the ready buffer (or discard it
        as a replay duplicate). Supervised DAGs pop in short slices,
        probing liveness between slices; unsupervised DAGs block the
        full remaining timeout (the watchdog-visible stall)."""
        sliced = self._dag._supervise
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"dag output seq={self._next} not ready"
                )
            slice_s = (
                min(remaining, _DRIVER_POP_SLICE_S) if sliced else remaining
            )
            try:
                if self._out["family"] == "shm":
                    value = self._chan.pop(self._next, timeout=slice_s)
                else:
                    value = self._chan.pop_edge(timeout=slice_s)
                break
            except (TimeoutError, asyncio.TimeoutError):
                if not sliced or slice_s >= remaining:
                    raise
                # Slow slice, time still left: probe (raises a typed
                # death error if an actor is gone; a slow-but-alive
                # graph just keeps waiting — no false-positive restart).
                self._dag._maybe_probe(self._out, self._next)
        if self._next >= self._discard_below:
            self._ready[self._next] = value
        else:
            self._dag.replay_discards += 1
        self._next += 1

    def _socket_pop(self, seq: int, deadline: float) -> Any:
        remaining = max(0.1, deadline - time.monotonic())
        # Client deadline strictly AFTER the server-side pop wait, so the
        # timeout reply always beats the transport deadline (an abandoned
        # pop would consume the result into a dropped reply).
        resp = self._dag._call_actor(
            self._actor_id, "dag_pop",
            {"dag_id": self._dag.dag_id, "seq": seq, "timeout": remaining},
            timeout=remaining + 15,
        )
        if resp.get("status") == "timeout":
            raise TimeoutError(f"dag output seq={seq} not ready")
        if resp.get("status") != "ok":
            raise RuntimeError(
                f"dag_pop failed: {resp.get('error', resp)!r}"
            )
        return serialization.deserialize(resp["value"], zero_copy=False)


class CompiledDAG:
    """rtdag compiled graph: placement-planned stages, pre-opened
    channels on every edge, resident worker loops, bounded in-flight
    pipelining with ring-depth backpressure, and real close()."""

    CHANNEL_DEPTH = 8  # ring slots per edge = max pipelined seqs in flight

    # Supervised liveness probing: how long a blocked driver pop waits
    # between probes when nothing flagged a stall (the flight watchdog's
    # stall listener short-circuits this).
    PROBE_INTERVAL_S = 2.0

    def __init__(self, output_node: DAGNode, *, channel: str | None = None,
                 quantize_wire: str | None = None, supervise: bool = False,
                 max_recoveries: int = 3):
        if isinstance(output_node, InputNode):
            raise ValueError("cannot compile a bare InputNode")
        if channel not in _CHANNEL_FAMILIES:
            raise ValueError(
                f"unknown channel family {channel!r} "
                f"(use one of {_CHANNEL_FAMILIES[1:]})"
            )
        self.dag_id = f"dag-{uuid.uuid4().hex[:8]}"
        self.output_node = output_node
        self._channel_override = None if channel == "auto" else channel
        self._quantize_wire = quantize_wire
        self._out_nodes = (
            list(output_node.nodes)
            if isinstance(output_node, MultiOutputNode)
            else [output_node]
        )
        self._multi_output = isinstance(output_node, MultiOutputNode)
        self._submitted = 0  # next execute() seq (replaces a bare count())
        self._ctx = worker_mod.get_global_context()
        self._stages: dict[int, dict] = {}  # node_id → stage spec
        self._input_targets: list[dict] = []
        self._out_readers: list[_OutReader] = []
        self._out_channel = None  # first output channel (back-compat)
        self._all_shm_bases: list[str] = []
        self._group = None
        self._group_name: str | None = None
        self._torn_down = False
        self._inflight: set[int] = set()
        # -- self-healing state (costs nothing until a failure) ----------
        self._supervise = bool(supervise)
        self._max_recoveries = int(max_recoveries)
        self._epoch = 0
        self.recoveries = 0
        self.replay_discards = 0
        self.last_recovery: dict | None = None
        # Driver retains each in-flight input until its out-edge results
        # complete (or, with snapshot hooks, until the next committed
        # snapshot) so a recovery can replay from the per-edge cursors.
        self._retained: dict[int, Any] = {}
        self._snapshots: dict[str, Any] | None = None
        self._snapshot_base: int | None = None
        self._stall_event = threading.Event()
        self._last_probe_ts = 0.0
        self._stall_cb = None
        self._compile()
        if self._supervise:
            from ray_tpu.util.collective import flight

            # Hang-doctor → supervisor wiring: a watchdog stall on any of
            # this DAG's channels (any epoch) wakes the blocked reader
            # into an immediate liveness probe instead of waiting out the
            # probe interval. The callback closes over the event, not the
            # DAG, so the listener registry never pins a dropped graph.
            evt = self._stall_event
            self._stall_cb = lambda event: evt.set()
            flight.register_stall_listener(self.dag_id, self._stall_cb)
        _LIVE_DAGS[self.dag_id] = self

    # -- graph lowering --------------------------------------------------
    def _compile(self) -> None:
        nodes: dict[int, DAGNode] = {}

        def walk(node: DAGNode):
            if node.node_id in nodes:
                return
            nodes[node.node_id] = node
            for up in node._upstream():
                walk(up)

        walk(self.output_node)
        method_nodes = sorted(
            (n for n in nodes.values() if isinstance(n, ClassMethodNode)),
            key=lambda n: n.node_id,
        )
        if not method_nodes:
            raise ValueError("DAG has no actor method nodes")
        self._method_nodes = method_nodes
        for node in method_nodes:
            for arg in node.args:
                if not isinstance(arg, DAGNode):
                    raise ValueError(
                        "compiled DAG args must be upstream nodes or the "
                        "InputNode (got a constant; close over it in the "
                        "actor instead)"
                    )
        # Stable out-edge dst ids: allocated once so device tags stay
        # identical across recovery re-lowers.
        self._out_dst_ids = [next(_node_counter) for _ in self._out_nodes]
        # Explicit compile-time placement (no swallowed probe): pins each
        # actor's node, assigns device-plane ranks, raises on failure.
        ordered_actors: list[str] = []
        for node in method_nodes:
            aid = node.actor._actor_id
            if aid not in ordered_actors:
                ordered_actors.append(aid)
        self._actor_ids = ordered_actors
        plan = placement.PlacementPlan.resolve(self._ctx, ordered_actors)
        self._plan = plan
        self._lower(plan)
        self._register(
            plan, need_group="device" in self._families, epoch=0,
            start_seq=0,
        )
        self._open_driver_channels(plan, start_seq=0)

    def _lower(self, plan: placement.PlacementPlan) -> None:
        """Lower the graph onto a placement plan: stage specs, edge
        families, channel names. Pure function of (graph, plan) — re-run
        during recovery because a restarted actor may land on a new node
        and change edge families."""
        method_nodes = self._method_nodes
        self._stages = {}
        self._input_targets = []
        self._all_shm_bases = []
        self._out_channel = None
        # Stage skeletons: slots for DAG-node args; constants stay the
        # reference restriction (close over them in the actor).
        for node in method_nodes:
            slots = [
                f"a{i}" for i, arg in enumerate(node.args)
                if isinstance(arg, DAGNode)
            ]
            self._stages[node.node_id] = {
                "node": node.node_id,
                "actor_id": node.actor._actor_id,
                "method": node.method_name,
                "slots": slots,
                "in_edges": [],
                "downstream": [],
                "outs": [],
                "is_output": False,
                "depth": self.CHANNEL_DEPTH,
            }
        families: set[str] = set()

        # -- wire edges --------------------------------------------------
        for node in method_nodes:
            stage = self._stages[node.node_id]
            dst_aid = stage["actor_id"]
            for i, arg in enumerate(node.args):
                slot = f"a{i}"
                if isinstance(arg, InputNode):
                    fam = placement.edge_family(
                        plan, None, dst_aid, node.channel_hint,
                        self._channel_override,
                    )
                    families.add(fam)
                    edge = {
                        "slot": slot, "family": fam, "src": arg.node_id,
                        "dst": node.node_id, "slot_id": i,
                    }
                    target = {
                        "actor_id": dst_aid, "node": node.node_id,
                        "slot": slot, "family": fam, "channel": None,
                        "src": arg.node_id, "dst": node.node_id,
                        "slot_id": i, "chan": None,
                    }
                    if fam == "shm":
                        base = f"dagch-{self.dag_id}-in-{node.node_id}-{slot}"
                        edge["channel"] = base
                        target["channel"] = base
                        self._all_shm_bases.append(base)
                    elif fam == "device":
                        edge["peer_rank"] = 0
                        target["channel"] = (
                            f"dagch:p{self._epoch}:e{arg.node_id}:"
                            f"{node.node_id}:{i}"
                        )
                    stage["in_edges"].append(edge)
                    self._input_targets.append(target)
                else:  # ClassMethodNode
                    src_stage = self._stages[arg.node_id]
                    src_aid = src_stage["actor_id"]
                    fam = placement.edge_family(
                        plan, src_aid, dst_aid, node.channel_hint,
                        self._channel_override,
                    )
                    families.add(fam)
                    common = {
                        "src": arg.node_id, "dst": node.node_id,
                        "slot_id": i,
                    }
                    in_edge = {"slot": slot, "family": fam, **common}
                    down = {
                        "actor_id": dst_aid, "node": node.node_id,
                        "slot": slot, "family": fam, **common,
                    }
                    if fam == "shm":
                        base = (
                            f"dagch-{self.dag_id}-e{arg.node_id}-"
                            f"{node.node_id}-{slot}"
                        )
                        in_edge["channel"] = base
                        down["channel"] = base
                        self._all_shm_bases.append(base)
                    elif fam == "device":
                        in_edge["peer_rank"] = plan.rank_of(src_aid)
                        down["peer_rank"] = plan.rank_of(dst_aid)
                    src_stage["downstream"].append(down)
                    stage["in_edges"].append(in_edge)
        # -- output edges ------------------------------------------------
        out_specs: list[tuple[str, dict]] = []
        for k, out_node in enumerate(self._out_nodes):
            stage = self._stages[out_node.node_id]
            stage["is_output"] = True
            aid = stage["actor_id"]
            fam = placement.edge_family(
                plan, aid, None, out_node.channel_hint,
                self._channel_override,
            )
            families.add(fam)
            out = {
                "family": fam, "src": out_node.node_id,
                "dst": self._out_dst_ids[k], "slot_id": 0,
            }
            if fam == "shm":
                out["channel"] = f"dagch-{self.dag_id}-out-{k}"
                self._all_shm_bases.append(out["channel"])
            elif fam == "device":
                out["peer_rank"] = 0
            stage["outs"].append(out)
            out_specs.append((aid, out))
            if self._out_channel is None:
                self._out_channel = out.get("channel") or (
                    f"dagch:p{self._epoch}:e{out['src']}:{out['dst']}:0"
                    if fam == "device" else None
                )
        if (
            self._multi_output
            and sum(1 for _, o in out_specs if o["family"] == "socket") > 1
        ):
            raise ValueError(
                "the socket fallback supports a single output edge; use "
                "shm or device channels for MultiOutputNode graphs"
            )
        self._out_specs = out_specs
        self._families = families

    def _open_driver_channels(self, plan: placement.PlacementPlan,
                              start_seq: int) -> None:
        """Build (or on recovery, re-build) the driver's ends of every
        input and output edge at the current epoch. Existing readers are
        refitted in place so their delivery state (buffered seqs, dedup
        frontier) survives the epoch bump."""
        wire_cfg, ef = self._make_wire_codec()
        store = self._ctx.store
        for t in self._input_targets:
            if t["family"] == "shm":
                t["chan"] = ShmChannel(
                    store, t["channel"], self.CHANNEL_DEPTH,
                    group=self.dag_id, epoch=self._epoch,
                )
            elif t["family"] == "device":
                t["chan"] = DeviceChannel(
                    self._group, plan.rank_of(t["actor_id"]),
                    src=t["src"], dst=t["dst"], slot=t["slot_id"],
                    wire_cfg=wire_cfg, ef=ef, epoch=self._epoch,
                )
        refit = bool(self._out_readers)
        for i, (aid, out) in enumerate(self._out_specs):
            chan = None
            if out["family"] == "shm":
                chan = ShmChannel(
                    store, out["channel"], self.CHANNEL_DEPTH,
                    group=self.dag_id, epoch=self._epoch,
                )
            elif out["family"] == "device":
                chan = DeviceChannel(
                    self._group, plan.rank_of(aid), src=out["src"],
                    dst=out["dst"], slot=out["slot_id"], epoch=self._epoch,
                )
            if refit:
                self._out_readers[i].refit(out, chan, start_seq)
            else:
                self._out_readers.append(_OutReader(self, aid, out, chan))

    def _make_wire_codec(self):
        if not self._quantize_wire:
            return None, None
        from ray_tpu.util.collective.quantization import (
            CollectiveConfig,
            ErrorFeedback,
        )

        cfg = CollectiveConfig(quantize_activations=self._quantize_wire)
        return cfg.activation_wire_config(), ErrorFeedback()

    def _group_name_for(self, epoch: int) -> str:
        """Per-epoch collective group name. Epoch 0 keeps the bare
        dag_id (steady-state tags and tests unchanged); recovery epochs
        get a fresh rendezvous namespace so a half-dead old group can
        never collide with the re-opened one. All epochs share the
        dag_id prefix, so the DAG's stall listener covers every epoch."""
        return self.dag_id if epoch == 0 else f"{self.dag_id}:p{epoch}"

    def _register(self, plan: placement.PlacementPlan, need_group: bool,
                  epoch: int, start_seq: int) -> None:
        """Register stage bundles on every participating worker; when
        device edges exist, rendezvous the per-DAG collective group (the
        driver is rank 0). The register RPCs are issued CONCURRENTLY
        with the driver's own group init — each worker's handler blocks
        in the group rendezvous until all ranks (driver included) have
        registered, so awaiting acks first would deadlock.

        On recovery re-registration the bundles carry the bumped channel
        epoch and the replay base: every stage loop restarts its seq
        counter at ``start_seq`` and stamps ``epoch`` into its frames."""
        group_name = self._group_name_for(epoch)
        by_actor: dict[str, list] = {}
        for stage in self._stages.values():
            by_actor.setdefault(stage["actor_id"], []).append(stage)
        ctx = self._ctx

        async def _register_all():
            async def one(aid: str):
                client = await ctx._actor_client(aid)
                resp = await client.call("dag_register", {
                    "dag_id": self.dag_id,
                    "stages": by_actor[aid],
                    "depth": self.CHANNEL_DEPTH,
                    "wire_quant": self._quantize_wire,
                    "epoch": epoch,
                    "start_seq": start_seq,
                    "group": (
                        {
                            "name": group_name,
                            "world_size": plan.world_size,
                            "rank": plan.rank_of(aid),
                        }
                        if need_group else None
                    ),
                }, timeout=120)
                if (resp or {}).get("status") != "ok":
                    raise RuntimeError(
                        f"dag_register failed on actor {aid}: {resp!r}"
                    )

            await asyncio.gather(*[one(aid) for aid in by_actor])

        if not need_group:
            ctx.io.run(_register_all(), timeout=180)
            return
        from ray_tpu.util.collective import collective

        fut = asyncio.run_coroutine_threadsafe(_register_all(), ctx.io.loop)
        try:
            collective.init_collective_group(
                plan.world_size, 0, backend="ring", group_name=group_name
            )
            self._group = collective.get_group(group_name)
            self._group_name = group_name
            fut.result(timeout=180)
        except Exception:
            fut.cancel()
            self._destroy_group(sync=True)
            raise

    # -- worker RPC helpers ----------------------------------------------
    def _call_actor(
        self, actor_id: str, method: str, payload: dict,
        timeout: float = 300.0,
    ) -> dict:
        ctx = self._ctx
        # Fast lane: socket-family pushes and pops ride the native call
        # table straight from this thread (no io-loop round trip per hop).
        conn = (
            ctx._direct_actor_conn(actor_id)
            if ctx._engine is not None
            else None
        )
        if conn is not None:
            import ctypes
            import msgpack

            from ray_tpu import _native
            from ray_tpu._private.rpc import REP, RpcError

            engine = ctx._engine
            raw = msgpack.packb(payload, use_bin_type=True)
            lib = (
                engine.pylib
                if len(raw) < engine._PYLIB_MAX_PAYLOAD
                else engine.lib
            )
            handle = lib.rt_call_start(
                engine.handle, conn[0], method.encode(), len(method),
                raw, len(raw),
            )
            if handle:
                view = _native.RtMsgView()
                rc = engine.lib.rt_call_wait(
                    engine.handle, handle, int(timeout * 1000),
                    ctypes.byref(view),
                )
                if rc == 1:
                    kind = view.kind
                    out = (
                        msgpack.unpackb(
                            ctypes.string_at(view.payload, view.plen),
                            raw=False,
                        )
                        if view.plen
                        else None
                    )
                    engine.pylib.rt_msg_free(view.opaque)
                    if kind == REP:
                        return out
                    raise RpcError(out)
                # dag methods are NOT idempotent (a pop consumes the
                # result, a push feeds a slot): once the request is on the
                # wire we must never re-issue it — surface the failure.
                engine.pylib.rt_call_abandon(engine.handle, handle)
                if rc == 0:
                    raise TimeoutError(
                        f"{method} to {actor_id} timed out after {timeout}s"
                    )
                from ray_tpu._private.rpc import ConnectionLost

                raise ConnectionLost(
                    f"{method}: connection to actor {actor_id} lost"
                )

        async def call():
            client = await ctx._actor_client(actor_id)
            return await client.call(method, payload, timeout=timeout)

        return ctx.io.run(call(), timeout=timeout + 30)

    # -- execution -------------------------------------------------------
    def execute(self, value: Any) -> DAGRef:
        if self._torn_down:
            raise RuntimeError(f"{self.dag_id} is torn down")
        # Bounded in-flight executions (the reference's max-inflight cap):
        # channel rings hold CHANNEL_DEPTH seqs per edge, so admitting
        # more un-popped executions than the ring depth would wedge the
        # submitting thread against its own un-issued pops.
        if len(self._inflight) >= self.CHANNEL_DEPTH:
            raise RuntimeError(
                f"{self.dag_id}: {len(self._inflight)} executions already "
                f"in flight (max {self.CHANNEL_DEPTH}); get() earlier "
                "results before submitting more"
            )
        seq = self._submitted
        self._submitted += 1
        self._inflight.add(seq)
        if self._supervise:
            # Retain the input until its results complete (or the next
            # committed snapshot supersedes it): the retained dict IS the
            # replay log a recovery re-feeds from. The submit-time trace
            # context rides along so a post-crash replay re-pushes each
            # frame under its ORIGINAL trace id, not the supervisor's.
            self._retained[seq] = (value, tracing.inject())
        self._push_input(seq, value)
        return DAGRef(self, seq)

    def _push_input(self, seq: int, value: Any,
                    trace: dict | None = None) -> None:
        """Push one input seq into every input edge (shared by execute()
        and the supervisor's replay pump). ``trace`` overrides the
        ambient trace context — the replay pump passes the retained
        submit-time context so replayed frames keep their trace ids."""
        ctx = trace if trace is not None else tracing.inject()
        parts = total = raw = None
        for target in self._input_targets:
            fam = target["family"]
            if fam == "shm":
                if parts is None:
                    parts, total, _ = serialization.serialize_parts(value)
                target["chan"].push_parts(seq, parts, total, trace=ctx)
            elif fam == "device":
                target["chan"].push_edge(value, trace=ctx)
            else:  # socket fallback: one RPC per push
                if raw is None:
                    raw = serialization.join_parts(
                        serialization.serialize_parts(value)[0]
                    )
                payload = {
                    "dag_id": self.dag_id, "node": target["node"],
                    "seq": seq, "slot": target["slot"], "value": raw,
                    "epoch": self._epoch,
                }
                if ctx is not None:
                    payload["trace"] = ctx
                resp = self._call_actor(
                    target["actor_id"], "dag_push", payload
                )
                if (resp or {}).get("status") == "stale_epoch":
                    raise RuntimeError(
                        f"{self.dag_id}: dag_push rejected — worker is at "
                        f"a newer epoch than this driver (epoch "
                        f"{self._epoch})"
                    )

    def _pop(self, seq: int, timeout: float) -> Any:
        self._inflight.discard(seq)
        deadline = time.monotonic() + timeout
        values = []
        for i in range(len(self._out_readers)):
            while True:
                try:
                    values.append(
                        self._out_readers[i].read(seq, deadline)
                    )
                    break
                except exceptions.DAGActorDiedError as err:
                    self._handle_death(err)
                    # Recovered: fresh budget for the replayed stream.
                    deadline = time.monotonic() + timeout
                except (TimeoutError, asyncio.TimeoutError):
                    err = self._probe_death(
                        seq, self._out_readers[i]._out
                    )
                    if err is None:
                        raise TimeoutError(
                            f"dag output seq={seq} not ready in {timeout}s"
                        ) from None
                    self._handle_death(err)
                    deadline = time.monotonic() + timeout
        self._retire(seq)
        errors = [v for v in values if isinstance(v, exceptions.TaskError)]
        if errors:
            raise errors[0]
        return values if self._multi_output else values[0]

    def _retire(self, seq: int) -> None:
        """Drop retained inputs no recovery could ever need to replay:
        everything below the slowest reader's channel cursor has been
        fully consumed (with snapshot hooks, the snapshot commit is the
        floor instead — replay restarts from the committed state)."""
        if not self._retained:
            return
        floor = min(r._next for r in self._out_readers)
        if self._snapshot_base is not None:
            floor = min(floor, self._snapshot_base)
        for s in [s for s in self._retained if s < floor]:
            del self._retained[s]

    # -- supervised liveness probing -------------------------------------
    def _maybe_probe(self, out: dict, frontier: int) -> None:
        """Called by a blocked supervised reader between pop slices:
        probe actor liveness when the watchdog flagged a stall on this
        DAG's channels, or the probe interval elapsed. Raises a typed
        DAGActorDiedError (caught by _pop's recovery loop) when an actor
        is DEAD; a slow-but-alive graph just keeps waiting."""
        now = time.monotonic()
        stalled = self._stall_event.is_set()
        if not stalled and now - self._last_probe_ts < self.PROBE_INTERVAL_S:
            return
        self._last_probe_ts = now
        self._stall_event.clear()
        err = self._probe_death(frontier, out)
        if err is not None:
            raise err

    def _probe_death(self, frontier: int,
                     out: dict | None = None) -> "exceptions.DAGActorDiedError | None":
        """Probe every DAG actor's controller state; a DEAD one becomes a
        typed death error carrying the edge evidence (channel name,
        family, epoch, seq frontier) the supervisor and the hang report
        line up on. Returns None when everyone is alive."""
        fam = out.get("family") if out else None
        channel = None
        if out is not None:
            if fam == "shm":
                channel = out.get("channel")
            elif fam == "device":
                channel = (
                    f"dagch:p{self._epoch}:e{out['src']}:{out['dst']}:"
                    f"{out['slot_id']}"
                )
            else:
                channel = "dag_pop"
        for aid in self._actor_ids:
            try:
                info = self._ctx.io.run(
                    self._ctx.controller.call(
                        "get_actor_info", {"actor_id": aid}, timeout=10
                    ),
                    timeout=15,
                )
            except Exception:  # rtlint: disable=swallowed-exception - controller unreachable: treat as alive, keep waiting
                continue
            if (info or {}).get("state") == "DEAD":
                return exceptions.DAGActorDiedError(
                    self.dag_id, aid, self._plan.rank_of(aid),
                    detail=str((info or {}).get("death_cause") or ""),
                    channel=channel, family=fam, epoch=self._epoch,
                    seq=frontier,
                )
        return None

    def _handle_death(self, err: "exceptions.DAGActorDiedError") -> None:
        """An actor died with executions in flight: recover in place
        (supervised, budget left) or tear the graph down and re-raise —
        a failed execute() must not strand ring slots or parked loops."""
        if not self._supervise or self.recoveries >= self._max_recoveries:
            self._fail_cleanup()
            raise err
        from ray_tpu.dag import supervisor

        try:
            supervisor.recover(self, err)
        except Exception:
            self._fail_cleanup()
            raise
        self.recoveries += 1

    def _fail_cleanup(self) -> None:
        """Failure-path teardown: release every ring slot, stop every
        resident loop, drop retained inputs. The graph is unusable after
        this — close() becomes a no-op."""
        if self._torn_down:
            return
        self._torn_down = True
        _LIVE_DAGS.pop(self.dag_id, None)
        self._unregister_stall_listener()
        self._inflight.clear()
        self._retained.clear()
        try:
            self._ctx.io.run(self._teardown_async(), timeout=15)
        except Exception:  # rtlint: disable=swallowed-exception - dead workers can't ack teardown; driver-side slot frees already ran
            pass
        self._destroy_group(sync=True)

    # -- snapshot hooks ---------------------------------------------------
    def snapshot(self, timeout: float = 60.0) -> int:
        """Commit a stateful checkpoint: calls ``__dag_snapshot__`` on
        every actor that defines it and retains the blobs driver-side.
        All-or-nothing — on any failure the previous committed snapshot
        (if any) stays in force. Requires a quiescent graph (no in-flight
        executions), so the snapshot corresponds to an exact seq
        frontier: on recovery, hooked actors are restored to this commit
        and the driver replays every retained input from it. Returns the
        snapshot base seq (the next seq to execute after restore)."""
        if self._torn_down:
            raise RuntimeError(f"{self.dag_id} is torn down")
        if self._inflight:
            raise RuntimeError(
                f"{self.dag_id}: snapshot() requires a quiescent graph "
                f"({len(self._inflight)} executions in flight — get() "
                "them first)"
            )
        blobs: dict[str, Any] = {}
        for aid in self._actor_ids:
            resp = self._call_actor(
                aid, "dag_snapshot", {"dag_id": self.dag_id},
                timeout=timeout,
            )
            status = (resp or {}).get("status")
            if status == "no_hook":
                continue
            if status != "ok":
                raise RuntimeError(
                    f"dag_snapshot failed on actor {aid}: {resp!r}"
                )
            blobs[aid] = resp["blob"]
        self._snapshots = blobs
        self._snapshot_base = self._submitted
        # Inputs before the commit can never be replayed again.
        for s in [s for s in self._retained if s < self._snapshot_base]:
            del self._retained[s]
        return self._snapshot_base

    def _unregister_stall_listener(self) -> None:
        if self._stall_cb is None:
            return
        from ray_tpu.util.collective import flight

        flight.unregister_stall_listener(self._stall_cb)
        self._stall_cb = None

    # -- teardown ---------------------------------------------------------
    def close(self, timeout: float = 30.0) -> None:
        """Drain in-flight executions, stop the resident worker loops,
        and free every channel ring slot. Idempotent."""
        if self._torn_down:
            return
        self._torn_down = True
        _LIVE_DAGS.pop(self.dag_id, None)
        self._unregister_stall_listener()
        self._retained.clear()
        # Drain admitted-but-unpopped seqs so no worker loop is wedged
        # mid-push when the teardown RPC lands.
        for seq in sorted(self._inflight):
            deadline = time.monotonic() + min(5.0, timeout)
            for reader in self._out_readers:
                try:
                    reader.read(seq, deadline)
                except Exception:  # rtlint: disable=swallowed-exception - draining a dead or torn graph; slots are freed below regardless
                    pass
        self._inflight.clear()
        try:
            self._ctx.io.run(self._teardown_async(), timeout=timeout)
        except Exception:  # rtlint: disable=swallowed-exception - teardown race with shutdown; worker side is idempotent
            pass
        self._destroy_group(sync=True)

    def teardown(self) -> None:
        """Back-compat alias for close(); safe to call from the io loop
        or a GC finalizer (falls back to fire-and-forget there)."""
        if self._torn_down:
            return
        try:
            on_io_loop = asyncio.get_running_loop() is self._ctx.io.loop
        except RuntimeError:
            on_io_loop = False
        if on_io_loop or getattr(self._ctx, "_shutdown", False):
            # Never block the io loop (a GC-triggered __del__ can run
            # on ANY thread, including the loop itself): fire and
            # forget — worker-side teardown is idempotent.
            self._torn_down = True
            _LIVE_DAGS.pop(self.dag_id, None)
            self._unregister_stall_listener()
            self._spawn_teardown()
            self._destroy_group(sync=False)
        else:
            self.close()

    async def _teardown_async(self) -> None:
        async def one(actor_id: str) -> None:
            try:
                client = await self._ctx._actor_client(actor_id)
                await client.call(
                    "dag_teardown", {"dag_id": self.dag_id}, timeout=10
                )
            except Exception:  # rtlint: disable=swallowed-exception - actor may be dead; teardown is idempotent
                pass

        # Concurrent: one dead actor's timeout must not serialize the
        # survivors' teardown behind it (failure-path latency).
        await asyncio.gather(*[one(aid) for aid in self._actor_ids])
        # Driver-side backstop: every shm ring slot of this DAG (input,
        # inter-stage, and output rings) — a dead worker must not leak
        # its consumer-owned slots, and the driver-owned output ring is
        # freed here so the __del__ fire-and-forget path leaks nothing.
        for base in self._all_shm_bases:
            for i in range(self.CHANNEL_DEPTH):
                try:
                    self._ctx.store.delete(f"{base}-{i}")
                except Exception:  # rtlint: disable=swallowed-exception - ring slot already freed
                    pass

    def _destroy_group(self, sync: bool) -> None:
        if self._group is None:
            return
        from ray_tpu.util.collective import collective

        name = self._group_name or self.dag_id
        if sync:
            try:
                collective.destroy_collective_group(name)
            except Exception:  # rtlint: disable=swallowed-exception - rendezvous keys die with the controller; the registry entry is what must go
                collective._groups.pop(name, None)
        else:
            # destroy() round-trips the controller KV via the io loop we
            # may be ON: drop the registry entry only.
            collective._groups.pop(name, None)
        self._group = None

    def _spawn_teardown(self) -> None:
        """Fire-and-forget teardown that never leaks an unawaited
        coroutine: if the io loop is already gone (interpreter/cluster
        shutdown), the coroutine is closed instead of dropped — a dropped
        one surfaces as a 'never awaited' RuntimeWarning, which the test
        suite escalates to an error."""
        coro = self._teardown_async()
        try:
            self._ctx.io.spawn(coro)
        except Exception:
            coro.close()

    def __del__(self):  # best-effort: a dropped DAG must not leak slots
        try:
            if not self._torn_down:
                self._torn_down = True
                self._spawn_teardown()
                self._destroy_group(sync=False)
        except Exception:  # rtlint: disable=swallowed-exception - __del__ during interpreter teardown
            pass

"""Shared-memory channel primitives for compiled DAGs.

Role-equivalent of python/ray/experimental/channel/shared_memory_channel.py
(SURVEY §2.2 aDAG row): a channel is a bounded ring of named slots in the
node's shm object store. The producer streams serialized parts straight
into the arena allocation (create/seal, one copy total) and the consumer
deletes the slot after reading — the delete IS the backpressure release.
Cross-process payloads therefore never touch a socket; only a tiny notify
RPC moves per hop.
"""

from __future__ import annotations

import struct
import weakref

from ray_tpu._private import serialization

# Payloads at or above this deserialize as zero-copy views onto the
# arena; the ring slot is freed when the VALUE is garbage-collected
# (backpressure then tracks value lifetime, like plasma pinning).
# Like the core get() path (and the reference's plasma-backed arrays),
# zero-copy values are READ-ONLY — stages that mutate inputs in place
# must copy first; the socket (non-co-located) path returns writable
# copies, so in-place mutation is placement-dependent by construction.
# Non-weakref-able payloads (dicts/tuples) pay a second, copying
# deserialize — numpy/array payloads (the hot case) are weakref-able.
ZERO_COPY_THRESHOLD = 256 * 1024


def slot_name(base: str, seq: int, depth: int) -> str:
    return f"{base}-{seq % depth}"


def try_write(store, name: str, parts, total: int) -> bool:
    """One streamed write attempt; False when the ring slot is still
    occupied (consumer behind — caller waits and retries)."""
    try:
        view = store.create(name, total)
    except FileExistsError:
        return False
    offset = 0
    for part in parts:
        n = part.nbytes if isinstance(part, memoryview) else len(part)
        view[offset:offset + n] = part
        offset += n
    store.seal(name)
    return True


def _free_slot(store, name: str) -> None:
    try:
        store.release(name)
    except Exception:  # rtlint: disable=swallowed-exception - slot may be unreferenced already
        pass
    try:
        store.delete(name)
    except Exception:  # rtlint: disable=swallowed-exception - slot may already be deleted by the peer
        pass


def read_consume(store, name: str, timeout_ms: int = 60_000):
    """Blocking read of a slot, then free it (producer unblocks). Large
    payloads come back as zero-copy views; their slot frees when the
    value dies."""
    view = store.get(name, timeout_ms=timeout_ms)
    if view is None:
        raise TimeoutError(f"channel slot {name} never arrived")
    return _consume_view(store, name, view)


def _consume_view(store, name: str, view):
    if view.nbytes >= ZERO_COPY_THRESHOLD:
        value = serialization.deserialize(view, zero_copy=True)
        try:
            weakref.finalize(value, _free_slot, store, name)
            return value
        except TypeError:
            pass  # not weakref-able: copy out below
    try:
        return serialization.deserialize(view, zero_copy=False)
    finally:
        _free_slot(store, name)


# -- seq-framed slots (rtdag polling channels) ---------------------------
# The resident executor loops (dag/executor.py) consume slots by POLLING
# (non-blocking store.get) instead of a notify RPC, so each slot carries
# a (channel epoch, sequence number) header: a consumer that wakes up on
# a slot can verify it holds the seq it expects rather than a stale or
# wrapped-around write, and a frame written before a crash-recovery
# epoch bump is DISCARDED (freeing the slot for the replaying producer)
# instead of desequencing the re-opened ring.

SEQ_HEADER = struct.Struct("<QQ")  # (epoch, seq)

# ISSUE 19: an optional trace-context segment rides the frame header
# right after (epoch, seq) — one length byte, then ``length`` bytes of
# ``tracing.pack_ctx`` payload (25 bytes for a sampled context, 0 when
# tracing is off). The disabled path costs exactly one b"\x00" byte per
# frame; no import of the tracing module happens on it.
_NO_TRACE = b"\x00"

# Distinguishes "slot not written yet" from any legitimate payload value
# (None included) on the non-blocking read path.
NOT_READY = object()

# Loud evidence that epoch fencing fired: every discarded pre-crash
# frame bumps this counter (scraped by tests and the recovery
# benchmark) and emits a ``stale_frame`` note into the comm flight ring.
_stale_frames = 0


def stale_frame_count() -> int:
    return _stale_frames


def _note_stale_frame(name: str, got_epoch: int, epoch: int,
                      seq: int) -> None:
    global _stale_frames
    _stale_frames += 1
    try:
        from ray_tpu.util.collective import flight

        with flight.site("dag"):
            # Evidence rides the tag (frame epoch vs channel epoch) and
            # the seq field — flight records have a fixed shape.
            flight.note(
                "dag", "stale_frame",
                tag=f"{name}:e{got_epoch}<{epoch}", seq=seq,
            )
    except Exception:  # rtlint: disable=swallowed-exception - fencing must work without a flight ring (unit tests)
        pass


def try_write_seq(store, name: str, seq: int, parts, total: int,
                  epoch: int = 0, trace: bytes = b"") -> bool:
    """One seq-framed write attempt; False while the ring slot is still
    occupied by an unconsumed earlier seq. ``trace`` is an optional
    pre-packed trace-context segment (``tracing.pack_ctx``) that rides
    the header beside (epoch, seq)."""
    header = SEQ_HEADER.pack(epoch, seq)
    seg = bytes([len(trace)]) + trace if trace else _NO_TRACE
    return try_write(
        store, name, [header, seg, *parts],
        total + SEQ_HEADER.size + len(seg),
    )


def read_seq_consume(store, name: str, seq: int, epoch: int = 0,
                     trace_out: list | None = None):
    """Non-blocking epoch+seq-framed read. Returns NOT_READY when the
    slot is absent, still holds an older seq, or holds a stale-epoch
    frame (which is consumed and discarded loudly — the slot frees so
    the post-recovery producer can claim it); otherwise consumes the
    slot and returns its value (zero-copy above the threshold, like
    read_consume). When the frame header carries a trace segment and the
    caller passed ``trace_out``, the raw segment bytes are appended to
    it (the caller unpacks — this module stays tracing-agnostic)."""
    view = store.get(name, timeout_ms=0)
    if view is None:
        return NOT_READY
    if view.nbytes < SEQ_HEADER.size + 1:
        _free_slot(store, name)
        raise RuntimeError(f"channel slot {name}: truncated seq header")
    got_epoch, got = SEQ_HEADER.unpack(view[: SEQ_HEADER.size])
    if got_epoch != epoch:
        if got_epoch < epoch:
            # Pre-crash frame surviving into a re-opened channel: fence
            # it out — free the slot (unblocking the replaying producer)
            # and count the discard instead of raising a seq desync.
            _free_slot(store, name)
            _note_stale_frame(name, got_epoch, epoch, seq)
            return NOT_READY
        _free_slot(store, name)
        raise RuntimeError(
            f"channel slot {name}: frame epoch {got_epoch} is ahead of "
            f"this consumer's epoch {epoch} (reader missed a recovery)"
        )
    if got != seq:
        # Unreachable under strict in-order consumption — surface loudly
        # rather than polling a wedged slot forever.
        _free_slot(store, name)
        raise RuntimeError(
            f"channel slot {name}: seq desync (holds {got}, expected {seq})"
        )
    trace_len = view[SEQ_HEADER.size]
    body = SEQ_HEADER.size + 1 + trace_len
    if trace_len and trace_out is not None:
        trace_out.append(bytes(view[SEQ_HEADER.size + 1: body]))
    return _consume_view(store, name, view[body:])

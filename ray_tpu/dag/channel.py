"""Shared-memory channel primitives for compiled DAGs.

Role-equivalent of python/ray/experimental/channel/shared_memory_channel.py
(SURVEY §2.2 aDAG row): a channel is a bounded ring of named slots in the
node's shm object store. The producer streams serialized parts straight
into the arena allocation (create/seal, one copy total) and the consumer
deletes the slot after reading — the delete IS the backpressure release.
Cross-process payloads therefore never touch a socket; only a tiny notify
RPC moves per hop.
"""

from __future__ import annotations

import weakref

from ray_tpu._private import serialization

# Payloads at or above this deserialize as zero-copy views onto the
# arena; the ring slot is freed when the VALUE is garbage-collected
# (backpressure then tracks value lifetime, like plasma pinning).
# Like the core get() path (and the reference's plasma-backed arrays),
# zero-copy values are READ-ONLY — stages that mutate inputs in place
# must copy first; the socket (non-co-located) path returns writable
# copies, so in-place mutation is placement-dependent by construction.
# Non-weakref-able payloads (dicts/tuples) pay a second, copying
# deserialize — numpy/array payloads (the hot case) are weakref-able.
ZERO_COPY_THRESHOLD = 256 * 1024


def slot_name(base: str, seq: int, depth: int) -> str:
    return f"{base}-{seq % depth}"


def try_write(store, name: str, parts, total: int) -> bool:
    """One streamed write attempt; False when the ring slot is still
    occupied (consumer behind — caller waits and retries)."""
    try:
        view = store.create(name, total)
    except FileExistsError:
        return False
    offset = 0
    for part in parts:
        n = part.nbytes if isinstance(part, memoryview) else len(part)
        view[offset:offset + n] = part
        offset += n
    store.seal(name)
    return True


def _free_slot(store, name: str) -> None:
    try:
        store.release(name)
    except Exception:  # rtlint: disable=swallowed-exception - slot may be unreferenced already
        pass
    try:
        store.delete(name)
    except Exception:  # rtlint: disable=swallowed-exception - slot may already be deleted by the peer
        pass


def read_consume(store, name: str, timeout_ms: int = 60_000):
    """Blocking read of a slot, then free it (producer unblocks). Large
    payloads come back as zero-copy views; their slot frees when the
    value dies."""
    view = store.get(name, timeout_ms=timeout_ms)
    if view is None:
        raise TimeoutError(f"channel slot {name} never arrived")
    if view.nbytes >= ZERO_COPY_THRESHOLD:
        value = serialization.deserialize(view, zero_copy=True)
        try:
            weakref.finalize(value, _free_slot, store, name)
            return value
        except TypeError:
            pass  # not weakref-able: copy out below
    try:
        return serialization.deserialize(view, zero_copy=False)
    finally:
        _free_slot(store, name)

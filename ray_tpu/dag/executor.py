"""Resident per-stage executor loops — the worker half of rtdag.

After compile, every (dag_id, stage) on a worker gets a daemon
``StageLoop`` thread that processes sequence numbers strictly in order:
pop every input edge for seq k, run the bound actor method on the
actor's single-width executor (preserving actor single-threadedness
while stages on DIFFERENT actors pipeline), push every output edge.
Steady state is pure channel-push/channel-pop — no controller RPC, no
per-hop notify.

Blocking discipline (hang-doctor compatibility): worker-side device pops
use SHORT retry slices (timed-out slices complete ok=False, never feed
the watchdog's p95 window, and age out below the deadline floor), so an
idle resident loop can never trip a false stall — while its in-flight
short-slice records ARE harvested as waiting-rank evidence when the
driver's full-timeout pop flags the shared DAG channel.
"""

from __future__ import annotations

import asyncio
import itertools
import threading
import traceback

from ray_tpu import exceptions
from ray_tpu._private import serialization
from ray_tpu.dag.channels import (
    _TR_WIRE,
    ChannelClosedError,
    DeviceChannel,
    ShmChannel,
)
from ray_tpu.util import tracing

# Worker-side device-pop retry slice: long enough to stay cheap, short
# enough that stop() is honored promptly and a timed-out slice stays
# under the watchdog's deadline floor.
_POP_SLICE_S = 0.5

_TIMEOUTS = (TimeoutError, asyncio.TimeoutError)


class SeqBuffer:
    """Thread-safe in-order mailbox feeding one input slot (local and
    socket edges; shm/device edges pop their channel directly)."""

    def __init__(self):
        self._items: dict[int, object] = {}
        self._cv = threading.Condition()

    def put(self, seq: int, value) -> None:
        with self._cv:
            self._items[seq] = value
            self._cv.notify_all()

    def pop(self, seq: int, stop) -> object:
        with self._cv:
            while seq not in self._items:
                if stop():
                    raise ChannelClosedError("stage loop stopped")
                self._cv.wait(timeout=0.1)
            return self._items.pop(seq)

    def wake(self) -> None:
        with self._cv:
            self._cv.notify_all()


class StageLoop(threading.Thread):
    """One stage's resident loop: in-order pop → compute → push."""

    def __init__(self, *, dag_id: str, stage: dict, store, group,
                 run_stage, deliver_local, send_socket, park_output,
                 wire_cfg=None, ef=None, epoch: int = 0,
                 start_seq: int = 0):
        super().__init__(
            daemon=True, name=f"rtdag-{dag_id}-n{stage['node']}"
        )
        self.dag_id = dag_id
        self.stage = stage
        self.epoch = epoch
        self.start_seq = start_seq
        # High-water seq this loop has FULLY pushed downstream — the
        # per-stage replay cursor the supervisor reads when deciding how
        # far back a post-recovery re-register must rewind.
        self.completed_seq = start_seq - 1
        self._stop = threading.Event()
        self._run_stage = run_stage
        self._deliver_local = deliver_local
        self._send_socket = send_socket
        self._park_output = park_output
        depth = stage.get("depth", 8)
        # Input poppers, in declared slot order (== method arg order).
        self._in_pops: list[tuple[str, object]] = []
        self._buffers: dict[str, SeqBuffer] = {}
        for edge in stage.get("in_edges", ()):
            fam = edge["family"]
            if fam == "shm":
                chan = ShmChannel(
                    store, edge["channel"], depth, group=dag_id,
                    epoch=epoch,
                )
            elif fam == "device":
                chan = DeviceChannel(
                    group, edge["peer_rank"], src=edge["src"],
                    dst=edge["dst"], slot=edge["slot_id"],
                    wire_cfg=wire_cfg, ef=ef, epoch=epoch,
                )
            else:  # local / socket: fed via feed()
                chan = self._buffers.setdefault(edge["slot"], SeqBuffer())
            self._in_pops.append((edge["slot"], fam, chan))
        # Output channels, keyed by (node, slot) for downstream edges.
        self._down_chans: dict[tuple, object] = {}
        for edge in stage.get("downstream", ()):
            fam = edge["family"]
            key = (edge["node"], edge["slot"])
            if fam == "shm":
                self._down_chans[key] = ShmChannel(
                    store, edge["channel"], depth, group=dag_id,
                    epoch=epoch,
                )
            elif fam == "device":
                self._down_chans[key] = DeviceChannel(
                    group, edge["peer_rank"], src=edge["src"],
                    dst=edge["dst"], slot=edge["slot_id"],
                    wire_cfg=wire_cfg, ef=ef, epoch=epoch,
                )
        # Output edges to the driver (a stage may back several
        # MultiOutputNode members).
        self._out_chans: list[tuple[dict, object]] = []
        for out in stage.get("outs", ()):
            if out["family"] == "shm":
                chan = ShmChannel(
                    store, out["channel"], depth, group=dag_id, epoch=epoch
                )
            elif out["family"] == "device":
                chan = DeviceChannel(
                    group, out["peer_rank"], src=out["src"],
                    dst=out["dst"], slot=out["slot_id"],
                    wire_cfg=wire_cfg, ef=ef, epoch=epoch,
                )
            else:  # socket: parked locally, pulled via dag_pop
                chan = None
            self._out_chans.append((out, chan))

    # -- control ---------------------------------------------------------
    def feed(self, slot: str, seq: int, value) -> None:
        buf = self._buffers.get(slot)
        if buf is None:
            raise KeyError(
                f"{self.name}: slot {slot!r} is not a buffered edge"
            )
        buf.put(seq, value)

    def stop(self) -> None:
        self._stop.set()
        for buf in self._buffers.values():
            buf.wake()

    def stopped(self) -> bool:
        return self._stop.is_set()

    def free_slots(self) -> None:
        """Consumer-owned shm ring slots (this stage's input edges)."""
        for _, fam, chan in self._in_pops:
            if fam == "shm":
                chan.free_slots()

    # -- per-edge ops ----------------------------------------------------
    def _pop_input(self, fam: str, chan, seq: int):
        """One input value + the trace context that rode its frame (the
        channel's ``last_trace`` for shm/device edges, the ``_TR_WIRE``
        envelope for buffered local/socket edges; None untraced)."""
        if fam == "shm":
            value = chan.pop(seq, timeout=None, stop=self.stopped)
            return value, chan.last_trace
        if fam == "device":
            while True:
                if self.stopped():
                    raise ChannelClosedError("stage loop stopped")
                try:
                    value = chan.pop_edge(timeout=_POP_SLICE_S)
                    return value, chan.last_trace
                except _TIMEOUTS:
                    continue
        value = chan.pop(seq, stop=self.stopped)  # SeqBuffer
        if (
            isinstance(value, tuple) and len(value) == 3
            and value[0] == _TR_WIRE
        ):
            return value[2], value[1]
        return value, None

    def _push_downstream(self, edge, seq: int, result, cache: dict,
                         trace: dict | None = None) -> None:
        fam = edge["family"]
        if fam == "local":
            # Same-actor edge: deliver a private copy in-process (the
            # serialize round trip IS the copy barrier).
            if "raw" not in cache:
                parts, total, _ = serialization.serialize_parts(result)
                cache["raw"] = serialization.join_parts(parts)
            self._deliver_local(
                edge["node"], edge["slot"], seq, cache["raw"], trace
            )
        elif fam == "shm":
            if "parts" not in cache:
                cache["parts"], cache["total"], _ = (
                    serialization.serialize_parts(result)
                )
            chan = self._down_chans[(edge["node"], edge["slot"])]
            chan.push_parts(
                seq, cache["parts"], cache["total"], stop=self.stopped,
                trace=trace,
            )
        elif fam == "device":
            self._down_chans[(edge["node"], edge["slot"])].push_edge(
                result, trace=trace
            )
        else:  # socket
            if "raw" not in cache:
                parts, total, _ = serialization.serialize_parts(result)
                cache["raw"] = serialization.join_parts(parts)
            self._send_socket(edge, seq, cache["raw"], trace)

    # -- main loop -------------------------------------------------------
    def run(self) -> None:
        stage = self.stage
        try:
            # A post-recovery loop starts at the replay base, not 0: the
            # driver re-pushes every retained seq and each stage
            # recomputes from there (duplicated outputs are deduplicated
            # by the driver-side readers' delivery frontier).
            for seq in itertools.count(self.start_seq):
                if self.stopped():
                    return
                args = []
                err = None
                in_ctx = None
                for slot, fam, chan in self._in_pops:
                    value, ctx = self._pop_input(fam, chan, seq)
                    if in_ctx is None and ctx is not None:
                        in_ctx = ctx
                    if err is None and isinstance(
                        value, exceptions.TaskError
                    ):
                        err = value
                    args.append(value)
                # A traced input makes the whole stage invocation part of
                # that trace: the stage span parents on the frame context
                # and its OWN context flows into every downstream push,
                # so cross-stage hops chain push → pop → stage → push.
                stage_span = None
                if in_ctx is not None and tracing.enabled():
                    stage_span = tracing.begin(
                        f"dag.stage {stage['method']}", parent=in_ctx,
                        dag_id=self.dag_id, node=stage["node"], seq=seq,
                    )
                if err is not None:
                    result = err  # skip compute, forward the failure
                else:
                    try:
                        result = self._run_stage(stage["method"], args)
                    except Exception:
                        result = exceptions.TaskError(
                            stage["method"], traceback.format_exc()
                        )
                        if stage_span is not None:
                            stage_span.set_error(result.__class__.__name__)
                out_ctx = (
                    {"trace_id": stage_span.trace_id,
                     "span_id": stage_span.span_id}
                    if stage_span is not None else in_ctx
                )
                cache: dict = {}
                for edge in stage.get("downstream", ()):
                    self._push_downstream(edge, seq, result, cache, out_ctx)
                for out, chan in self._out_chans:
                    if chan is None:
                        self._park_output(seq, result)
                    elif out["family"] == "shm":
                        chan.push(
                            seq, result, stop=self.stopped, trace=out_ctx
                        )
                    else:
                        chan.push_edge(result, trace=out_ctx)
                if stage_span is not None:
                    tracing.finish(stage_span)
                self.completed_seq = seq
        except ChannelClosedError:
            return
        except Exception:
            if not self.stopped():
                traceback.print_exc()


class DagRuntime:
    """All rtdag state one worker holds for one dag_id: the per-dag
    device-plane group membership (if any device edges exist), the
    resident StageLoops, and the parked results of a socket-family
    output edge. Built OFF the io loop (group rendezvous blocks on
    controller KV via ctx.io.run)."""

    def __init__(self, *, ctx, dag_id: str, payload: dict, run_stage,
                 notify_loop):
        self._ctx = ctx
        self.dag_id = dag_id
        self.epoch = payload.get("epoch", 0)
        self._stages = payload["stages"]
        self._notify_loop = notify_loop
        self._results: dict[int, object] = {}
        self._events: dict[int, asyncio.Event] = {}
        self._group_name = None
        group = None
        gspec = payload.get("group")
        if gspec:
            from ray_tpu.util.collective import collective

            collective.init_collective_group(
                gspec["world_size"], gspec["rank"], backend="ring",
                group_name=gspec["name"],
            )
            group = collective.get_group(gspec["name"])
            self._group_name = gspec["name"]
        wire_cfg = None
        ef = None
        if payload.get("wire_quant"):
            from ray_tpu.util.collective.quantization import (
                CollectiveConfig,
                ErrorFeedback,
            )

            wire_cfg = CollectiveConfig(
                quantize_activations=payload["wire_quant"]
            ).activation_wire_config()
            ef = ErrorFeedback()
        self._loops = [
            StageLoop(
                dag_id=dag_id, stage=stage, store=ctx.store, group=group,
                run_stage=run_stage, deliver_local=self._deliver_local,
                send_socket=self._send_socket,
                park_output=self._park_output, wire_cfg=wire_cfg, ef=ef,
                epoch=self.epoch,
                start_seq=payload.get("start_seq", 0),
            )
            for stage in self._stages
        ]
        for loop in self._loops:
            loop.start()

    # -- inbound ---------------------------------------------------------
    def feed(self, node: int, slot: str, seq: int, value) -> None:
        for loop in self._loops:
            if loop.stage["node"] == node:
                loop.feed(slot, seq, value)
                return
        raise KeyError(f"dag {self.dag_id}: stage {node} not on this worker")

    # -- StageLoop callbacks ---------------------------------------------
    def _deliver_local(self, node: int, slot: str, seq: int, raw,
                       trace: dict | None = None) -> None:
        value = serialization.deserialize(raw, zero_copy=False)
        if trace is not None:
            value = (_TR_WIRE, trace, value)
        self.feed(node, slot, seq, value)

    def _send_socket(self, edge: dict, seq: int, raw,
                     trace: dict | None = None) -> None:
        payload = {
            "dag_id": self.dag_id, "node": edge["node"],
            "slot": edge["slot"], "seq": seq, "value": raw,
            "epoch": self.epoch,
        }
        if trace is not None:
            # Sidecar field, not an envelope: the receiver re-wraps after
            # deserializing so the value bytes stay format-stable.
            payload["trace"] = trace

        async def _push():
            client = await self._ctx._actor_client(edge["actor_id"])
            await client.call("dag_push", payload)

        def _log_err(f):
            try:
                exc = f.exception()
            except Exception:  # rtlint: disable=swallowed-exception - cancelled future during teardown
                return
            if exc is not None:
                traceback.print_exception(type(exc), exc, exc.__traceback__)

        fut = asyncio.run_coroutine_threadsafe(_push(), self._ctx.io.loop)
        fut.add_done_callback(_log_err)

    def _park_output(self, seq: int, result) -> None:
        self._results[seq] = result

        def _set():
            self._events.setdefault(seq, asyncio.Event()).set()

        self._notify_loop.call_soon_threadsafe(_set)

    # -- outbound (socket out-edge legacy pop) ---------------------------
    async def pop(self, seq: int, timeout: float) -> dict:
        event = self._events.setdefault(seq, asyncio.Event())
        try:
            await asyncio.wait_for(event.wait(), timeout)
        except asyncio.TimeoutError:
            return {"status": "timeout"}
        result = self._results.pop(seq)
        self._events.pop(seq, None)
        raw, _ = serialization.serialize(result)
        return {"status": "ok", "value": raw}

    # -- teardown --------------------------------------------------------
    def stop(self) -> None:
        """Stop every loop, free consumer-owned ring slots, and leave the
        per-dag collective group. Blocking — run off the io loop."""
        for loop in self._loops:
            loop.stop()
        for loop in self._loops:
            loop.join(timeout=5)
        for loop in self._loops:
            loop.free_slots()
        self._results.clear()
        if self._group_name is not None:
            from ray_tpu.util.collective import collective

            try:
                collective.destroy_collective_group(self._group_name)
            except Exception:  # rtlint: disable=swallowed-exception - teardown races worker shutdown; the group registry entry is gone either way
                pass
            self._group_name = None

"""Compile-time placement plan for rtdag graphs.

Compiling a DAG pins every participating actor to the cluster node that
hosts it BEFORE any channel is opened: channel-family selection (shm vs
device vs socket) is a pure function of this plan, every actor gets a
stable device-plane rank (driver = 0, actors = 1..N in graph order), and
placement failures surface as compile errors instead of silently
degrading an edge to a slower family.
"""

from __future__ import annotations

import asyncio


class PlacementError(RuntimeError):
    """An actor's placement could not be resolved at compile time."""


class PlacementPlan:
    """Resolved placement for one compiled DAG: driver node plus, per
    actor, its hosting cluster node and device-plane rank."""

    def __init__(self, driver_node: str, actors: dict[str, dict]):
        self.driver_node = driver_node
        self.actors = actors  # actor_id → {"node_id": str, "rank": int}

    @classmethod
    def resolve(cls, ctx, actor_ids, timeout: float = 60.0) -> "PlacementPlan":
        """Query the controller for every actor's placement concurrently,
        waiting for scheduling (compile typically runs right after actor
        creation). Raises PlacementError on any unresolved actor — an
        unplaceable DAG must fail at compile, not at first execute."""

        async def _gather():
            return await asyncio.gather(*[
                ctx.controller.call(
                    "get_actor_info",
                    {"actor_id": aid, "wait_ready": True},
                    timeout=timeout,
                )
                for aid in actor_ids
            ])

        try:
            infos = ctx.io.run(_gather(), timeout=timeout + 10)
        except Exception as exc:
            raise PlacementError(
                f"placement query failed for actors {list(actor_ids)}: "
                f"{type(exc).__name__}: {exc}"
            ) from exc
        actors: dict[str, dict] = {}
        for rank, (aid, info) in enumerate(zip(actor_ids, infos), start=1):
            node = (info or {}).get("node_id")
            state = (info or {}).get("state")
            if not node or state == "DEAD":
                raise PlacementError(
                    f"actor {aid} has no live placement "
                    f"(state={state!r}, node={node!r})"
                )
            actors[aid] = {"node_id": node, "rank": rank}
        return cls(ctx.node_id, actors)

    # -- queries ---------------------------------------------------------
    def node_of(self, actor_id: str | None) -> str:
        """Hosting node; None means the driver."""
        if actor_id is None:
            return self.driver_node
        return self.actors[actor_id]["node_id"]

    def rank_of(self, actor_id: str | None) -> int:
        """Device-plane rank; the driver is rank 0."""
        if actor_id is None:
            return 0
        return self.actors[actor_id]["rank"]

    def colocated(self, a: str | None, b: str | None) -> bool:
        return self.node_of(a) == self.node_of(b)

    @property
    def world_size(self) -> int:
        return len(self.actors) + 1  # + driver


def edge_family(plan: PlacementPlan, src: str | None, dst: str | None,
                hint: str | None, override: str | None) -> str:
    """Channel family for one edge (src/dst are actor ids; None = the
    driver endpoint). Precedence: same-actor > compile-wide override >
    per-node hint > auto (co-located → shm, else device)."""
    if src is not None and src == dst:
        return "local"
    choice = override or hint
    if choice is None:
        return "shm" if plan.colocated(src, dst) else "device"
    if choice == "shm" and not plan.colocated(src, dst):
        raise ValueError(
            f"edge {src or 'driver'} → {dst or 'driver'} requested an shm "
            "channel but the endpoints are on different nodes"
        )
    if choice not in ("shm", "device", "socket"):
        raise ValueError(f"unknown channel family {choice!r}")
    return choice

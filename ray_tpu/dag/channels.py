"""rtdag channel families — the typed edges of a compiled dataflow graph.

A compiled DAG edge is one of four channel families, chosen by the
compile-time placement plan (dag/placement.py):

* ``ShmChannel``    — co-located host payloads ride the node's shm object
                      store in a seq-framed bounded ring (dag/channel.py
                      primitives). Steady state is pure write/poll: no
                      RPC of any kind moves per hop.
* ``DeviceChannel`` — the collective p2p plane (``util/collective`` ring
                      wire send/recv), exact or block-scale quantized via
                      the PR-7 codec. Payloads move worker→worker without
                      touching the driver or the object store, and every
                      op records into the comm flight ring (the group
                      methods are ``_traced_method``-wrapped), so the
                      hang doctor covers DAG wires for free.
* ``LocalChannel``  — bounded in-process asyncio ring for same-process
                      streams (the serve replica token stream rides it).
* socket            — legacy per-push RPC fallback (no channel object;
                      the driver/worker issue ``dag_push`` calls), kept
                      for explicitly requested ``channel="socket"``
                      edges.

Device-edge tags follow the rtgraph skeleton convention
(``dagch:p{epoch}:e{src}:{dst}:{slot}`` with all-integer holes — the
channel epoch fences pre-crash frames out of re-opened edges), so the
static commgraph extractor certifies DAG wires like any other channel
and the hang doctor's static reconciliation unifies runtime records
with these call sites.
"""

from __future__ import annotations

import time

import numpy as np

from ray_tpu._private import chaos, serialization
from ray_tpu.dag import channel as shm
from ray_tpu.util import tracing
from ray_tpu.util.collective import flight

# Wire marker for codec-compressed device payloads — same self-describing
# envelope the pipeline activation wire uses, so mixed exact/quantized
# edges share one decode path.
_ACT_WIRE = "__act"

# Wire marker for trace-carrying payloads (ISSUE 19): device/local
# channel frames have no header to extend, so a sampled trace context
# rides a compact ``(marker, ctx, payload)`` envelope instead. Only
# written when a context is actually flowing — the untraced payload
# shape is byte-identical to PR 15.
_TR_WIRE = "__tr"


def _resolve_ctx(trace):
    """The context a push should propagate: the explicit one the caller
    threaded through (a popped upstream context), else the ambient span
    (``tracing.inject()`` — None when tracing is disabled, which keeps
    the disabled path at one attribute read)."""
    return trace if trace is not None else tracing.inject()


def _push_span(ctx, *, channel: str, family: str, seq, nbytes: int):
    """Open the ``channel.push`` span whose OWN context rides the wire —
    the consumer's ``channel.pop`` parents on it, so the hop is causally
    linked producer → frame → consumer."""
    if ctx is None:
        return None, None
    span = tracing.begin(
        "channel.push", parent=ctx, channel=channel, family=family,
        seq=seq, nbytes=nbytes,
    )
    return span, {"trace_id": span.trace_id, "span_id": span.span_id}


class ChannelClosedError(RuntimeError):
    """The channel's owning loop was stopped while an op was blocked."""


class ShmChannel:
    """One shm-ring edge: bounded ring of seq-framed slots, producer
    busy-waits on slot reuse (the consumer's free IS the backpressure
    release), consumer polls non-blockingly (timeout_ms=0 keeps the
    store-client lock uncontended) with idle backoff."""

    def __init__(self, store, base: str, depth: int, *, group: str = "dag",
                 site: str = "dag", epoch: int = 0):
        self._store = store
        self.base = base
        self.depth = depth
        self._group = group
        self._site = site
        self.epoch = epoch
        # Trace context of the most recent pop (single-consumer rings:
        # each channel end is owned by exactly one loop thread, so a
        # side-channel attribute needs no lock and keeps pop's return
        # shape stable).
        self.last_trace: dict | None = None

    def push(self, seq: int, value, timeout: float = 120.0, stop=None,
             trace: dict | None = None) -> None:
        parts, total, _ = serialization.serialize_parts(value)
        self.push_parts(seq, parts, total, timeout=timeout, stop=stop,
                        trace=trace)

    def push_parts(self, seq: int, parts, total: int,
                   timeout: float = 120.0, stop=None,
                   trace: dict | None = None) -> None:
        ctx = _resolve_ctx(trace)
        span, wire_ctx = _push_span(
            ctx, channel=self.base, family="shm", seq=seq, nbytes=total,
        )
        wire = tracing.pack_ctx(wire_ctx) if wire_ctx else b""
        name = shm.slot_name(self.base, seq, self.depth)
        deadline = time.monotonic() + timeout
        while not shm.try_write_seq(
            self._store, name, seq, parts, total, epoch=self.epoch,
            trace=wire,
        ):
            if stop is not None and stop():
                raise ChannelClosedError(f"{self.base}: channel closed")
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"channel slot {name} still unread after {timeout}s"
                )
            time.sleep(0.002)
        with flight.site(self._site), flight.trace(
            ctx["trace_id"] if ctx else None
        ):
            flight.note(self._group, "chan_push", tag=self.base, nbytes=total)
        if span is not None:
            tracing.finish(span)

    def pop(self, seq: int, timeout: float | None = None, stop=None):
        name = shm.slot_name(self.base, seq, self.depth)
        deadline = None if timeout is None else time.monotonic() + timeout
        started = time.monotonic()
        delay = 0.002
        trace_out: list = []
        while True:
            value = shm.read_seq_consume(
                self._store, name, seq, epoch=self.epoch,
                trace_out=trace_out,
            )
            if value is not shm.NOT_READY:
                ctx = (
                    tracing.unpack_ctx(trace_out[0]) if trace_out else None
                )
                self.last_trace = ctx
                with flight.site(self._site), flight.trace(
                    ctx["trace_id"] if ctx else None
                ):
                    flight.note(self._group, "chan_pop", tag=self.base)
                if ctx is not None:
                    # The pop span covers the wait-for-frame window and
                    # parents on the producer's channel.push context
                    # that rode the frame header.
                    wait_s = time.monotonic() - started
                    end_ns = time.time_ns()
                    tracing.emit(
                        "channel.pop", ctx,
                        start_ns=end_ns - int(wait_s * 1e9),
                        end_ns=end_ns, channel=self.base, family="shm",
                        seq=seq,
                    )
                return value
            if stop is not None and stop():
                raise ChannelClosedError(f"{self.base}: channel closed")
            now = time.monotonic()
            if deadline is not None and now > deadline:
                raise TimeoutError(
                    f"channel slot {name} not ready in {timeout}s"
                )
            time.sleep(delay)
            if now - started > 1.0:
                # Idle backoff: a cold edge must not hammer the store
                # server with 500 polls/s forever; a hot edge never gets
                # past the 2ms floor.
                delay = min(delay * 2, 0.05)

    def free_slots(self) -> None:
        """Delete every ring slot (teardown; idempotent)."""
        for i in range(self.depth):
            shm._free_slot(self._store, f"{self.base}-{i}")


class DeviceChannel:
    """One edge on the collective p2p plane.

    Two calling modes share the instance:

    * edge mode (``push_edge``/``pop_edge``) — the rtdag executor's fixed
      (src, dst, slot) identity; the wire tag is the certified skeleton
      ``dagch:p{epoch}:e{src}:{dst}:{slot}``.
    * tagged mode (``push``/``pop`` with a keyword-only ``tag``) — the
      pipeline stage runner's per-(step, microbatch, virtual-stage) tags;
      the caller's f-string IS the certified site.

    Ordering rides the ring wire's per-(peer, tag) mailbox sequence
    numbers; bounded driver admission bounds mailbox growth. With a
    ``wire_cfg`` (PR-7 codec), float ndarrays are block-scale quantized
    with per-edge error feedback; everything else stays exact.
    """

    def __init__(self, group, peer: int, *, src: int = 0, dst: int = 0,
                 slot: int = 0, site: str = "dag", wire_cfg=None, ef=None,
                 epoch: int = 0):
        self._group = group
        self._peer = peer
        self._src = src
        self._dst = dst
        self._slot = slot
        self._site = site
        self._wire_cfg = wire_cfg
        self._ef = ef
        self.epoch = epoch
        self.last_trace: dict | None = None

    # -- tagged mode (pipeline wire) ------------------------------------
    def push(self, value, *, tag: str, ef_site=None) -> None:
        payload = self._encode(value, ef_site)
        with flight.site(self._site):
            self._group.send(payload, self._peer, tag=tag)

    def pop(self, *, tag: str, timeout: float = 60.0, like=None):
        with flight.site(self._site):
            out = self._group.recv(
                self._peer, tag=tag, timeout=timeout, like=like
            )
        return self._decode(out)

    # -- edge mode (rtdag wire) -----------------------------------------
    # The channel epoch rides the tag itself (``p{epoch}``): a frame sent
    # before a crash-recovery epoch bump lands in a mailbox no
    # post-recovery pop ever reads, so stale device frames are fenced by
    # construction. All holes are integers, so the commgraph extractor
    # still folds every DAG wire to one certified skeleton.
    def push_edge(self, value, trace: dict | None = None) -> None:
        tag = f"dagch:p{self.epoch}:e{self._src}:{self._dst}:{self._slot}"
        payload = self._encode(value, (self._src, self._dst, self._slot))
        ctx = _resolve_ctx(trace)
        span, wire_ctx = _push_span(
            ctx, channel=tag, family="device", seq=None, nbytes=0,
        )
        if wire_ctx is not None:
            # The device wire has no frame header to extend — the
            # context rides a compact envelope around the payload.
            payload = (_TR_WIRE, wire_ctx, payload)
        with flight.site(self._site), flight.trace(
            ctx["trace_id"] if ctx else None
        ):
            # Tag f-string inlined at the call: the commgraph extractor
            # reads tag= literals at send/recv sites to certify the wire.
            self._group.send(
                payload, self._peer,
                tag=f"dagch:p{self.epoch}:e{self._src}:{self._dst}:{self._slot}",
            )
        if span is not None:
            tracing.finish(span)

    def pop_edge(self, *, timeout: float = 60.0, like=None):
        # Chaos latency point: a windowed schedule makes the whole device
        # wire slow-but-alive, which is exactly what the supervisor's
        # false-positive tests need to distinguish from death.
        extra = chaos.latency_delay("dag.device.pop")
        if extra > 0:
            time.sleep(extra)
        started = time.monotonic()
        with flight.site(self._site):
            out = self._group.recv(
                self._peer,
                tag=f"dagch:p{self.epoch}:e{self._src}:{self._dst}:{self._slot}",
                timeout=timeout, like=like,
            )
        if (
            isinstance(out, tuple) and len(out) == 3 and out[0] == _TR_WIRE
        ):
            _, ctx, out = out
            self.last_trace = ctx
            wait_s = time.monotonic() - started
            end_ns = time.time_ns()
            tracing.emit(
                "channel.pop", ctx,
                start_ns=end_ns - int(wait_s * 1e9), end_ns=end_ns,
                channel=(
                    f"dagch:p{self.epoch}:"
                    f"e{self._src}:{self._dst}:{self._slot}"
                ),
                family="device",
            )
        else:
            self.last_trace = None
        return self._decode(out)

    # -- codec ----------------------------------------------------------
    def _encode(self, value, ef_site):
        if (
            self._wire_cfg is not None
            and self._ef is not None
            and ef_site is not None
            and isinstance(value, np.ndarray)
            and value.dtype.kind == "f"
        ):
            enc = self._ef.encode(ef_site, value.ravel(), self._wire_cfg)
            return (_ACT_WIRE, value.shape, value.dtype.str, enc)
        return value

    def _decode(self, out):
        if isinstance(out, tuple) and len(out) == 4 and out[0] == _ACT_WIRE:
            from ray_tpu.util.collective.quantization import decode

            _, shape, dtype_str, enc = out
            return decode(enc).reshape(shape).astype(np.dtype(dtype_str))
        return out


class LocalChannel:
    """Bounded in-process channel for asyncio producers/consumers — the
    rtdag family backing same-process streams (serve replica token
    streams). ``pop_batch`` implements the batched-drain semantics the
    streaming RPC needs: one blocking wait, then drain without waiting."""

    def __init__(self, maxsize: int = 256, *, group: str = "dag",
                 label: str = ""):
        import asyncio

        self._q: "asyncio.Queue" = asyncio.Queue(maxsize=maxsize)
        self._group = group
        self._label = label
        self._closed = False
        self.last_trace: dict | None = None
        # Lifecycle-only flight notes: per-item records would rotate
        # genuinely stalled ops out of the bounded flight ring.
        flight.note(self._group, "chan_open", tag=label)

    async def put(self, item, trace: dict | None = None) -> None:
        if self._closed:
            raise ChannelClosedError(f"{self._label}: channel closed")
        if trace is not None:
            # Same compact envelope as the device wire: the consumer's
            # pop_batch unwraps and surfaces the context on last_trace.
            item = (_TR_WIRE, trace, item)
        await self._q.put(item)

    def qsize(self) -> int:
        return self._q.qsize()

    async def pop_batch(self, max_items: int, timeout_s: float) -> list:
        """Block up to ``timeout_s`` for the first item, then drain up to
        ``max_items`` without waiting. Returns [] on timeout."""
        import asyncio

        items: list = []
        try:
            items.append(await asyncio.wait_for(self._q.get(), timeout_s))
        except asyncio.TimeoutError:
            return items
        while len(items) < max_items:
            try:
                items.append(self._q.get_nowait())
            except asyncio.QueueEmpty:
                break
        unwrapped: list = []
        for item in items:
            if (
                isinstance(item, tuple) and len(item) == 3
                and item[0] == _TR_WIRE
            ):
                self.last_trace = item[1]
                item = item[2]
            unwrapped.append(item)
        return unwrapped

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            flight.note(self._group, "chan_close", tag=self._label)

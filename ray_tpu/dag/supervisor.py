"""rtdag supervisor — driver-side crash recovery for compiled graphs.

A supervised CompiledDAG (``experimental_compile(supervise=True)``) that
sees a ``DAGActorDiedError`` — from a liveness probe the blocked reader
ran between pop slices, or short-circuited by the comm watchdog's stall
listener — calls :func:`recover` instead of surfacing the error. The
sequence:

1. **Diagnose** — probe every DAG actor's controller state; every DEAD
   one is a victim (the triggering error names at least one).
2. **Restart** — resurrect each victim through the controller's normal
   lease path (``restart_actor``; mutation-token idempotent), then poll
   with full-jitter backoff until ALIVE. The replacement may land on a
   different node — the re-lower below re-derives edge families.
3. **Quiesce** — stop every surviving stage loop (``dag_teardown``,
   idempotent, best-effort to the dead) and drop the driver's old-epoch
   collective group; sweep every old shm ring slot. Anything that slips
   the sweep is fenced by the epoch header.
4. **Re-open** — bump the channel epoch, re-resolve placement (ranks are
   stable: same actor order), re-lower the graph, restore committed
   ``__dag_snapshot__`` state to every hooked actor (survivors roll back
   too — the graph restarts from ONE consistent cut), re-register every
   stage at ``(epoch, start_seq)`` (per-epoch collective group name),
   and re-open the driver's channel ends (readers refit in place).
5. **Replay** — re-push every retained input from the replay base in
   order, draining laggard readers so ring-depth backpressure can't
   wedge a >depth replay. Consumers discard replayed seqs below their
   old cursors, so ``execute()`` stays exactly-once end to end.

Steady state costs nothing: no timer, no thread, no extra RPC — all of
this is reached only from a failed pop.
"""

from __future__ import annotations

import time

from ray_tpu import exceptions
from ray_tpu.dag import placement
from ray_tpu.util.backoff import Backoff
from ray_tpu.util.collective import flight

# How long a recovery will wait for one victim to come back ALIVE
# through the lease path before giving up (matches the controller's own
# scheduling deadline).
RECOVERY_TIMEOUT_S = 120.0

# Generous per-seq ceiling for the replay pump: replayed frames flow
# through already-warm stages, so this only bounds a pathological wedge.
_REPLAY_DRAIN_TIMEOUT_S = 60.0


def _actor_state(dag, actor_id: str) -> dict:
    try:
        return dag._ctx.io.run(
            dag._ctx.controller.call(
                "get_actor_info", {"actor_id": actor_id}, timeout=10
            ),
            timeout=15,
        ) or {}
    except Exception:  # rtlint: disable=swallowed-exception - controller hiccup: caller treats unknown as not-yet-alive
        return {}


def _find_victims(dag, err: exceptions.DAGActorDiedError) -> list[str]:
    victims = []
    for aid in dag._actor_ids:
        if aid == err.actor_id:
            victims.append(aid)
            continue
        if _actor_state(dag, aid).get("state") == "DEAD":
            victims.append(aid)
    return victims


def _restart_victim(dag, actor_id: str, new_epoch: int) -> None:
    """Resurrect one dead actor through the controller lease path and
    wait for it to come back ALIVE. The mutation token makes a re-sent
    restart (dropped reply, reconnect replay) a no-op instead of a
    double-schedule."""
    ctx = dag._ctx
    resp = ctx.io.run(
        ctx.controller.call("restart_actor", {
            "actor_id": actor_id,
            "mutation_token": f"dag-restart:{dag.dag_id}:{actor_id}:{new_epoch}",
        }, timeout=30),
        timeout=45,
    )
    if (resp or {}).get("status") != "ok":
        raise exceptions.ActorDiedError(
            f"{dag.dag_id}: controller refused to restart actor "
            f"{actor_id}: {resp!r}"
        )
    # The old address is poison now; the resolver re-learns the new one.
    ctx._actor_addr_cache.pop(actor_id, None)
    deadline = time.monotonic() + RECOVERY_TIMEOUT_S
    backoff = Backoff(initial_backoff_s=0.05, max_backoff_s=2.0)
    while True:
        state = _actor_state(dag, actor_id).get("state")
        if state == "ALIVE":
            ctx._actor_addr_cache.pop(actor_id, None)
            return
        if state == "DEAD":
            raise exceptions.ActorDiedError(
                f"{dag.dag_id}: actor {actor_id} died again while "
                "restarting (lease path exhausted)"
            )
        if time.monotonic() > deadline:
            raise exceptions.ActorDiedError(
                f"{dag.dag_id}: actor {actor_id} not ALIVE within "
                f"{RECOVERY_TIMEOUT_S}s of restart (state={state!r})"
            )
        time.sleep(backoff.next_delay(cap=deadline - time.monotonic()))


def _quiesce(dag) -> None:
    """Stop every surviving stage loop and sweep every old-epoch shm
    ring slot. Idempotent and best-effort: dead actors can't ack, and a
    frame that slips the sweep is fenced by its stale epoch header."""
    ctx = dag._ctx

    async def _teardown_all():
        import asyncio

        async def one(aid):
            try:
                client = await ctx._actor_client(aid)
                await client.call(
                    "dag_teardown", {"dag_id": dag.dag_id}, timeout=10
                )
            except Exception:  # rtlint: disable=swallowed-exception - victim can't ack its own teardown
                pass

        await asyncio.gather(*[one(aid) for aid in dag._actor_ids])

    try:
        ctx.io.run(_teardown_all(), timeout=30)
    except Exception:  # rtlint: disable=swallowed-exception - quiesce is best-effort; epoch fencing covers stragglers
        pass
    for base in dag._all_shm_bases:
        for i in range(dag.CHANNEL_DEPTH):
            try:
                ctx.store.delete(f"{base}-{i}")
            except Exception:  # rtlint: disable=swallowed-exception - slot already freed
                pass


def _restore_snapshots(dag) -> None:
    if not dag._snapshots:
        return
    for aid, blob in dag._snapshots.items():
        resp = dag._call_actor(
            aid, "dag_restore",
            {"dag_id": dag.dag_id, "blob": blob}, timeout=60,
        )
        if (resp or {}).get("status") != "ok":
            raise RuntimeError(
                f"{dag.dag_id}: dag_restore failed on actor {aid}: {resp!r}"
            )


def _replay(dag, start_seq: int) -> None:
    """Re-push every retained input from the replay base, in order.
    When a replayed seq would outrun the slowest reader by a full ring
    depth, drain that reader first — its frames are buffered (or
    discarded as duplicates) driver-side, so backpressure never wedges
    a longer-than-depth replay."""
    for seq in sorted(s for s in dag._retained if s >= start_seq):
        while dag._out_readers:
            laggard = min(dag._out_readers, key=lambda r: r._next)
            if seq - laggard._next < dag.CHANNEL_DEPTH:
                break
            laggard.drain_one(time.monotonic() + _REPLAY_DRAIN_TIMEOUT_S)
        value, trace = dag._retained[seq]
        dag._push_input(seq, value, trace=trace)


def _doctor_ranks(dag) -> list[int]:
    """Best-effort: what the hang doctor's merged report blames, for
    cross-checking against the supervisor's own victim ranks."""
    try:
        from ray_tpu._private import hang_doctor
        from ray_tpu.util import state

        report = state.get_hang_report(fresh=False, stacks=False)
        return sorted(hang_doctor.blamed_ranks(report))
    except Exception:  # rtlint: disable=swallowed-exception - no report yet / controller gone: agreement is advisory
        return []


def recover(dag, err: exceptions.DAGActorDiedError) -> None:
    """Restart victims, re-open every channel under a bumped epoch, and
    replay the retained inputs. Raises (and the caller tears the graph
    down) if any step fails — a half-recovered graph is worse than a
    dead one."""
    t0 = time.monotonic()
    new_epoch = dag._epoch + 1
    victims = _find_victims(dag, err)
    with flight.site("dag"):
        # Fixed-shape flight records: the new epoch rides the seq field,
        # the triggering edge rides the tag.
        flight.note(
            dag.dag_id, "dag_recovery_start", tag=err.channel or "",
            seq=new_epoch,
        )
    for aid in victims:
        _restart_victim(dag, aid, new_epoch)
    _quiesce(dag)
    dag._destroy_group(sync=True)
    dag._epoch = new_epoch
    plan = placement.PlacementPlan.resolve(dag._ctx, dag._actor_ids)
    old_ranks = {aid: dag._plan.rank_of(aid) for aid in dag._actor_ids}
    for aid in dag._actor_ids:
        if plan.rank_of(aid) != old_ranks[aid]:
            raise RuntimeError(
                f"{dag.dag_id}: rank drift on recovery for actor {aid} "
                f"({old_ranks[aid]} -> {plan.rank_of(aid)})"
            )
    dag._plan = plan
    dag._lower(plan)
    _restore_snapshots(dag)
    if dag._retained:
        start_seq = min(dag._retained)
    elif dag._snapshot_base is not None:
        start_seq = dag._snapshot_base
    else:
        start_seq = dag._submitted
    dag._register(
        plan, need_group="device" in dag._families,
        epoch=new_epoch, start_seq=start_seq,
    )
    dag._open_driver_channels(plan, start_seq)
    _replay(dag, start_seq)
    dag._stall_event.clear()
    duration = time.monotonic() - t0
    dag.last_recovery = {
        "victims": victims,
        "victim_ranks": sorted(plan.rank_of(a) for a in victims),
        "doctor_ranks": _doctor_ranks(dag),
        "epoch": new_epoch,
        "start_seq": start_seq,
        "duration_s": duration,
    }
    with flight.site("dag"):
        flight.note(
            dag.dag_id, "dag_recovery_done", tag=err.channel or "",
            seq=new_epoch,
        )

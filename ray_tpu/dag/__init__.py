from ray_tpu.dag.dag import (
    ClassMethodNode,
    CompiledDAG,
    DAGNode,
    DAGRef,
    InputNode,
    MultiOutputNode,
)

__all__ = [
    "InputNode",
    "DAGNode",
    "ClassMethodNode",
    "CompiledDAG",
    "DAGRef",
    "MultiOutputNode",
]

"""Dashboard — REST backend + minimal UI.

Role-equivalent of python/ray/dashboard/head.py + modules/{node,actor,job,
state,metrics} (SURVEY §2.3, §5.5): an aiohttp server aggregating
controller state into JSON endpoints, a Prometheus /metrics endpoint
(fed by ray_tpu.util.metrics), per-node log listing from the session dir,
and a single-page HTML overview. Runs in-process of the driver (thread)
or as a detached actor via start_dashboard().
"""

from __future__ import annotations

import asyncio
import glob
import json
import os
import threading
import time
from typing import Optional

import ray_tpu
from ray_tpu.util import metrics as metrics_mod
from ray_tpu.util import state as state_mod

_INDEX_HTML = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<style>
 body { font-family: system-ui, sans-serif; margin: 2rem; color: #222; }
 h1 { font-size: 1.3rem; } h2 { font-size: 1.05rem; margin-top: 1.5rem; }
 table { border-collapse: collapse; min-width: 40rem; }
 td, th { border: 1px solid #ccc; padding: 4px 10px; font-size: 0.85rem; }
 th { background: #f4f4f4; text-align: left; }
 code { background: #f4f4f4; padding: 1px 4px; }
 nav a { margin-right: 1rem; }
 .muted { color: #888; font-size: 0.8rem; }
</style></head>
<body>
<h1>ray_tpu dashboard</h1>
<nav>
 <a href="#" onclick="view='overview';refresh();return false">overview</a>
 <a href="#" onclick="view='tasks';refresh();return false">tasks</a>
 <a href="#" onclick="view='jobs';refresh();return false">jobs</a>
 <a href="#" onclick="view='serveView';refresh();return false">serve</a>
 <a href="#" onclick="view='sequences';refresh();return false">sequences</a>
 <a href="#" onclick="view='workers';refresh();return false">workers</a>
 <a href="#" onclick="view='resources';refresh();return false">resources</a>
 <a href="#" onclick="view='workload';refresh();return false">workload</a>
 <a href="#" onclick="view='logs';refresh();return false">logs</a>
 <a href="#" onclick="view='autoscaler';refresh();return false">autoscaler</a>
 <a href="#" onclick="view='events';refresh();return false">events</a>
 <a href="/api/timeline">timeline</a>
 <a href="/metrics">metrics</a>
 <a href="/api/grafana_dashboard" download="raytpu-grafana.json">grafana</a>
</nav>
<div id="content">loading…</div>
<script>
let view = 'overview';
function esc(s) {
  return String(s).replace(/&/g,'&amp;').replace(/</g,'&lt;').replace(/>/g,'&gt;');
}
function table(headers, rows) {
  let h = '<table><tr>' + headers.map(x => `<th>${esc(x)}</th>`).join('') + '</tr>';
  for (const r of rows) h += '<tr>' + r.map(x => `<td>${x}</td>`).join('') + '</tr>';
  return h + '</table>';
}
async function overview() {
  const [cluster, nodes, actors, pgs] = await Promise.all([
    fetch('/api/cluster').then(r => r.json()),
    fetch('/api/nodes').then(r => r.json()),
    fetch('/api/actors').then(r => r.json()),
    fetch('/api/placement_groups').then(r => r.json()),
  ]);
  let html = '<h2>Cluster resources</h2>' + table(
    ['resource', 'available', 'total'],
    Object.keys(cluster.total).map(k =>
      [esc(k), esc(cluster.available[k] ?? 0), esc(cluster.total[k])]));
  html += '<h2>Nodes</h2>' + table(['node', 'alive', 'resources'],
    nodes.map(n => [`<code>${esc(n.node_id)}</code>`, esc(n.alive),
                    esc(JSON.stringify(n.resources_total))]));
  html += '<h2>Actors</h2>' + table(['actor', 'class', 'state', 'node'],
    actors.map(a => [`<code>${esc(a.actor_id)}</code>`, esc(a.class_name ?? ''),
                     esc(a.state), `<code>${esc(a.node_id ?? '')}</code>`]));
  html += '<h2>Placement groups</h2>' + table(['pg', 'state', 'bundles'],
    pgs.map(p => [`<code>${esc(p.pg_id)}</code>`, esc(p.state),
                  esc(JSON.stringify(p.bundles))]));
  return html;
}
async function tasks() {
  const rows = await fetch('/api/tasks').then(r => r.json());
  const when = t => {
    const ts = t.end_time ?? t.start_time;
    return ts ? new Date(ts * 1000).toLocaleTimeString() : '';
  };
  return '<h2>Recent tasks</h2>' + table(
    ['task', 'name', 'state', 'node', 'time'],
    rows.slice(-200).reverse().map(t =>
      [`<code>${esc((t.task_id ?? '').slice(-12))}</code>`, esc(t.name),
       esc(t.state), `<code>${esc((t.node_id ?? '').slice(-8))}</code>`,
       esc(when(t))]));
}
async function jobs() {
  const rows = await fetch('/api/jobs').then(r => r.json());
  return '<h2>Jobs</h2>' + table(['job', 'state', 'started'],
    rows.map(j => [`<code>${esc(j.job_id)}</code>`, esc(j.state),
                   esc(new Date(j.start_time * 1000).toLocaleString())]));
}
async function events() {
  const rows = await fetch('/api/events?limit=200').then(r => r.json());
  return '<h2>Exported events</h2><div class="muted">structured lifecycle export (events_*.jsonl)</div>' +
    table(['time', 'source', 'data'],
      rows.reverse().map(e =>
        [esc(new Date(e.timestamp * 1000).toLocaleTimeString()),
         esc(e.source_type),
         `<code>${esc(JSON.stringify(e.data).slice(0, 140))}</code>`]));
}
async function serveView() {
  const apps = await fetch('/api/serve').then(r => r.json());
  if (apps.__error__) return '<h2>Serve</h2><div>error: ' + esc(apps.__error__) + '</div>';
  const names = Object.keys(apps);
  if (!names.length) return '<h2>Serve</h2><div class="muted">no applications deployed</div>';
  let html = '<h2>Serve applications</h2>';
  for (const app of names) {
    const info = apps[app];
    html += `<h2>${esc(app)} <span class="muted">${esc(info.status ?? '')}</span></h2>`;
    const deps = info.deployments ?? {};
    html += table(['deployment', 'status', 'replicas'],
      Object.keys(deps).map(d => [esc(d), esc(JSON.stringify(deps[d].status ?? deps[d])),
        esc(deps[d].running_replicas ?? '')]));
  }
  return html;
}
async function sequences() {
  const s = await fetch('/api/sequences').then(r => r.json());
  const ms = v => (typeof v === 'number' ? (1000 * v).toFixed(1) : '');
  let html = '<h2>Served sequences</h2><div class="muted">' +
    `sampled terminal records ${esc(s.count ?? 0)} · ` +
    `TTFT p50/p99 ${esc(ms(s.ttft_p50_s))}/${esc(ms(s.ttft_p99_s))} ms · ` +
    `TPOT p50/p99 ${esc(ms(s.tpot_p50_s))}/${esc(ms(s.tpot_p99_s))} ms</div>`;
  const led = s.ledger ?? {};
  html += '<h2>Token ledger</h2>' + table(['class', 'tokens'],
    ['issued', 'productive', 'shed', 'evicted', 'replay_discarded']
      .map(k => [esc(k), esc(led[k] ?? 0)]));
  const rows = s.sequences ?? [];
  if (!rows.length) return html + '<div class="muted">no sampled sequences yet ' +
    '(enable tracing + LLMConfig.seq_trace_sample)</div>';
  html += '<h2>Recent sequences</h2>' + table(
    ['request', 'outcome', 'cause', 'tokens', 'queue ms', 'prefill ms',
     'kv ms', 'TTFT ms', 'TPOT p99 ms', 'trace'],
    rows.slice().reverse().map(r =>
      [`<code>${esc((r.request_id ?? '').slice(0, 18))}</code>`,
       esc(r.outcome ?? ''), esc(r.cause ?? ''), esc(r.tokens ?? 0),
       esc(ms(r.queue_wait_s)), esc(ms(r.prefill_s)), esc(ms(r.kv_transfer_s)),
       esc(ms(r.ttft_s)), esc(ms(r.tpot_p99_s)),
       `<code>${esc((r.trace_id ?? '').slice(0, 12))}</code>`]));
  return html;
}
function fmtBytes(b) {
  if (b === undefined || b === null) return '';
  const units = ['B', 'KiB', 'MiB', 'GiB', 'TiB'];
  let i = 0;
  while (b >= 1024 && i < units.length - 1) { b /= 1024; i++; }
  return b.toFixed(i ? 1 : 0) + ' ' + units[i];
}
function spark(points, key, w = 240, h = 36) {
  const vals = points.map(p => p[key]).filter(v => typeof v === 'number');
  if (vals.length < 2) return '<span class="muted">gathering…</span>';
  const min = Math.min(...vals), max = Math.max(...vals);
  const span = (max - min) || 1;
  const pts = vals.map((v, i) =>
    `${(i / (vals.length - 1) * w).toFixed(1)},` +
    `${(h - 2 - (v - min) / span * (h - 4)).toFixed(1)}`).join(' ');
  return `<svg width="${w}" height="${h}"><polyline points="${pts}"` +
    ` fill="none" stroke="#36c" stroke-width="1.5"/></svg>`;
}
async function resources() {
  const s = await fetch('/api/resources').then(r => r.json());
  const cf = await fetch('/api/commflight').then(r => r.json()).catch(() => ({}));
  const ids = Object.keys(s.nodes ?? {});
  const cfWorkers = cf.inflight ?? {};
  const cfTotal = Object.values(cfWorkers)
    .reduce((a, v) => a + (v.inflight ?? 0), 0);
  let html = '<h2>Resources</h2><div class="muted">' +
    `ingested ${esc(s.total_ingested ?? 0)} samples · ` +
    `dropped ${esc(s.total_dropped ?? 0)} · ` +
    `oom_risk events ${esc(s.oom_risk_events ?? 0)} · ` +
    `comm in-flight ${esc(cfTotal)} · ` +
    `comm stalls ${esc(cf.stall_total ?? 0)}` +
    (cf.last_stall_age_s != null
      ? ` (last ${esc(cf.last_stall_age_s.toFixed?.(0) ?? '')}s ago)` : '') +
    '</div>';
  if (Object.keys(cfWorkers).length) {
    html += '<h2>Comm flight</h2>' + table(
      ['worker', 'in-flight', 'oldest op age'],
      Object.entries(cfWorkers).map(([w, v]) =>
        [esc(w.slice(-26)), esc(v.inflight ?? 0),
         (v.inflight ? esc((v.oldest_age_s ?? 0).toFixed?.(1) ?? '') + 's' : '-')]));
  }
  if (!ids.length) return html + '<div class="muted">no telemetry yet</div>';
  for (const id of ids) {
    const tl = await fetch('/api/timeseries?node_id=' +
      encodeURIComponent(id) + '&tier=raw').then(r => r.json());
    const pts = tl.raw ?? [];
    const n = s.nodes[id], latest = n.latest ?? {};
    html += `<h2><code>${esc(id.slice(-12))}</code> ` +
      `<span class="muted">${n.alive ? 'alive' : 'dead'} · ` +
      `tiers raw:${esc(n.points?.raw ?? 0)} 10s:${esc(n.points?.['10s'] ?? 0)} ` +
      `60s:${esc(n.points?.['60s'] ?? 0)}</span></h2>`;
    const rows = [
      ['cpu %', esc((latest.cpu_percent ?? 0).toFixed?.(1) ?? ''), spark(pts, 'cpu_percent')],
      ['node mem', fmtBytes(latest.mem_used) + ' / ' + fmtBytes(latest.mem_total), spark(pts, 'mem_used')],
      ['workers rss', fmtBytes(latest.workers_rss_total) + ` (${esc(latest.num_workers ?? 0)} workers)`, spark(pts, 'workers_rss_total')],
      ['object store', fmtBytes(latest.object_store_bytes), spark(pts, 'object_store_bytes')],
    ];
    if (latest.hbm_total)
      rows.push(['TPU HBM', fmtBytes(latest.hbm_used) + ' / ' + fmtBytes(latest.hbm_total), spark(pts, 'hbm_used')]);
    html += table(['metric', 'now', 'raw history'], rows);
  }
  return html;
}
async function workload() {
  const s = await fetch('/api/workload').then(r => r.json());
  const keys = Object.keys(s.series ?? {});
  let html = '<h2>Workload flight recorder</h2><div class="muted">' +
    `ingested ${esc(s.total_ingested ?? 0)} samples · ` +
    `dropped ${esc(s.total_dropped ?? 0)}</div>`;
  if (!keys.length) return html + '<div class="muted">no workload series yet ' +
    '(train a model or send serve traffic)</div>';
  const pct = v => (typeof v === 'number' ? (100 * v).toFixed(1) + '%' : '');
  for (const key of keys.sort()) {
    const entry = s.series[key], latest = entry.latest ?? {};
    const tl = await fetch('/api/workload?key=' +
      encodeURIComponent(key) + '&tier=raw').then(r => r.json());
    const pts = tl.raw ?? [];
    html += `<h2><code>${esc(key)}</code></h2>`;
    let rows;
    if (key.endsWith('/goodput')) {
      rows = [
        ['goodput', pct(latest.goodput_fraction), spark(pts, 'goodput_fraction')],
        ['wall s', esc((latest.wall_s ?? 0).toFixed?.(1) ?? ''), spark(pts, 'wall_s')],
        ['checkpoint s', esc((latest.checkpoint_s ?? 0).toFixed?.(1) ?? ''), spark(pts, 'checkpoint_s')],
        ['restart s', esc((latest.restart_s ?? 0).toFixed?.(1) ?? ''), spark(pts, 'restart_s')],
      ];
    } else if (key.startsWith('serve/')) {
      rows = [
        ['p50 ms', esc((latest.p50_ms ?? 0).toFixed?.(1) ?? ''), spark(pts, 'p50_ms')],
        ['p99 ms', esc((latest.p99_ms ?? 0).toFixed?.(1) ?? ''), spark(pts, 'p99_ms')],
        ['qps', esc((latest.qps ?? 0).toFixed?.(1) ?? ''), spark(pts, 'qps')],
        ['errors', esc(latest.errors ?? 0), spark(pts, 'errors')],
      ];
    } else {
      rows = [
        ['tokens/s', esc((latest.tokens_per_s ?? 0).toFixed?.(0) ?? ''), spark(pts, 'tokens_per_s')],
        ['MFU', pct(latest.mfu), spark(pts, 'mfu')],
        ['data-wait', pct(latest.data_wait_frac), spark(pts, 'data_wait_frac')],
        ['collective', pct(latest.collective_frac), spark(pts, 'collective_frac')],
        ['steps', esc(latest.steps ?? 0), spark(pts, 'steps')],
      ];
    }
    html += table(['metric', 'now', 'raw history'], rows);
  }
  return html;
}
async function workers() {
  const rows = await fetch('/api/workers').then(r => r.json());
  return '<h2>Workers</h2>' + table(['worker', 'node', 'pid/state'],
    rows.slice(-200).map(w => [`<code>${esc((w.worker_id ?? '').slice(-12))}</code>`,
      `<code>${esc((w.node_id ?? '').slice(-8))}</code>`,
      esc(w.pid ?? w.state ?? '')]));
}
async function logs() {
  const files = await fetch('/api/logs').then(r => r.json());
  return '<h2>Session logs</h2>' + table(['file'],
    files.map(f => [`<a href="/api/logs/${encodeURIComponent(f)}">${esc(f)}</a>`]));
}
async function autoscaler() {
  const s = await fetch('/api/autoscaler').then(r => r.json());
  if (!s.enabled) return '<h2>Autoscaler</h2><div class="muted">not running ' +
    '(start with ray_tpu.init(autoscaling="v2") or ray_tpu start --head --autoscaler=v2)</div>';
  let html = `<h2>Autoscaler <span class="muted">${esc(s.version ?? '')}</span></h2>`;
  html += `<div class="muted">last update ${esc(new Date((s.ts ?? 0) * 1000).toLocaleTimeString())}</div>`;
  if (s.error) return html + `<div>monitor error: <code>${esc(s.error)}</code></div>`;
  const inst = s.instances ?? {};
  html += table(['instance state', 'count'],
    Object.keys(inst).map(k => [esc(k), esc(inst[k])]));
  html += table(['metric', 'value'],
    [['slices requested (last update)', esc(s.slices_requested ?? '-')],
     ['slices drained (last update)', esc(s.slices_drained ?? '-')],
     ['launched', esc(s.launched ?? '-')], ['terminated', esc(s.terminated ?? '-')],
     ['pending demands', esc(s.pending_demands ?? '-')]]);
  return html;
}
async function refresh() {
  const render = {overview, tasks, jobs, serveView, sequences, workers,
                  resources, workload, logs, events, autoscaler}[view];
  try { document.getElementById('content').innerHTML = await render(); }
  catch (err) { document.getElementById('content').innerHTML = 'error: ' + esc(err); }
}
refresh(); setInterval(refresh, 3000);
</script>
</body></html>
"""


def _dumps(obj) -> str:
    return json.dumps(obj, default=str)


class DashboardHead:
    def __init__(self, host: str = "127.0.0.1", port: int = 8265,
                 session_dir: Optional[str] = None):
        self.host = host
        self.port = port
        self.session_dir = session_dir
        self._started = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=30):
            raise RuntimeError("dashboard failed to start")

    def _serve(self) -> None:
        asyncio.run(self._amain())

    def stop(self) -> None:
        loop = getattr(self, "_loop", None)
        if loop is not None and not loop.is_closed():
            loop.call_soon_threadsafe(self._stop_event.set)
        self._thread.join(timeout=10)

    async def _amain(self) -> None:
        from aiohttp import web

        app = web.Application()
        app.router.add_get("/", self._index)
        app.router.add_get("/api/cluster", self._cluster)
        app.router.add_get("/api/nodes", self._nodes)
        app.router.add_get("/api/actors", self._actors)
        app.router.add_get("/api/tasks", self._tasks)
        app.router.add_get("/api/placement_groups", self._pgs)
        app.router.add_get("/api/jobs", self._jobs)
        app.router.add_get("/api/logs", self._logs)
        app.router.add_get("/api/logs/{name}", self._log_file)
        app.router.add_get("/api/timeline", self._timeline)
        app.router.add_get("/api/resources", self._resources)
        app.router.add_get("/api/timeseries", self._timeseries)
        app.router.add_get("/api/workload", self._workload)
        app.router.add_get("/api/tracing", self._tracing)
        app.router.add_get("/api/events", self._events)
        app.router.add_get("/api/stacks", self._stacks)
        app.router.add_get("/api/commflight", self._commflight)
        app.router.add_post("/api/profile", self._profile)
        app.router.add_get("/api/profiles", self._profiles)
        app.router.add_get(
            "/api/profiles/{capture_id}/flamegraph", self._flamegraph
        )
        app.router.add_get("/api/serve", self._serve_state)
        app.router.add_get("/api/sequences", self._sequences)
        app.router.add_get("/api/workers", self._workers)
        app.router.add_get("/api/grafana_dashboard", self._grafana)
        app.router.add_get("/api/autoscaler", self._autoscaler)
        app.router.add_get("/metrics", self._metrics)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, self.host, self.port)
        await site.start()
        # port=0 → kernel-assigned; expose the real one for tests/clients.
        sockets = getattr(site._server, "sockets", None) or []
        self.bound_port = (
            sockets[0].getsockname()[1] if sockets else self.port
        )
        self._loop = asyncio.get_running_loop()
        self._stop_event = asyncio.Event()
        self._started.set()
        await self._stop_event.wait()
        await runner.cleanup()

    async def _index(self, request):
        from aiohttp import web

        return web.Response(text=_INDEX_HTML, content_type="text/html")

    async def _cluster(self, request):
        from aiohttp import web

        total = await asyncio.to_thread(ray_tpu.cluster_resources)
        available = await asyncio.to_thread(ray_tpu.available_resources)
        return web.json_response({"total": total, "available": available})

    async def _autoscaler(self, request):
        """Latest monitor status (the bootstrap-launched autoscaler
        publishes to the controller KV, namespace _autoscaler)."""
        import json as _json

        from aiohttp import web

        def read():
            from ray_tpu._private import worker as worker_mod

            ctx = worker_mod.get_global_context()
            resp = ctx.io.run(
                ctx.controller.call(
                    "kv_get", {"namespace": "_autoscaler", "key": "status"}
                )
            )
            if resp.get("status") != "ok":
                return {"enabled": False}
            value = resp["value"]
            if isinstance(value, (bytes, bytearray, memoryview)):
                value = bytes(value).decode()
            return {"enabled": True, **_json.loads(value)}

        return web.json_response(await asyncio.to_thread(read))

    async def _nodes(self, request):
        from aiohttp import web

        return web.json_response(
            await asyncio.to_thread(state_mod.list_nodes)
        )

    async def _actors(self, request):
        from aiohttp import web

        return web.json_response(
            await asyncio.to_thread(state_mod.list_actors)
        )

    async def _tasks(self, request):
        from aiohttp import web

        return web.json_response(await asyncio.to_thread(state_mod.list_tasks))

    async def _pgs(self, request):
        from aiohttp import web

        return web.json_response(
            await asyncio.to_thread(state_mod.list_placement_groups)
        )

    async def _jobs(self, request):
        from aiohttp import web

        return web.json_response(await asyncio.to_thread(state_mod.list_jobs))

    async def _logs(self, request):
        from aiohttp import web

        if not self.session_dir:
            return web.json_response([])
        files = sorted(
            os.path.basename(p)
            for p in glob.glob(os.path.join(self.session_dir, "logs", "*"))
        )
        return web.json_response(files)

    async def _log_file(self, request):
        from aiohttp import web

        name = os.path.basename(request.match_info["name"])
        path = os.path.join(self.session_dir or "", "logs", name)
        if not os.path.exists(path):
            return web.Response(status=404, text="no such log")
        try:
            lines = int(request.query.get("lines", "200"))
        except ValueError:
            return web.Response(
                status=400, text="?lines= must be an integer"
            )
        def _tail() -> bytes:
            with open(path, "rb") as f:
                # Tail without loading the whole file: a multi-GB worker
                # log must not transit driver memory for a 200-line view.
                f.seek(max(0, os.fstat(f.fileno()).st_size - 200_000))
                return f.read(200_000)

        # Off the event loop: a cold-cache read from a slow disk must not
        # stall every other dashboard request.
        data = await asyncio.to_thread(_tail)
        text = data.decode(errors="replace")
        return web.Response(text="\n".join(text.splitlines()[-lines:]))

    async def _timeline(self, request):
        from aiohttp import web

        from ray_tpu.util.timeline import build_chrome_trace

        def build():
            try:
                return ray_tpu.timeline()
            except Exception:
                # No driver connection: still render the span layer from
                # the session dir (task events need the controller).
                return build_chrome_trace(
                    self.session_dir, include_counters=False
                )

        return web.json_response(await asyncio.to_thread(build))

    async def _resources(self, request):
        """Cluster telemetry summary: per-node latest sample + tier
        depths (ISSUE 5; backs the 'resources' view and `ray_tpu top`)."""
        from aiohttp import web

        return web.json_response(
            await asyncio.to_thread(state_mod.summarize_resources),
            dumps=_dumps,
        )

    async def _commflight(self, request):
        """Comm-plane flight view (ISSUE 14): watchdog stall events,
        per-worker in-flight gauges, and — with ?report=1 — the latest
        merged hang report (?fresh=1 forces a harvest). The summary is a
        snapshot of controller state (never drained), so a retried fetch
        sees the same stalls: PR-5 snapshot-don't-drain."""
        from aiohttp import web

        out = await asyncio.to_thread(state_mod.summarize_commflight)
        if request.query.get("report"):
            out["report"] = await asyncio.to_thread(
                state_mod.get_hang_report,
                bool(request.query.get("fresh")),
                bool(request.query.get("stacks")),
            )
        return web.json_response(out, dumps=_dumps)

    _TIERS = ("raw", "10s", "60s")

    async def _timeseries(self, request):
        """GET ?node_id=...[&tier=raw|10s|60s] — one node's resource
        time-series from the controller's tiered ring-buffer store.
        Unknown node or tier is a 404 with a JSON error body, not an
        unhandled 500 (ISSUE 8 satellite)."""
        from aiohttp import web

        node_id = request.query.get("node_id", "")
        tier = request.query.get("tier") or None
        if tier is not None and tier not in self._TIERS:
            return web.json_response(
                {"error": f"unknown tier {tier!r}",
                 "tiers": list(self._TIERS)},
                status=404,
            )
        timeline = await asyncio.to_thread(
            state_mod.get_node_timeline, node_id, tier
        )
        if not timeline:
            return web.json_response(
                {"error": f"unknown node_id {node_id!r}"}, status=404
            )
        return web.json_response(timeline, dumps=_dumps)

    async def _workload(self, request):
        """Workload flight recorder (ISSUE 8). No params: summary of all
        series. ?key=train/<exp>[&tier=...]: one series' timeline.
        Unknown key/tier → 404 JSON error body."""
        from aiohttp import web

        key = request.query.get("key")
        tier = request.query.get("tier") or None
        if tier is not None and tier not in self._TIERS:
            return web.json_response(
                {"error": f"unknown tier {tier!r}",
                 "tiers": list(self._TIERS)},
                status=404,
            )
        if key is None:
            return web.json_response(
                await asyncio.to_thread(state_mod.summarize_workload),
                dumps=_dumps,
            )
        timeline = await asyncio.to_thread(
            state_mod.get_workload_timeline, key, tier
        )
        if not timeline:
            return web.json_response(
                {"error": f"unknown workload series {key!r}"}, status=404
            )
        return web.json_response(timeline, dumps=_dumps)

    async def _metrics(self, request):
        from aiohttp import web

        text = await asyncio.to_thread(metrics_mod.collect_prometheus_text)
        return web.Response(text=text, content_type="text/plain")

    async def _serve_state(self, request):
        """Serve drill-down: per-app deployment/replica status (the
        reference dashboard's Serve view role)."""
        from aiohttp import web

        def status():
            # serve.status() itself returns {} for the legitimate
            # nothing-deployed case; a raising controller must surface
            # as an error, not masquerade as an empty deployment list.
            try:
                from ray_tpu import serve

                return serve.status()
            except Exception as exc:
                return {"__error__": f"serve status unavailable: {exc}"}

        return web.json_response(
            await asyncio.to_thread(status), dumps=_dumps
        )

    async def _sequences(self, request):
        """Token-level serving view (ISSUE 19): sampled per-sequence
        timelines + the exact-sum token ledger from the session dir."""
        from aiohttp import web

        try:
            limit = int(request.query.get("limit", "200"))
        except ValueError:
            return web.Response(
                status=400, text="?limit= must be an integer"
            )
        return web.json_response(
            await asyncio.to_thread(
                state_mod.summarize_sequences, self.session_dir, limit
            ),
            dumps=_dumps,
        )

    async def _workers(self, request):
        from aiohttp import web

        return web.json_response(
            await asyncio.to_thread(state_mod.list_workers), dumps=_dumps
        )

    async def _grafana(self, request):
        """Importable Grafana dashboard generated from the LIVE metric
        registry (grafana_dashboard_factory role)."""
        from aiohttp import web

        from ray_tpu.dashboard import grafana

        text = await asyncio.to_thread(metrics_mod.collect_prometheus_text)
        return web.json_response(grafana.generate_dashboard(text))

    async def _tracing(self, request):
        from aiohttp import web

        from ray_tpu.util import tracing as tracing_mod

        if not self.session_dir:
            return web.json_response([])
        spans = await asyncio.to_thread(
            tracing_mod.read_spans, self.session_dir
        )
        return web.json_response(spans)

    async def _events(self, request):
        from aiohttp import web

        from ray_tpu._private.event_export import read_events

        if not self.session_dir:
            return web.json_response([])
        events = await asyncio.to_thread(
            read_events, self.session_dir, request.query.get("source")
        )
        return web.json_response(events[-int(request.query.get("limit", 500)):])

    @staticmethod
    def _call_node_agent(node_id: str | None, method: str, payload: dict) -> dict:
        """Reporter-agent routing: reach a worker through ITS node's agent.
        Without node_id, every agent is tried until one knows the worker
        (worker ids are cluster-unique)."""
        from ray_tpu._private.worker import get_global_context

        ctx = get_global_context()
        if node_id:
            nodes = state_mod.list_nodes()
            match = next((n for n in nodes if n["node_id"] == node_id), None)
            if match is None:
                return {"status": "error", "error": "unknown node"}
            agents = [tuple(match["agent_addr"])]
        else:
            agents = [tuple(n["agent_addr"]) for n in state_mod.list_nodes()
                      if n.get("alive", True)]
        last = {"status": "error", "error": "no live node agents"}
        for addr in agents:
            try:
                client = ctx.io.run(ctx._client_for(addr), timeout=15)
                last = ctx.io.run(
                    client.call(method, payload, timeout=15), timeout=20
                )
            except Exception as exc:
                # One unreachable/wedged agent must not abort the scan —
                # the worker may live on the next node.
                last = {"status": "error", "error": str(exc)}
                continue
            if not (last.get("status") == "error"
                    and last.get("error") == "unknown worker"):
                return last
        return last

    async def _stacks(self, request):
        """GET ?worker_id=[&node_id=] — live thread stacks via the worker's
        node agent (reference reporter_agent.py py-spy role)."""
        from aiohttp import web

        worker_id = request.query.get("worker_id", "")
        node_id = request.query.get("node_id") or None
        return web.json_response(
            await asyncio.to_thread(
                self._call_node_agent, node_id, "stack_trace_worker",
                {"worker_id": worker_id},
            )
        )

    async def _profile(self, request):
        """POST {node_id?, worker_id, action: start|stop} — trigger an XLA
        profiler capture on a worker via its node agent (SURVEY §5.1)."""
        from aiohttp import web

        payload = await request.json()
        return web.json_response(
            await asyncio.to_thread(
                self._call_node_agent,
                payload.get("node_id"),
                "profile_worker",
                {
                    "worker_id": payload.get("worker_id"),
                    "action": payload.get("action"),
                    "log_dir": payload.get("log_dir"),
                },
            )
        )

    async def _profiles(self, request):
        """GET — coordinated capture records (ISSUE 20): the controller's
        rolling ledger of manual and auto-triggered step captures, newest
        last, each carrying artifact paths + per-rank hot phases."""
        from aiohttp import web

        return web.json_response(
            {"profiles": await asyncio.to_thread(state_mod.list_profiles)},
            dumps=_dumps,
        )

    async def _flamegraph(self, request):
        """GET /api/profiles/{capture_id}/flamegraph — the capture's
        merged folded host stacks as a d3-flamegraph-style nested
        {name, value, children} tree. 404 JSON body when the capture or
        its folded artifact is unknown (same contract as _timeseries)."""
        from aiohttp import web

        from ray_tpu._private import profile_merge

        capture_id = request.match_info["capture_id"]
        # Resist path traversal: capture ids are flat tokens minted by the
        # controller, never paths.
        if not self.session_dir or "/" in capture_id or ".." in capture_id:
            return web.json_response(
                {"error": f"unknown capture_id {capture_id!r}"}, status=404
            )
        path = os.path.join(
            self.session_dir, "profiles", capture_id, "merged_folded.json"
        )

        def read():
            try:
                with open(path) as fh:
                    return json.load(fh)
            except (OSError, ValueError):
                return None

        folded = await asyncio.to_thread(read)
        if not isinstance(folded, dict):
            return web.json_response(
                {"error": f"unknown capture_id {capture_id!r}"}, status=404
            )
        return web.json_response(
            profile_merge.flamegraph_tree(folded), dumps=_dumps
        )


def start_dashboard(
    host: str = "127.0.0.1", port: int = 8265
) -> DashboardHead:
    from ray_tpu._private import worker as worker_mod

    ctx = worker_mod.get_global_context()
    session_dir = getattr(ctx, "session_dir", None) or os.environ.get(
        "RAYTPU_SESSION_DIR"
    )
    return DashboardHead(host, port, session_dir)

"""Dashboard — REST backend + minimal UI.

Role-equivalent of python/ray/dashboard/head.py + modules/{node,actor,job,
state,metrics} (SURVEY §2.3, §5.5): an aiohttp server aggregating
controller state into JSON endpoints, a Prometheus /metrics endpoint
(fed by ray_tpu.util.metrics), per-node log listing from the session dir,
and a single-page HTML overview. Runs in-process of the driver (thread)
or as a detached actor via start_dashboard().
"""

from __future__ import annotations

import asyncio
import glob
import json
import os
import threading
import time
from typing import Optional

import ray_tpu
from ray_tpu.util import metrics as metrics_mod
from ray_tpu.util import state as state_mod

_INDEX_HTML = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<style>
 body { font-family: system-ui, sans-serif; margin: 2rem; color: #222; }
 h1 { font-size: 1.3rem; } h2 { font-size: 1.05rem; margin-top: 1.5rem; }
 table { border-collapse: collapse; min-width: 40rem; }
 td, th { border: 1px solid #ccc; padding: 4px 10px; font-size: 0.85rem; }
 th { background: #f4f4f4; text-align: left; }
 code { background: #f4f4f4; padding: 1px 4px; }
</style></head>
<body>
<h1>ray_tpu dashboard</h1>
<div id="content">loading…</div>
<script>
async function refresh() {
  const [cluster, nodes, actors] = await Promise.all([
    fetch('/api/cluster').then(r => r.json()),
    fetch('/api/nodes').then(r => r.json()),
    fetch('/api/actors').then(r => r.json()),
  ]);
  let html = '<h2>Cluster resources</h2><table><tr><th>resource</th><th>available</th><th>total</th></tr>';
  for (const k of Object.keys(cluster.total)) {
    html += `<tr><td>${k}</td><td>${cluster.available[k] ?? 0}</td><td>${cluster.total[k]}</td></tr>`;
  }
  html += '</table><h2>Nodes</h2><table><tr><th>node</th><th>alive</th><th>resources</th></tr>';
  for (const n of nodes) {
    html += `<tr><td><code>${n.node_id}</code></td><td>${n.alive}</td><td>${JSON.stringify(n.resources_total)}</td></tr>`;
  }
  html += '</table><h2>Actors</h2><table><tr><th>actor</th><th>class</th><th>state</th><th>node</th></tr>';
  for (const a of actors) {
    html += `<tr><td><code>${a.actor_id}</code></td><td>${a.class_name ?? ''}</td><td>${a.state}</td><td><code>${a.node_id ?? ''}</code></td></tr>`;
  }
  html += '</table>';
  document.getElementById('content').innerHTML = html;
}
refresh(); setInterval(refresh, 3000);
</script>
</body></html>
"""


class DashboardHead:
    def __init__(self, host: str = "127.0.0.1", port: int = 8265,
                 session_dir: Optional[str] = None):
        self.host = host
        self.port = port
        self.session_dir = session_dir
        self._started = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=30):
            raise RuntimeError("dashboard failed to start")

    def _serve(self) -> None:
        asyncio.run(self._amain())

    async def _amain(self) -> None:
        from aiohttp import web

        app = web.Application()
        app.router.add_get("/", self._index)
        app.router.add_get("/api/cluster", self._cluster)
        app.router.add_get("/api/nodes", self._nodes)
        app.router.add_get("/api/actors", self._actors)
        app.router.add_get("/api/tasks", self._tasks)
        app.router.add_get("/api/placement_groups", self._pgs)
        app.router.add_get("/api/jobs", self._jobs)
        app.router.add_get("/api/logs", self._logs)
        app.router.add_get("/api/logs/{name}", self._log_file)
        app.router.add_get("/api/timeline", self._timeline)
        app.router.add_get("/metrics", self._metrics)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, self.host, self.port)
        await site.start()
        self._started.set()
        while True:
            await asyncio.sleep(3600)

    async def _index(self, request):
        from aiohttp import web

        return web.Response(text=_INDEX_HTML, content_type="text/html")

    async def _cluster(self, request):
        from aiohttp import web

        total = await asyncio.to_thread(ray_tpu.cluster_resources)
        available = await asyncio.to_thread(ray_tpu.available_resources)
        return web.json_response({"total": total, "available": available})

    async def _nodes(self, request):
        from aiohttp import web

        return web.json_response(
            await asyncio.to_thread(state_mod.list_nodes)
        )

    async def _actors(self, request):
        from aiohttp import web

        return web.json_response(
            await asyncio.to_thread(state_mod.list_actors)
        )

    async def _tasks(self, request):
        from aiohttp import web

        return web.json_response(await asyncio.to_thread(state_mod.list_tasks))

    async def _pgs(self, request):
        from aiohttp import web

        return web.json_response(
            await asyncio.to_thread(state_mod.list_placement_groups)
        )

    async def _jobs(self, request):
        from aiohttp import web

        return web.json_response(await asyncio.to_thread(state_mod.list_jobs))

    async def _logs(self, request):
        from aiohttp import web

        if not self.session_dir:
            return web.json_response([])
        files = sorted(
            os.path.basename(p)
            for p in glob.glob(os.path.join(self.session_dir, "logs", "*"))
        )
        return web.json_response(files)

    async def _log_file(self, request):
        from aiohttp import web

        name = os.path.basename(request.match_info["name"])
        path = os.path.join(self.session_dir or "", "logs", name)
        if not os.path.exists(path):
            return web.Response(status=404, text="no such log")
        lines = int(request.query.get("lines", "200"))
        with open(path, "rb") as f:
            data = f.read()[-200_000:]
        text = data.decode(errors="replace")
        return web.Response(text="\n".join(text.splitlines()[-lines:]))

    async def _timeline(self, request):
        from aiohttp import web

        return web.json_response(await asyncio.to_thread(ray_tpu.timeline))

    async def _metrics(self, request):
        from aiohttp import web

        text = await asyncio.to_thread(metrics_mod.collect_prometheus_text)
        return web.Response(text=text, content_type="text/plain")


def start_dashboard(
    host: str = "127.0.0.1", port: int = 8265
) -> DashboardHead:
    from ray_tpu._private import worker as worker_mod

    ctx = worker_mod.get_global_context()
    session_dir = getattr(ctx, "session_dir", None) or os.environ.get(
        "RAYTPU_SESSION_DIR"
    )
    return DashboardHead(host, port, session_dir)

"""Grafana dashboard generation.

Role-equivalent of python/ray/dashboard/modules/metrics/
grafana_dashboard_factory.py (SURVEY §2.3): emit importable Grafana
dashboard JSON over the framework's Prometheus export (`/metrics`,
families prefixed ``ray_tpu_``). One timeseries panel per metric family
— generated from the LIVE registry so user-defined Counters/Gauges/
Histograms get panels too, not just a hardcoded core set.
"""

from __future__ import annotations

import hashlib
import re

_FAMILY_RE = re.compile(r"^# TYPE (ray_tpu_[A-Za-z0-9_:]+) (\w+)$")


def metric_families(prometheus_text: str) -> list[tuple[str, str]]:
    """(family, type) pairs from a Prometheus exposition payload."""
    out = []
    for line in prometheus_text.splitlines():
        match = _FAMILY_RE.match(line.strip())
        if match:
            out.append((match.group(1), match.group(2)))
    return out


def _panel(panel_id: int, title: str, expr: str, y: int) -> dict:
    return {
        "id": panel_id,
        "type": "timeseries",
        "title": title,
        "datasource": {"type": "prometheus", "uid": "${DS_PROMETHEUS}"},
        "gridPos": {"h": 8, "w": 12, "x": 12 * (panel_id % 2), "y": y},
        "targets": [
            {"expr": expr, "legendFormat": "{{instance}}", "refId": "A"}
        ],
        "fieldConfig": {"defaults": {"unit": "short"}, "overrides": []},
    }


def generate_dashboard(prometheus_text: str, title: str = "ray_tpu") -> dict:
    """Importable Grafana (schema v36+) dashboard covering every exported
    metric family: counters as rate(), histograms as p50/p99 quantiles,
    gauges raw."""
    panels = []
    panel_id = 0
    y = 0
    for family, ftype in metric_families(prometheus_text):
        short = family[len("ray_tpu_"):]
        if ftype == "counter":
            expr = f"rate({family}[1m])"
            ptitle = f"{short} (rate/s)"
        elif ftype == "histogram":
            expr = (
                f"histogram_quantile(0.99, "
                f"rate({family}_bucket[5m]))"
            )
            ptitle = f"{short} (p99)"
        else:
            expr = family
            ptitle = short
        panels.append(_panel(panel_id, ptitle, expr, y))
        panel_id += 1
        if panel_id % 2 == 0:
            y += 8
    return {
        "__inputs": [
            {
                "name": "DS_PROMETHEUS",
                "label": "Prometheus",
                "type": "datasource",
                "pluginId": "prometheus",
            }
        ],
        "title": title,
        # deterministic uid (builtin hash() is per-process randomized):
        # re-imports UPDATE the dashboard instead of duplicating it
        "uid": "raytpu-" + hashlib.sha1(title.encode()).hexdigest()[:8],
        "schemaVersion": 36,
        "version": 1,
        "refresh": "10s",
        "time": {"from": "now-1h", "to": "now"},
        "panels": panels,
        "templating": {"list": []},
        "tags": ["ray_tpu", "generated"],
    }

"""Job submission — run driver scripts on the cluster.

Role-equivalent of python/ray/dashboard/modules/job/ :: JobSubmissionClient
+ job_manager.py (SURVEY §2.2): a detached JobManager actor spawns the
entrypoint as a subprocess with RAYTPU_ADDRESS set (so the script's
ray_tpu.init("auto") connects to this cluster), captures combined output,
and tracks status PENDING → RUNNING → SUCCEEDED | FAILED | STOPPED.
"""

from __future__ import annotations

import os
import subprocess
import threading
import time
import uuid
from typing import Optional

import ray_tpu

JOB_MANAGER_NAME = "JOB_MANAGER"


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"


class _JobManager:
    """Detached actor owning job subprocesses."""

    def __init__(self, controller_address: str):
        self._jobs: dict[str, dict] = {}
        self._procs: dict[str, subprocess.Popen] = {}
        self._controller_address = controller_address
        self._lock = threading.Lock()

    def submit(
        self,
        entrypoint: str,
        submission_id: str | None = None,
        runtime_env: dict | None = None,
        metadata: dict | None = None,
    ) -> str:
        job_id = submission_id or f"raytpu-job-{uuid.uuid4().hex[:8]}"
        env = dict(os.environ)
        env["RAYTPU_ADDRESS"] = self._controller_address
        for key, value in ((runtime_env or {}).get("env_vars") or {}).items():
            env[str(key)] = str(value)
        cwd = (runtime_env or {}).get("working_dir") or None
        with self._lock:
            self._jobs[job_id] = {
                "job_id": job_id,
                "entrypoint": entrypoint,
                "status": JobStatus.PENDING,
                "metadata": metadata or {},
                "logs": "",
                "start_time": time.time(),
                "end_time": None,
            }
        try:
            proc = subprocess.Popen(
                entrypoint,
                shell=True,
                env=env,
                cwd=cwd,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        except OSError as exc:
            with self._lock:
                self._jobs[job_id]["status"] = JobStatus.FAILED
                self._jobs[job_id]["logs"] = str(exc)
                self._jobs[job_id]["end_time"] = time.time()
            return job_id
        with self._lock:
            self._jobs[job_id]["status"] = JobStatus.RUNNING
            self._procs[job_id] = proc
        threading.Thread(
            target=self._watch, args=(job_id, proc), daemon=True
        ).start()
        return job_id

    def _watch(self, job_id: str, proc: subprocess.Popen) -> None:
        for line in proc.stdout:
            with self._lock:
                self._jobs[job_id]["logs"] += line
        code = proc.wait()
        with self._lock:
            job = self._jobs[job_id]
            if job["status"] != JobStatus.STOPPED:
                job["status"] = (
                    JobStatus.SUCCEEDED if code == 0 else JobStatus.FAILED
                )
            job["end_time"] = time.time()
            job["exit_code"] = code
            self._procs.pop(job_id, None)

    def status(self, job_id: str) -> Optional[str]:
        with self._lock:
            job = self._jobs.get(job_id)
            return job["status"] if job else None

    def info(self, job_id: str) -> Optional[dict]:
        with self._lock:
            job = self._jobs.get(job_id)
            return dict(job) if job else None

    def logs(self, job_id: str) -> str:
        with self._lock:
            job = self._jobs.get(job_id)
            return job["logs"] if job else ""

    def stop(self, job_id: str) -> bool:
        with self._lock:
            proc = self._procs.get(job_id)
            if proc is None:
                return False
            self._jobs[job_id]["status"] = JobStatus.STOPPED
        proc.terminate()
        return True

    def list(self) -> list[dict]:
        with self._lock:
            return [
                {k: v for k, v in job.items() if k != "logs"}
                for job in self._jobs.values()
            ]


class JobSubmissionClient:
    def __init__(self, address: str | None = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address)
        ctx = ray_tpu.get_runtime_context()
        from ray_tpu._private import worker as worker_mod

        controller = worker_mod.get_global_context().controller_addr
        controller_address = f"{controller[0]}:{controller[1]}"
        try:
            self._manager = ray_tpu.get_actor(JOB_MANAGER_NAME)
        except ValueError:
            try:
                self._manager = (
                    ray_tpu.remote(_JobManager)
                    .options(name=JOB_MANAGER_NAME, lifetime="detached")
                    .remote(controller_address)
                )
            except ValueError:
                self._manager = ray_tpu.get_actor(JOB_MANAGER_NAME)

    def submit_job(
        self,
        *,
        entrypoint: str,
        submission_id: str | None = None,
        runtime_env: dict | None = None,
        metadata: dict | None = None,
    ) -> str:
        return ray_tpu.get(
            self._manager.submit.remote(
                entrypoint, submission_id, runtime_env, metadata
            ),
            timeout=60,
        )

    def get_job_status(self, job_id: str) -> str:
        status = ray_tpu.get(self._manager.status.remote(job_id), timeout=30)
        if status is None:
            raise ValueError(f"unknown job {job_id!r}")
        return status

    def get_job_info(self, job_id: str) -> dict:
        info = ray_tpu.get(self._manager.info.remote(job_id), timeout=30)
        if info is None:
            raise ValueError(f"unknown job {job_id!r}")
        return info

    def get_job_logs(self, job_id: str) -> str:
        return ray_tpu.get(self._manager.logs.remote(job_id), timeout=30)

    def stop_job(self, job_id: str) -> bool:
        return ray_tpu.get(self._manager.stop.remote(job_id), timeout=30)

    def list_jobs(self) -> list[dict]:
        return ray_tpu.get(self._manager.list.remote(), timeout=30)

    def wait_until_finished(
        self, job_id: str, timeout: float = 300.0
    ) -> str:
        deadline = time.time() + timeout
        terminal = (JobStatus.SUCCEEDED, JobStatus.FAILED, JobStatus.STOPPED)
        while time.time() < deadline:
            status = self.get_job_status(job_id)
            if status in terminal:
                return status
            time.sleep(0.5)
        raise TimeoutError(f"job {job_id} still running after {timeout}s")

"""AIR-layer integration surface (experiment-tracker sinks).

Role-equivalent of python/ray/air/integrations/ (SURVEY §2.5): tracker
callbacks that forward per-trial configs + metric streams to an
experiment-tracking backend. See ray_tpu.air.integrations.
"""

from ray_tpu.air.integrations import (  # noqa: F401
    FileTrackerCallback, TrackerCallback,
)

__all__ = ["TrackerCallback", "FileTrackerCallback"]

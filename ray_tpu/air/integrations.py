"""Experiment-tracker sinks for Tune.

Role-equivalent of python/ray/air/integrations/{wandb,mlflow}.py ::
WandbLoggerCallback / MLflowLoggerCallback (SURVEY §2.5): a tracker
observes every trial as a *run* — params once at add time, a metric
stream per report, a terminal status — decoupled from Tune's own result
logging. The W&B/MLflow network services don't exist in this image, so
the shipped implementation is file-backed with their run/params/metrics
data model; pointing a real backend at the same interface is a subclass
away (override the four _backend hooks).

Register like any logger callback:

    tune.Tuner(..., run_config=RunConfig(
        callbacks=[FileTrackerCallback(root_dir)],
    ))
"""

from __future__ import annotations

import json
import os
import time
from typing import Any

from ray_tpu._private import atomic_io
from ray_tpu.tune.logger import LoggerCallback


class TrackerCallback(LoggerCallback):
    """Tracker-shaped adapter over the trial lifecycle: subclasses
    implement run start/metrics/end against their backend; param and
    metric filtering/flattening is shared here."""

    def __init__(self, *, flatten_sep: str = "/"):
        self._sep = flatten_sep
        self._started: set[str] = set()

    # -- backend hooks (the integration surface) ------------------------
    def _backend_start_run(self, run_id: str, name: str, params: dict) -> None:
        raise NotImplementedError

    def _backend_log_metrics(self, run_id: str, step: int, metrics: dict) -> None:
        raise NotImplementedError

    def _backend_end_run(self, run_id: str, status: str) -> None:
        raise NotImplementedError

    # -- trial lifecycle -> run lifecycle -------------------------------
    def on_trial_add(self, trial) -> None:
        self._ensure_started(trial)

    def _ensure_started(self, trial) -> None:
        if trial.trial_id in self._started:
            return
        self._started.add(trial.trial_id)
        self._backend_start_run(
            trial.trial_id,
            getattr(trial, "experiment_tag", None) or trial.trial_id,
            self._flatten(dict(trial.config or {})),
        )

    def on_trial_result(self, trial, result: dict) -> None:
        self._ensure_started(trial)
        step = int(result.get("training_iteration", 0))
        metrics = {
            k: v
            for k, v in self._flatten(result).items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }
        if metrics:
            self._backend_log_metrics(trial.trial_id, step, metrics)

    def on_trial_complete(self, trial, result: dict) -> None:
        if trial.trial_id in self._started:
            self._started.discard(trial.trial_id)
            self._backend_end_run(trial.trial_id, "FINISHED")

    def on_trial_error(self, trial) -> None:
        if trial.trial_id in self._started:
            self._started.discard(trial.trial_id)
            self._backend_end_run(trial.trial_id, "FAILED")

    # -- shared shaping -------------------------------------------------
    def _flatten(self, mapping: dict, prefix: str = "") -> dict:
        out: dict[str, Any] = {}
        for key, value in mapping.items():
            name = f"{prefix}{self._sep}{key}" if prefix else str(key)
            if isinstance(value, dict):
                out.update(self._flatten(value, name))
            else:
                out[name] = value
        return out


class FileTrackerCallback(TrackerCallback):
    """File-backed tracker with the W&B/MLflow run data model:

        <root>/<run_id>/run.json       {run_id, name, status, timestamps}
        <root>/<run_id>/params.json    flattened trial config
        <root>/<run_id>/metrics.jsonl  one {step, ts, **metrics} per report
    """

    def __init__(self, root_dir: str, **kwargs):
        super().__init__(**kwargs)
        self.root_dir = root_dir
        os.makedirs(root_dir, exist_ok=True)

    def _run_dir(self, run_id: str) -> str:
        d = os.path.join(self.root_dir, run_id)
        os.makedirs(d, exist_ok=True)
        return d

    def _backend_start_run(self, run_id, name, params) -> None:
        d = self._run_dir(run_id)
        atomic_io.atomic_write_json(
            os.path.join(d, "run.json"),
            {
                "run_id": run_id,
                "name": name,
                "status": "RUNNING",
                "start_time": time.time(),
            },
        )
        atomic_io.atomic_write_json(
            os.path.join(d, "params.json"),
            {k: v if _jsonable(v) else repr(v) for k, v in params.items()},
        )

    def _backend_log_metrics(self, run_id, step, metrics) -> None:
        with open(
            os.path.join(self._run_dir(run_id), "metrics.jsonl"), "a"
        ) as f:
            f.write(json.dumps({"step": step, "ts": time.time(), **metrics}))
            f.write("\n")

    def _backend_end_run(self, run_id, status) -> None:
        path = os.path.join(self._run_dir(run_id), "run.json")
        try:
            with open(path) as f:
                run = json.load(f)
        except (OSError, ValueError):
            run = {"run_id": run_id}
        run["status"] = status
        run["end_time"] = time.time()
        atomic_io.atomic_write_json(path, run)


def _jsonable(value) -> bool:
    try:
        json.dumps(value)
        return True
    except (TypeError, ValueError):
        return False

"""Declarative Serve config schema + YAML deploy.

Role-equivalent of python/ray/serve/schema.py :: ServeDeploySchema /
ServeApplicationSchema / DeploymentSchema (SURVEY §2.6 schema row): a YAML
file describes applications (import path + per-deployment overrides); the
`serve deploy` CLI verb and serve.run_from_config() apply it.

Example:

    http_options:
      host: 127.0.0.1
      port: 8200
    applications:
      - name: summarizer
        route_prefix: /api
        import_path: my_pkg.app:graph        # module:attr -> Application
        deployments:
          - name: Summarizer
            num_replicas: 2
            max_ongoing_requests: 16
            user_config: {temperature: 0.2}
            autoscaling_config: {min_replicas: 1, max_replicas: 4}
"""

from __future__ import annotations

import dataclasses
import importlib
from typing import Any, Optional


@dataclasses.dataclass
class DeploymentSchema:
    name: str
    num_replicas: Optional[int] = None
    max_ongoing_requests: Optional[int] = None
    user_config: Any = None
    autoscaling_config: Optional[dict] = None
    ray_actor_options: Optional[dict] = None
    # Reliability knobs (ISSUE 13): request deadline seed, health-probe
    # timeout, admission queue allowance, retry/hedge policy, drain budget.
    request_timeout_s: Optional[float] = None
    health_probe_timeout_s: Optional[float] = None
    max_queued_requests: Optional[int] = None
    retry_policy: Optional[dict] = None
    graceful_shutdown_timeout_s: Optional[float] = None

    def overrides(self) -> dict:
        out: dict = {}
        for field in (
            "num_replicas", "max_ongoing_requests", "user_config",
            "autoscaling_config", "ray_actor_options",
            "request_timeout_s", "health_probe_timeout_s",
            "max_queued_requests", "retry_policy",
            "graceful_shutdown_timeout_s",
        ):
            value = getattr(self, field)
            if value is not None:
                out[field] = value
        return out


@dataclasses.dataclass
class ServeApplicationSchema:
    name: str
    import_path: str
    route_prefix: str = "/"
    runtime_env: Optional[dict] = None
    deployments: list = dataclasses.field(default_factory=list)

    @classmethod
    def from_dict(cls, raw: dict) -> "ServeApplicationSchema":
        deployments = [
            DeploymentSchema(**d) for d in raw.get("deployments", [])
        ]
        return cls(
            name=raw["name"],
            import_path=raw["import_path"],
            route_prefix=raw.get("route_prefix", "/"),
            runtime_env=raw.get("runtime_env"),
            deployments=deployments,
        )


@dataclasses.dataclass
class HTTPOptionsSchema:
    host: str = "127.0.0.1"
    port: int = 8000
    # Multi-proxy ingress (ISSUE 13): N proxies on consecutive ports,
    # health-checked and restarted by the controller.
    num_proxies: int = 1


@dataclasses.dataclass
class ServeDeploySchema:
    applications: list
    http_options: HTTPOptionsSchema = dataclasses.field(
        default_factory=HTTPOptionsSchema
    )

    @classmethod
    def from_dict(cls, raw: dict) -> "ServeDeploySchema":
        apps = [
            ServeApplicationSchema.from_dict(a)
            for a in raw.get("applications", [])
        ]
        if not apps:
            raise ValueError("config has no applications")
        http = HTTPOptionsSchema(**(raw.get("http_options") or {}))
        return cls(applications=apps, http_options=http)

    @classmethod
    def from_yaml(cls, path: str) -> "ServeDeploySchema":
        import yaml

        with open(path) as f:
            raw = yaml.safe_load(f)
        if not isinstance(raw, dict):
            raise ValueError(f"{path}: expected a mapping at top level")
        return cls.from_dict(raw)


def _import_target(import_path: str):
    """'pkg.module:attr' -> the bound Application object."""
    module_name, _, attr = import_path.partition(":")
    if not attr:
        raise ValueError(
            f"import_path {import_path!r} must be 'module:attribute'"
        )
    module = importlib.import_module(module_name)
    target = module
    for part in attr.split("."):
        target = getattr(target, part)
    return target


def build_application(app_schema: ServeApplicationSchema):
    """Import the bound app and apply per-deployment config overrides."""
    from ray_tpu.serve.api import Application

    app = _import_target(app_schema.import_path)
    if callable(app) and not isinstance(app, Application):
        app = app()  # builder function style
    if not isinstance(app, Application):
        raise TypeError(
            f"{app_schema.import_path} resolved to {type(app).__name__}, "
            "expected a bound Application (Deployment.bind(...))"
        )
    overrides = {d.name: d.overrides() for d in app_schema.deployments}
    if overrides:
        app = _apply_overrides(app, overrides)
    return app


def _apply_overrides(app, overrides: dict):
    """Rebuild the application graph with per-deployment .options()."""
    from ray_tpu.serve.api import Application

    def rebuild(node):
        if isinstance(node, Application):
            deployment = node.deployment
            if deployment.name in overrides:
                deployment = deployment.options(**overrides[deployment.name])
            args = tuple(rebuild(a) for a in node.args)
            kwargs = {k: rebuild(v) for k, v in node.kwargs.items()}
            return Application(deployment, args, kwargs)
        if isinstance(node, (list, tuple)):
            rebuilt = [rebuild(x) for x in node]
            return type(node)(rebuilt)
        if isinstance(node, dict):
            return {k: rebuild(v) for k, v in node.items()}
        return node

    return rebuild(app)


def deploy_from_config(schema: ServeDeploySchema) -> dict:
    """Apply a deploy schema: start HTTP, run every application. Returns
    {app_name: ingress deployment name}."""
    from ray_tpu.serve import api

    api.start(
        http_host=schema.http_options.host,
        http_port=schema.http_options.port,
        num_proxies=schema.http_options.num_proxies,
    )
    deployed = {}
    for app_schema in schema.applications:
        app = build_application(app_schema)
        handle = api.run(
            app, name=app_schema.name, route_prefix=app_schema.route_prefix
        )
        deployed[app_schema.name] = handle.deployment_name
    return deployed

"""ray_tpu.serve — model serving (Ray Serve-equivalent, TPU-first).

Controller/replica/proxy/router architecture with power-of-two routing,
target-ongoing-requests autoscaling, bucketed dynamic batching for XLA
static shapes, model multiplexing, and composition via DeploymentHandles.
SURVEY §2.6.
"""

from ray_tpu.serve.api import (
    Application,
    Deployment,
    delete,
    deployment,
    get_app_handle,
    get_deployment_handle,
    run,
    run_from_config,
    shutdown,
    start,
    status,
)
from ray_tpu.serve.batching import batch
from ray_tpu.serve.handle import (
    DeploymentHandle,
    DeploymentResponse,
    ResponseStream,
)
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed
from ray_tpu.serve._private.common import (
    AutoscalingConfig,
    Deadline,
    DeploymentConfig,
    RetryPolicy,
)

__all__ = [
    "deployment",
    "Deployment",
    "Application",
    "run",
    "start",
    "status",
    "delete",
    "shutdown",
    "get_app_handle",
    "get_deployment_handle",
    "DeploymentHandle",
    "DeploymentResponse",
    "ResponseStream",
    "run_from_config",
    "batch",
    "multiplexed",
    "get_multiplexed_model_id",
    "AutoscalingConfig",
    "DeploymentConfig",
    "RetryPolicy",
    "Deadline",
]
